"""The process runtime: boot a cluster-state-driven, Ready, serving
gatekeeper-tpu instance.

Counterpart of main.go (:103-308) + pkg/operations: wires the watch
manager, the four ingestion controllers, the readiness tracker (with a
real /readyz), the status plane, metrics, and the serving workloads
(admission webhook + audit manager) — gated by `operations` roles the
way `--operation` splits the reference deployment into webhook and
audit pods (operations.go:15-19,77; deploy/gatekeeper.yaml).

Nothing outside this module touches the Client directly: state flows
cluster -> watch manager -> controllers -> Client, and the serving
paths consume the Client — the reference's exact architecture
(SURVEY §3 call stacks).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set

from .controllers import (
    CONFIG_GVK,
    CONFIG_NAME,
    CONFIG_NAMESPACE,
    ConfigController,
    ConstraintController,
    ControllerSwitch,
    MUTATOR_GVKS,
    MutatorController,
    PROVIDER_GVK,
    ProviderController,
    SyncController,
    TemplateController,
    TEMPLATE_GVK,
    constraint_gvk,
)
from .events import EventSource, FakeCluster, GVK
from .process import Excluder
from .readiness import ReadinessTracker
from .status import (
    CONSTRAINT_STATUS_GVK,
    StatusAggregator,
    StatusWriter,
    TEMPLATE_STATUS_GVK,
)
from .watch import WatchManager

OPERATION_WEBHOOK = "webhook"
OPERATION_AUDIT = "audit"
OPERATION_STATUS = "status"
ALL_OPERATIONS = (OPERATION_WEBHOOK, OPERATION_AUDIT, OPERATION_STATUS)

NAMESPACE_GVK = GVK("", "v1", "Namespace")


class Runner:
    def __init__(
        self,
        cluster: EventSource,
        client,
        target: str,
        operations: Sequence[str] = ALL_OPERATIONS,
        pod_name: str = "gatekeeper-pod",
        metrics=None,
        audit_interval: float = 60.0,
        # --audit-chunk-size (manager.go:50): page size for the
        # discovery-list sweep's batched reviews
        audit_chunk_size: int = 512,
        webhook_port: int = 0,
        readyz_port: Optional[int] = 0,  # None disables the endpoint
        exempt_namespaces: Sequence[str] = (),
        webhook_tls: bool = False,
        emit_admission_events: bool = False,
        emit_audit_events: bool = False,
        audit_from_cache: bool = True,
        enable_profiler: bool = False,
        log_denies: bool = False,
        logger=None,
        # name of a ValidatingWebhookConfiguration to keep injected with
        # the rotating CA bundle (certs.go:183,468-515); needs
        # webhook_tls
        vwh_name: Optional[str] = None,
        # TLS artifact dir (the reference's mounted cert Secret); None =
        # per-process temp dir
        cert_dir: Optional[str] = None,
        # serving bind address: loopback for tests, "0.0.0.0" in-cluster
        bind_addr: str = "127.0.0.1",
        # obs.Tracer threaded through webhook + audit; None builds one
        # (tracing is always on — the ring is bounded)
        tracer=None,
        # overload/degradation envelope (docs/robustness.md): what a
        # shed/expired/unevaluable request gets ("open" = allow, the
        # reference's failurePolicy: Ignore posture; "closed" = 503)
        # and the admission queue bound (None = unbounded; default
        # mirrors webhook.server.DEFAULT_MAX_QUEUE)
        fail_policy: str = "open",
        max_queue=2048,
        # device fault domains (docs/robustness.md §Fault domains):
        # split the constraint corpus into this many partitions, each
        # guarded by its own per-device breaker — a sick chip sheds its
        # constraint subset instead of tripping the whole plane. 0 =
        # monolithic dispatch (the pre-partition behavior).
        partitions: int = 0,
        # fleet plane (docs/fleet.md): CR-backed gossip making the
        # external-data cache and breaker trips fleet properties.
        # True builds a FleetPlane keyed by pod_name; pass an existing
        # FleetPlane to share one across in-process replicas in tests;
        # False disables (pure per-process state)
        fleet=True,
        # name of the Secret backing the SHARED cert store (the
        # reference's mounted cert Secret, certs.go:119-181): replicas
        # load-or-create one CA and pick up rotation via watch without
        # restart. None = pod-local CertRotator in cert_dir (single
        # replica / hermetic tests). Needs webhook_tls.
        cert_secret: Optional[str] = None,
        # namespace holding the cert Secret and FleetState CRs
        fleet_namespace: str = "gatekeeper-system",
        # graceful drain (docs/robustness.md): seconds /readyz reports
        # not-ready while the webhook listener still accepts, so the
        # LB/kubelet routes away before connections start failing
        drain_grace_s: float = 0.0,
        # live SLO & saturation plane (docs/observability.md §SLO &
        # saturation): SloTarget the streaming engine judges every
        # admission against. None = defaults (99% within the handler's
        # own deadline slack, 60s/900s burn windows).
        slo_target=None,
        # admission scheduling policy (docs/operations.md §Admission
        # scheduling): "deadline" turns on EDF batch formation,
        # per-tenant fair-share quotas, and predictive shedding;
        # "fifo" is the bit-compatible legacy queue and the rollback
        # path (--sched-policy fifo)
        sched_policy: str = "fifo",
        # wire-speed ingest plane (docs/ingest.md): "on" mounts the
        # framed-stream listener with zero-copy decode, "json" keeps
        # the framed transport but decodes with plain json.loads (the
        # decode-bisect knob), "off" (default, --ingest off) is the
        # rollback path — legacy HTTP only
        ingest: str = "off",
        ingest_port: int = 0,
        # verdict-integrity plane (docs/robustness.md §Verdict
        # integrity): canary rows in every fused dispatch's padding
        # slots, a CRC-sampled shadow oracle, and corruption
        # quarantine. True (default) builds an IntegrityPlane; False
        # disables the plane entirely (the rollback path); an
        # IntegrityPlane instance is adopted as-is (tests/bench tune
        # sampling/thresholds)
        integrity=True,
    ):
        from ..logs import null_logger
        from ..obs import (
            CostAttributor,
            DecisionLog,
            FlightRecorder,
            SloEngine,
            Tracer,
        )

        self.tracer = tracer if tracer is not None else Tracer()

        self.cluster = cluster
        self.client = client
        self.target = target
        self.pod_name = pod_name
        self.operations = set(operations)
        self.log_denies = log_denies
        self.log = logger if logger is not None else null_logger()
        if metrics is None:
            from ..metrics import MetricsRegistry

            metrics = MetricsRegistry()
        self.metrics = metrics
        # late-wire the driver's own metrics (template verdict gauges,
        # fallback-reason counters) into the shared registry
        driver = getattr(client, "_driver", None)
        set_m = getattr(driver, "set_metrics", None)
        if set_m is not None:
            set_m(metrics)
        # cost-attribution + flight-recorder plane
        # (docs/observability.md): per-constraint device-time
        # accounting at the driver's dispatch seam, served at
        # /debug/costs; trip-triggered postmortems (breaker OPEN,
        # quarantine, shed burst) at /debug/flightrecords, on disk
        # when GATEKEEPER_TPU_FLIGHT_DIR is set
        self.attributor = CostAttributor(
            metrics=metrics, replica=pod_name
        )
        set_a = getattr(driver, "set_attributor", None)
        if set_a is not None:
            set_a(self.attributor)
        # per-admission decision log (docs/observability.md §Decision
        # log): every plane's "why" records, served at /debug/decisions
        # and cross-linked into flight records by id + trace id
        self.decisions = DecisionLog(metrics=metrics, replica=pod_name)
        self.recorder = FlightRecorder(
            tracer=self.tracer,
            attributor=self.attributor,
            metrics=metrics,
            decisions=self.decisions,
            replica=pod_name,
        )
        # streaming SLO engine, fed through the decision-log seam so
        # every plane's verdicts/latencies/sheds stream in without any
        # handler changes; breaches fire slo_breach flight records
        self.slo = SloEngine(
            target=slo_target,
            metrics=metrics,
            recorder=self.recorder,
            replica=pod_name,
        )
        self.decisions.slo = self.slo
        # verdict-integrity plane (docs/robustness.md §Verdict
        # integrity): golden canary sets ride the ProgramStore as
        # sidecars when the driver has one; the driver packs/strips
        # canaries and gates warm-swaps from here on
        self.integrity = None
        if integrity:
            from ..integrity import IntegrityPlane

            self.integrity = (
                integrity
                if isinstance(integrity, IntegrityPlane)
                else IntegrityPlane(
                    metrics=metrics,
                    decisions=self.decisions,
                    recorder=self.recorder,
                    store=getattr(driver, "program_store", None),
                )
            )
            self.integrity.metrics = metrics
            self.integrity.decisions = self.decisions
            self.integrity.recorder = self.recorder
            set_i = getattr(driver, "set_integrity", None)
            if set_i is not None:
                set_i(self.integrity)
            self.integrity.attach_client(client)
        self.excluder = Excluder()
        self.tracker = ReadinessTracker()
        self.switch = ControllerSwitch()
        self.watch_mgr = WatchManager(cluster, metrics=metrics)
        self.status_writer = (
            StatusWriter(cluster, pod_name)
            if OPERATION_STATUS in self.operations
            else None
        )
        self.status_agg = StatusAggregator()
        self.audit_interval = audit_interval
        self.audit_chunk_size = audit_chunk_size
        self.audit_from_cache = audit_from_cache
        # --enable-pprof equivalent (main.go:89-90,111-117): when on,
        # the readyz server also exposes /debug/profile?seconds=N which
        # captures a JAX profiler trace (XPlane) — the device-side
        # analog of the reference's net/http/pprof endpoint
        self.enable_profiler = enable_profiler
        self._profile_lock = threading.Lock()
        self.webhook_port = webhook_port
        self.readyz_port = readyz_port
        self.fail_policy = fail_policy
        self.max_queue = max_queue
        self.partitions = int(partitions or 0)
        self.sched_policy = sched_policy
        self.ingest_mode = ingest if ingest in ("on", "json") else "off"
        self.ingest_port = ingest_port
        self.drain_grace_s = drain_grace_s
        self.exempt_namespaces = list(exempt_namespaces)
        self.webhook_tls = webhook_tls
        self.vwh_name = vwh_name
        self.cert_dir = cert_dir
        self.cert_secret = cert_secret
        self.fleet_namespace = fleet_namespace
        self.bind_addr = bind_addr
        self.ca_injector = None
        self.webhook = None
        self.audit = None
        # fleet state plane (docs/fleet.md): built here so the
        # external-data system below can attach before any provider
        # ingests; started (watch + first publish) in start()
        from ..fleet import FleetPlane

        if fleet is True:
            self.fleet = FleetPlane(
                cluster,
                replica_id=pod_name,
                namespace=fleet_namespace,
                metrics=metrics,
                logger=self.log.with_values(process="fleet"),
            )
        else:
            self.fleet = fleet or None
        self._readyz_httpd: Optional[ThreadingHTTPServer] = None
        from ..webhook.policy import TraceConfig

        self.trace_config = TraceConfig()
        self.emit_admission_events = emit_admission_events
        self.emit_audit_events = emit_audit_events
        # emitted violation events: a BOUNDED in-memory ring for
        # introspection (audit re-emits persisting violations every
        # sweep; an unbounded list would leak for the process lifetime)
        # PLUS real v1 Event objects written through the EventSource —
        # against a live apiserver these are actual cluster Events
        # (policy.go:253-273 AnnotatedEventf / audit emitEvent)
        from collections import deque

        self.events: Any = deque(maxlen=4096)
        self._event_queue: Any = deque(maxlen=4096)
        self._event_wake = threading.Event()
        self._event_stop = threading.Event()
        self._warm_stop = threading.Event()
        self._warm_thread: Optional[threading.Thread] = None
        self._event_thread = threading.Thread(
            target=self._drain_events, daemon=True
        )
        self._event_thread.start()

        # controllers (wired, not yet watching)
        self.constraint_controller = ConstraintController(
            client,
            tracker=self.tracker,
            switch=self.switch,
            metrics=metrics,
            status=self.status_writer,
        )
        self._constraint_registrar = self.watch_mgr.new_registrar(
            "constraint-controller", self.constraint_controller.sink
        )
        self.template_controller = TemplateController(
            client,
            self.watch_mgr,
            self._constraint_registrar,
            tracker=self.tracker,
            switch=self.switch,
            metrics=metrics,
            status=self.status_writer,
            constraint_controller=self.constraint_controller,
            logger=self.log,
        )
        self._template_registrar = self.watch_mgr.new_registrar(
            "template-controller", self.template_controller.sink
        )
        self.sync_controller = SyncController(
            client,
            tracker=self.tracker,
            switch=self.switch,
            metrics=metrics,
            excluder=self.excluder,
        )
        self._sync_registrar = self.watch_mgr.new_registrar(
            "sync-controller", self.sync_controller.sink
        )
        # mutation plane: the system is always built (cheap when no
        # mutators exist); the webhook serves /v1/mutate through it and
        # the controller keeps it synced with the three mutator GVKs
        from ..mutation import MutationSystem

        self.mutation_system = MutationSystem(
            metrics=metrics, logger=self.log
        )
        self.mutator_controller = MutatorController(
            self.mutation_system,
            switch=self.switch,
            metrics=metrics,
            status=self.status_writer,
            logger=self.log,
        )
        self._mutator_registrar = self.watch_mgr.new_registrar(
            "mutator-controller", self.mutator_controller.sink
        )
        # external-data plane: the system is always built (cheap with no
        # providers); the Provider controller keeps its registry synced,
        # the client/driver prefetch through it, and the interpreter's
        # external_data builtin resolves via the process binding
        from ..externaldata import ExternalDataSystem

        self.external_data = ExternalDataSystem(
            metrics=metrics, tracer=self.tracer, logger=self.log
        )
        if self.fleet is not None:
            # cache entries publish to peers; per-provider breakers
            # gossip as providers ingest (docs/fleet.md)
            self.fleet.attach_cache(self.external_data)
        set_ed = getattr(client, "set_external_data", None)
        if set_ed is not None:
            set_ed(self.external_data)
        # corpus analysis plane (docs/analysis.md §Corpus analysis):
        # whole-corpus diagnostics recomputed off the request path on
        # churn, snapshot on /readyz, prunable keys fed to the planner
        from ..analysis.corpus import CorpusPlane

        self.corpus = CorpusPlane(
            client,
            mutation_system=self.mutation_system,
            external_data=self.external_data,
            metrics=metrics,
        )
        self.provider_controller = ProviderController(
            self.external_data,
            switch=self.switch,
            metrics=metrics,
            status=self.status_writer,
            logger=self.log,
        )
        self._provider_registrar = self.watch_mgr.new_registrar(
            "provider-controller", self.provider_controller.sink
        )
        self.config_controller = ConfigController(
            client,
            self._sync_registrar,
            self.sync_controller,
            self.excluder,
            tracker=self.tracker,
            switch=self.switch,
            metrics=metrics,
            trace_config=self.trace_config,
            mutation_system=self.mutation_system,
            mutation_registrar=self._mutator_registrar,
            external_data_system=self.external_data,
            provider_registrar=self._provider_registrar,
        )
        self._config_registrar = self.watch_mgr.new_registrar(
            "config-controller", self.config_controller.sink
        )
        self._status_registrar = self.watch_mgr.new_registrar(
            "status-controller", self.status_agg.sink
        )

    # -- boot ----------------------------------------------------------------

    def _populate_expectations(self) -> None:
        """Boot-time readiness barrier: list what exists NOW and expect
        it to be ingested before reporting Ready
        (ready_tracker.go:336-520)."""
        templates = self.cluster.list(TEMPLATE_GVK)
        for t in templates:
            name = (t.get("metadata") or {}).get("name", "")
            self.tracker.templates.expect(name)
        self.tracker.templates.expectations_done()

        for t in templates:
            kind = (
                ((((t.get("spec") or {}).get("crd") or {}).get("spec") or {})
                 .get("names") or {})
            ).get("kind") or ""
            if not kind:
                continue
            tr = self.tracker.for_constraint_kind(kind)
            for c in self.cluster.list(constraint_gvk(kind)):
                tr.expect((c.get("metadata") or {}).get("name", ""))
            tr.expectations_done()

        configs = [
            c
            for c in self.cluster.list(CONFIG_GVK)
            if ((c.get("metadata") or {}).get("namespace"),
                (c.get("metadata") or {}).get("name"))
            == (CONFIG_NAMESPACE, CONFIG_NAME)
        ]
        if configs:
            self.tracker.config.expect((CONFIG_NAMESPACE, CONFIG_NAME))
            spec = configs[0].get("spec") or {}
            for entry in ((spec.get("sync") or {}).get("syncOnly") or []):
                gvk = GVK(
                    entry.get("group", "") or "",
                    entry.get("version", ""),
                    entry.get("kind", ""),
                )
                tr = self.tracker.for_data(str(gvk))
                for obj in self.cluster.list(gvk):
                    meta = obj.get("metadata") or {}
                    tr.expect(
                        (meta.get("namespace") or "", meta.get("name") or "")
                    )
                tr.expectations_done()
        self.tracker.config.expectations_done()

    def start(self) -> None:
        # stored-version migration first (pkg/upgrade runs before the
        # controllers see state; deprecated-version objects must be
        # visible at the preferred version the watches use)
        from .upgrade import UpgradeManager

        self.upgrade_mgr = UpgradeManager(self.cluster)
        try:
            self.upgrade_mgr.upgrade()
        except Exception as e:
            # upgrade failures must not block serving (the reference
            # logs and continues, upgrade/manager.go) — but they must
            # not be invisible either
            self.log.error(
                "stored-version upgrade failed; deprecated-version "
                "objects may not be ingested",
                err=e,
            )

        self._populate_expectations()

        if self.fleet is not None:
            # readiness: the state plane must have listed peers and
            # offered its first publish before the replica reports
            # Ready (start() below is synchronous; publish failures on
            # a cluster without the CRD degrade, never block)
            comp = self.tracker.for_component("fleet")
            comp.expect("state-plane")
            comp.expectations_done()
            self.fleet.start()
            comp.observe("state-plane")

        # watch registration order mirrors setupControllers: templates
        # first (they create constraint kinds), then config (it swaps the
        # sync watches), status kinds for the aggregator
        self._template_registrar.add_watch(TEMPLATE_GVK)
        self._config_registrar.add_watch(CONFIG_GVK)
        for gvk in MUTATOR_GVKS:
            self._mutator_registrar.add_watch(gvk)
        self._provider_registrar.add_watch(PROVIDER_GVK)
        if OPERATION_STATUS in self.operations:
            self._status_registrar.add_watch(TEMPLATE_STATUS_GVK)
            self._status_registrar.add_watch(CONSTRAINT_STATUS_GVK)

        if OPERATION_WEBHOOK in self.operations:
            from ..webhook.server import WebhookServer

            # the agent-action serving plane mounts automatically
            # when the client was built with the agent target
            # registered (docs/targets.md)
            from ..agentaction import TARGET_NAME as _AGENT_TARGET

            rotator = None
            if self.webhook_tls and self.cert_secret:
                # the Secret-backed shared cert store: one CA per
                # fleet, rotation picked up by peers without restart
                # (docs/fleet.md; certs.go:119-181 behaviorally)
                import tempfile

                from ..fleet import FleetCertRotator, SecretCertStore

                store = SecretCertStore(
                    self.cluster,
                    name=self.cert_secret,
                    namespace=self.fleet_namespace,
                    replica_id=self.pod_name,
                    metrics=self.metrics,
                    logger=self.log.with_values(process="fleet"),
                )
                rotator = FleetCertRotator(
                    self.cert_dir
                    or tempfile.mkdtemp(prefix="gk-certs-"),
                    store,
                    metrics=self.metrics,
                    logger=self.log.with_values(process="fleet"),
                )
                rotator.ensure()  # load-or-create BEFORE serving
                rotator.start()  # watch for peer rotations

            self.webhook = WebhookServer(
                self.client,
                self.target,
                agent_review=(
                    _AGENT_TARGET in getattr(self.client, "targets", {})
                ),
                port=self.webhook_port,
                excluder=self.excluder,
                namespace_getter=self._get_namespace,
                exempt_namespaces=self.exempt_namespaces,
                metrics=self.metrics,
                tls=self.webhook_tls,
                trace_config=self.trace_config,
                event_sink=self._emit_event,
                emit_admission_events=self.emit_admission_events,
                log_denies=self.log_denies,
                logger=self.log.with_values(process="webhook"),
                tracer=self.tracer,
                mutation_system=self.mutation_system,
                cert_dir=self.cert_dir,
                rotator=rotator,
                bind_addr=self.bind_addr,
                fail_policy=self.fail_policy,
                max_queue=self.max_queue,
                drain_grace_s=self.drain_grace_s,
                partitions=self.partitions or None,
                recorder=self.recorder,
                decision_log=self.decisions,
                attributor=self.attributor,
                replica=self.pod_name,
                corpus=self.corpus,
                sched_policy=self.sched_policy,
                slo=self.slo,
                integrity=self.integrity,
                ingest=self.ingest_mode != "off",
                ingest_port=self.ingest_port,
                ingest_decode=(
                    "zerocopy" if self.ingest_mode == "on" else "json"
                ),
            )
            # postmortem state sources: what a flight record snapshots
            # alongside the trace tail / cost table / fault points
            wh = self.webhook
            self.recorder.add_source(
                "webhook", lambda: {
                    "draining": wh.draining,
                    "shed": wh.batcher.shed_count,
                    "batch_failures": wh.batcher.batch_failures,
                    **(
                        {"breaker": wh.batcher.breaker.snapshot()}
                        if wh.batcher.breaker is not None
                        else {}
                    ),
                },
            )
            if wh.partitioner is not None:
                self.recorder.add_source(
                    "partitions", wh.partitioner.postmortem
                )
                # compile-plane state: a compile_storm record embeds the
                # program-store table + per-partition signatures
                self.recorder.add_source(
                    "programs", wh.partitioner.programs_table
                )
            if self.fleet is not None:
                self.recorder.add_source("fleet", self.fleet.snapshot)
            if self.integrity is not None:
                # a verdict_divergence / device_quarantine record
                # embeds the integrity plane's ledger + golden state
                self.recorder.add_source(
                    "integrity", self.integrity.snapshot
                )
            self.webhook.start()
            if (
                self.fleet is not None
                and self.webhook.partitioner is not None
            ):
                # per-device breaker state is a fleet property: each
                # device breaker registers under its
                # device:<plane>:<device_id> key as it is created, so a
                # chip sick on one replica pre-opens the same device's
                # breaker on peers (docs/fleet.md)
                self.webhook.partitioner.set_fleet(self.fleet)
            if self.fleet is not None:
                # device-breaker trips gossip: an outage one replica
                # discovered pre-opens peers' breakers to a half-open
                # probe instead of N independent rediscoveries
                for plane_name, batcher in (
                    ("device:validation", self.webhook.batcher),
                    ("device:mutation", self.webhook.mutate_batcher),
                    ("device:agent", self.webhook.agent_batcher),
                ):
                    if batcher is not None and batcher.breaker is not None:
                        self.fleet.register_breaker(
                            plane_name, batcher.breaker
                        )
            if self.vwh_name and self.webhook.rotator is not None:
                from ..webhook.certs import CaBundleInjector

                self.ca_injector = CaBundleInjector(
                    self.cluster, self.webhook.rotator, self.vwh_name
                )
                self.ca_injector.start()

        if OPERATION_AUDIT in self.operations:
            from ..audit import AuditManager

            self.audit = AuditManager(
                self.client,
                self.target,
                audit_interval=self.audit_interval,
                audit_chunk_size=self.audit_chunk_size,
                metrics=self.metrics,
                event_sink=self._emit_event,
                emit_audit_events=self.emit_audit_events,
                audit_from_cache=self.audit_from_cache,
                cluster=self.cluster,
                excluder=self.excluder,
                logger=self.log,
                tracer=self.tracer,
                wait_for=self._wait_ingested,
                decision_log=self.decisions,
            )
            self.audit.start()

        if self.webhook is not None:
            # background compile loop: warm the fused review path once
            # ingestion settles, and RE-warm whenever template churn
            # bumps the constraint generation and drops the route back
            # to the interpreter (serve-while-compiling: admission keeps
            # flowing on the interpreter throughout; the compiled route
            # swaps in atomically when each warm completes)
            def _warm():
                import time as _t

                # interruptible ingestion wait: this thread is
                # NON-daemon (a daemon killed mid-XLA-compile at
                # interpreter exit aborts the process, 'FATAL:
                # exception not rethrown'), so it must never out-wait
                # a stopped runner
                deadline = _t.monotonic() + 300
                while (
                    not self._warm_stop.is_set()
                    and _t.monotonic() < deadline
                ):
                    if self._wait_ingested(timeout=0.5):
                        break
                if self._warm_stop.is_set():
                    return
                self.webhook.warmup()
                drv = getattr(self.client, "_driver", None)
                check = getattr(drv, "review_path_warm", None)
                delay = 2.0
                while check is not None and not self._warm_stop.wait(delay):
                    if check(self.target):
                        delay = 2.0
                        continue
                    self.webhook.warmup()
                    if check(self.target):
                        delay = 2.0
                    else:
                        # deterministic compile failure: back off instead
                        # of re-attempting full compiles every 2s forever
                        delay = min(delay * 2, 120.0)
                        self.log.error(
                            "review-path warmup failed; backing off",
                            delay_seconds=delay,
                        )

            self._warm_thread = threading.Thread(
                target=_warm, name="gk-runner-warm", daemon=False
            )
            self._warm_thread.start()

        if self.readyz_port is not None:
            self._serve_readyz()

    def _emit_event(self, ev: Dict[str, Any]) -> None:
        """Violation-event sink: the bounded in-memory ring PLUS a real
        v1 Event through the EventSource — queued for a background
        drain thread so the ADMISSION PATH never blocks on an apiserver
        write (the reference decouples via the event broadcaster the
        same way, AnnotatedEventf policy.go:253-273 / audit emitEvent)."""
        self.events.append(ev)
        try:
            self._event_queue.append(ev)
            self._event_wake.set()
        except Exception:
            pass

    def _drain_events(self) -> None:
        import hashlib
        import time as _time

        while not self._event_stop.is_set():
            self._event_wake.wait(timeout=1.0)
            self._event_wake.clear()
            while True:
                try:
                    ev = self._event_queue.popleft()
                except IndexError:
                    break
                try:
                    ts = _time.strftime(
                        "%Y-%m-%dT%H:%M:%SZ", _time.gmtime()
                    )
                    ns = ev.get("resource_namespace") or "gatekeeper-system"
                    # deterministic name per (reason, object, message):
                    # re-emissions AGGREGATE via count/lastTimestamp like
                    # the reference's recorder instead of accumulating a
                    # new Event object per sweep forever
                    key = "|".join(
                        str(ev.get(k, ""))
                        for k in (
                            "reason",
                            "resource_kind",
                            "resource_namespace",
                            "resource_name",
                            "constraint_name",
                            "message",
                        )
                    )
                    name = (
                        "gatekeeper-tpu."
                        + hashlib.sha1(key.encode()).hexdigest()[:16]
                    )
                    gvk = GVK("", "v1", "Event")
                    count = 1
                    first_ts = ts
                    getter = getattr(self.cluster, "get", None)
                    if getter is not None:
                        cur = getter(gvk, ns, name)
                        if cur is not None:
                            count = int(cur.get("count") or 0) + 1
                            first_ts = cur.get("firstTimestamp", ts)
                    self.cluster.apply(
                        {
                            "apiVersion": "v1",
                            "kind": "Event",
                            "metadata": {"name": name, "namespace": ns},
                            "type": ev.get("type", "Warning"),
                            "reason": ev.get("reason", "Violation"),
                            "message": ev.get("message", ""),
                            "source": {"component": "gatekeeper-tpu"},
                            "involvedObject": {
                                "kind": ev.get("resource_kind", ""),
                                "namespace": ev.get(
                                    "resource_namespace", ""
                                ),
                                "name": ev.get("resource_name", ""),
                            },
                            "firstTimestamp": first_ts,
                            "lastTimestamp": ts,
                            "count": count,
                        }
                    )
                except Exception as e:
                    # Event emission is best-effort in the reference too
                    self.log.debug("event emission failed", err=str(e))

    def _wait_ingested(self, timeout: float = 30.0) -> bool:
        """Block until ingestion satisfies the readiness barrier."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.watch_mgr.wait_idle(timeout=1.0)
            if self.tracker.satisfied():
                return True
            time.sleep(0.01)
        return self.tracker.satisfied()

    def wait_ready(self, timeout: float = 30.0, warm: bool = False) -> bool:
        """Readiness = ingestion barrier satisfied, matching the
        reference (Ready as soon as state replays,
        pkg/readiness/ready_tracker.go:138-173). Kernel compilation no
        longer gates Ready (VERDICT r4 #4 reversing r3 #7): a cold pod
        serves admission from the interpreter within seconds while the
        fused path compiles in the background and swaps in atomically
        (TpuDriver.warm_review_path). Pass warm=True to additionally
        wait for the audit warm sweep — deterministic-measurement mode
        for benches and tests."""
        import time

        deadline = time.monotonic() + timeout
        if not self._wait_ingested(timeout):
            return False
        if warm and self.audit is not None:
            if not self.audit.warmed.wait(
                max(0.0, deadline - time.monotonic())
            ):
                return False
        return True

    def stop(self) -> None:
        # graceful drain FIRST: readiness flips not-ready while the
        # webhook listener still accepts, so a probing LB routes away
        # before any connection can fail (WebhookServer.stop then holds
        # the drain grace, closes the listener, and waits for in-flight
        # requests — a SIGTERM mid-load sheds zero accepted requests)
        if self.webhook is not None:
            self.webhook.begin_drain()
        # signal everything first, drain components, JOIN the warm
        # thread last — its join can ride out an in-flight XLA compile,
        # and serving must not keep running behind that wait
        self.switch.stop()
        self._event_stop.set()
        self._warm_stop.set()
        self._event_wake.set()
        if self.integrity is not None:
            self.integrity.close()  # stop the shadow-oracle worker
        if self.ca_injector is not None:
            self.ca_injector.stop()
        if self.fleet is not None:
            self.fleet.stop()
        if self.audit is not None:
            self.audit.stop()
        if self.webhook is not None:
            rot_stop = getattr(self.webhook.rotator, "stop", None)
            if rot_stop is not None:
                rot_stop()  # fleet rotator: unsubscribe the Secret watch
            self.webhook.stop()
        if self._readyz_httpd is not None:
            self._readyz_httpd.shutdown()
        if self.recorder is not None:
            self.recorder.stop()
        self.watch_mgr.stop()
        if self._warm_thread is not None:
            self._warm_thread.join(timeout=10)
            self._warm_thread = None

    # -- serving helpers -----------------------------------------------------

    def _get_namespace(self, name: str) -> Optional[dict]:
        return self.cluster.get(NAMESPACE_GVK, "", name)

    def _capture_profile(self, path: str) -> bytes:
        """Capture a JAX profiler trace for ?seconds=N (default 2,
        clamped to [0, 60]); returns JSON naming the XPlane trace
        directory (open with TensorBoard / xprof) or an error. One
        capture at a time (the profiler rejects nesting). Concurrent
        device work — sweeps, webhook dispatches — lands in the trace."""
        from urllib.parse import parse_qs, urlparse

        try:
            q = parse_qs(urlparse(path).query)
            seconds = float(q.get("seconds", ["2"])[0])
        except (ValueError, TypeError):
            return 400, json.dumps(
                {"error": "bad seconds parameter"}
            ).encode()
        if not self._profile_lock.acquire(blocking=False):
            return 409, json.dumps(
                {"error": "a profile capture is already running"}
            ).encode()
        try:
            doc = capture_jax_profile(seconds)
            code = 500 if "error" in doc else 200
            return code, json.dumps(doc).encode()
        finally:
            self._profile_lock.release()

    def _serve_readyz(self) -> None:
        runner = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path == "/readyz":
                    # Ready = state replayed (reference semantics); warm
                    # status stays visible in stats but does not gate.
                    # A DRAINING webhook reports not-ready immediately —
                    # the flip happens before its listener closes, so a
                    # probing LB stops routing while connections still
                    # succeed (docs/robustness.md graceful drain)
                    ingested = runner.tracker.satisfied()
                    draining = (
                        runner.webhook is not None
                        and runner.webhook.draining
                    )
                    ok = ingested and not draining
                    stats = {
                        "ingested": ingested,
                        "draining": draining,
                        **runner.tracker.stats(),
                    }
                    if runner.audit is not None:
                        stats["audit"] = {
                            "warm": runner.audit.warmed.is_set(),
                            "last_sweep_seconds": (
                                runner.audit.audit_duration_seconds
                            ),
                            "errors": runner.audit.error_count,
                        }
                    if runner.webhook is not None:
                        # overload/degradation envelope health
                        # (docs/robustness.md): breaker state answers
                        # "why is admission on the interpreter", shed
                        # counts answer "are we dropping load"
                        wh = {
                            "fail_policy": runner.fail_policy,
                            "shed": runner.webhook.batcher.shed_count,
                            "batch_failures": (
                                runner.webhook.batcher.batch_failures
                            ),
                        }
                        breaker = runner.webhook.batcher.breaker
                        if breaker is not None:
                            wh["breaker"] = breaker.snapshot()
                        partitioner = getattr(
                            runner.webhook, "partitioner", None
                        )
                        if partitioner is not None:
                            # fault-domain health: the partition plan,
                            # quarantine state, and per-device breaker
                            # snapshots (docs/robustness.md §Fault
                            # domains)
                            wh["partitions"] = partitioner.snapshot()
                        mb = runner.webhook.mutate_batcher
                        if mb is not None:
                            wh["mutation"] = {
                                "shed": mb.shed_count,
                                "batch_failures": mb.batch_failures,
                                **(
                                    {"breaker": mb.breaker.snapshot()}
                                    if mb.breaker is not None
                                    else {}
                                ),
                            }
                        stats["webhook"] = wh
                        ing = getattr(runner.webhook, "ingest", None)
                        if ing is not None:
                            # front-door health (docs/ingest.md):
                            # connection/frame counts, decode-route
                            # split, protocol-error sheds
                            stats["ingest"] = ing.stats()
                    if runner.external_data is not None:
                        # provider health: per-provider breaker state +
                        # failurePolicy answers "which lookups are
                        # degraded right now" (docs/externaldata.md)
                        stats["externaldata"] = (
                            runner.external_data.snapshot()
                        )
                    if runner.fleet is not None:
                        # fleet health (docs/fleet.md): which peers are
                        # alive, what state arrived from them, and the
                        # cert generation this replica serves
                        fl = runner.fleet.snapshot()
                        rot = getattr(runner.webhook, "rotator", None)
                        fl["cert_generation"] = getattr(
                            rot, "cert_generation", None
                        )
                        fl["cert_rotations_adopted"] = getattr(
                            rot, "rotations_adopted", None
                        )
                        stats["fleet"] = fl
                    drv = getattr(runner.client, "_driver", None)
                    if drv is not None and hasattr(drv, "stats"):
                        # engine routing health (docs/metrics.md): WHY
                        # templates run interpreted + the analyzer/
                        # compiler consistency assertion
                        d_stats = drv.stats or {}
                        stats["driver"] = {
                            "fallback_codes": d_stats.get(
                                "fallback_codes",
                                {
                                    k[1]: v
                                    for k, v in getattr(
                                        drv, "_fallback_codes", {}
                                    ).items()
                                },
                            ),
                            "analyzer_mismatches": getattr(
                                drv, "analyzer_mismatches", 0
                            ),
                            "cold_batches": getattr(
                                drv, "cold_batches", 0
                            ),
                        }
                    # cost-attribution + flight-recorder headlines
                    # (full payloads live at /debug/costs and
                    # /debug/flightrecords)
                    stats["obs"] = {
                        "costs": runner.attributor.snapshot(),
                        "flightrecords": runner.recorder.snapshot(),
                        "decisions": runner.decisions.snapshot(),
                    }
                    # live SLO headline — the `saturation`/`burning`
                    # fields are the autoscaler contract (full
                    # breakdown at /debug/slo); docs/observability.md
                    # §SLO & saturation
                    stats["slo"] = runner.slo.autoscaler()
                    # verdict-integrity headline: canary/shadow/
                    # self-test counters + corruption-quarantine state
                    # (full payload at /debug/integrity;
                    # docs/robustness.md §Verdict integrity)
                    if runner.integrity is not None:
                        stats["integrity"] = (
                            runner.integrity.snapshot()
                        )
                    # admission-scheduler headline: per-plane policy,
                    # overload state, shed split, and per-tenant
                    # quota/usage table (full payload at /debug/sched;
                    # docs/operations.md §Admission scheduling)
                    wh = getattr(runner, "webhook", None)
                    if wh is not None and hasattr(
                        wh, "sched_snapshot"
                    ):
                        stats["sched"] = wh.sched_snapshot()
                    # corpus analysis headline (docs/analysis.md
                    # §Corpus analysis): diagnostic counts + the
                    # dead/prunable/shadowed rollup; recompute is
                    # debounced + off-path, so this only reads the
                    # cached report (and may kick a background pass)
                    corpus = getattr(runner, "corpus", None)
                    if corpus is not None:
                        corpus.maybe_recompute()
                        stats["analysis"] = {
                            "corpus": corpus.snapshot()
                        }
                    # IR static-analysis headline (docs/analysis.md
                    # §IR analysis): liveness-plane counters + the
                    # per-target report rollup (reads the cached
                    # report; first touch computes it once per
                    # constraint generation)
                    if drv is not None and hasattr(
                        drv, "liveness_stats"
                    ):
                        ir: Dict[str, Any] = drv.liveness_stats()
                        # the admission target name lives on the
                        # webhook's batcher (WebhookServer itself
                        # holds no target attr)
                        tgt = getattr(
                            getattr(
                                runner.webhook, "batcher", None
                            ),
                            "target",
                            "admission.k8s.gatekeeper.sh",
                        )
                        try:
                            rep = drv.ir_report(tgt)
                        except Exception:
                            rep = None
                        if rep is not None:
                            ir.update({
                                "ok": rep.ok,
                                "subjects": rep.subjects,
                                "counts": rep.counts(),
                                "liveness": rep.liveness,
                                "certificates": len(
                                    rep.certificates
                                ),
                            })
                        stats.setdefault("analysis", {})["ir"] = ir
                    payload = json.dumps(
                        {"ready": ok, "stats": stats}
                    ).encode()
                    self.send_response(200 if ok else 503)
                elif self.path.split("?")[0] == "/debug/traces":
                    # recent request/sweep traces — ?trace_id=/?limit=/
                    # ?format=otlp (docs/observability.md)
                    from ..metrics.registry import export_traces

                    payload = export_traces(
                        runner.tracer, self.path
                    ).encode()
                    self.send_response(200)
                elif self.path.split("?")[0] == "/debug/costs":
                    # per-constraint device-time cost table, sorted
                    # costliest-first with share-of-plane fractions
                    # (docs/observability.md §Cost attribution)
                    from ..metrics.registry import _debug_costs_k

                    payload = json.dumps(
                        runner.attributor.table(
                            _debug_costs_k(self.path)
                        )
                    ).encode()
                    self.send_response(200)
                elif self.path == "/debug/partitions":
                    # live plan composition: per-partition constraint
                    # keys, static/measured cost share, home device
                    # (docs/robustness.md §Fault domains)
                    part = getattr(
                        runner.webhook, "partitioner", None
                    )
                    if part is not None:
                        payload = json.dumps(
                            part.plan_table()
                        ).encode()
                        self.send_response(200)
                    else:
                        payload = (
                            b'{"error": "partitions disabled"}'
                        )
                        self.send_response(404)
                elif self.path == "/debug/programs":
                    # compile plane: per-partition sub-program
                    # signature/staging state + program-store
                    # hit/miss/rejected and swap generation
                    # (docs/compile.md)
                    part = getattr(
                        runner.webhook, "partitioner", None
                    )
                    if part is not None:
                        payload = json.dumps(
                            part.programs_table()
                        ).encode()
                        self.send_response(200)
                    else:
                        payload = (
                            b'{"error": "partitions disabled"}'
                        )
                        self.send_response(404)
                elif self.path == "/debug/flightrecords":
                    # trip-triggered postmortem captures, newest first
                    # (docs/observability.md §Flight recorder)
                    payload = runner.recorder.export_json().encode()
                    self.send_response(200)
                elif self.path.split("?")[0] == "/debug/decisions":
                    # per-admission "why" records — ?trace_id=/
                    # ?verdict=/?plane=/?limit=/?format=ndjson
                    # (docs/observability.md §Decision log)
                    from ..metrics.registry import export_decisions

                    payload = export_decisions(
                        runner.decisions, self.path
                    ).encode()
                    self.send_response(200)
                elif self.path.split("?")[0] == "/debug/sched":
                    # admission-scheduler plane: per-plane policy /
                    # overload / shed counters + per-tenant fair-share
                    # quota table — ?plane=/?tenants=0
                    # (docs/operations.md §Admission scheduling)
                    from ..sched import export_sched

                    wh = getattr(runner, "webhook", None)
                    if wh is not None and hasattr(
                        wh, "sched_snapshot"
                    ):
                        payload = export_sched(
                            wh.sched_snapshot(), self.path
                        ).encode()
                        self.send_response(200)
                    else:
                        payload = (
                            b'{"error": "webhook not running"}'
                        )
                        self.send_response(404)
                elif self.path.split("?")[0] == "/debug/slo":
                    # live SLO plane: per-plane/per-tenant attainment,
                    # burn rates, saturation/headroom — ?plane=/
                    # ?tenants=0 (docs/observability.md §SLO &
                    # saturation)
                    from ..obs.slo import export_slo

                    payload = export_slo(
                        runner.slo, self.path
                    ).encode()
                    self.send_response(200)
                elif self.path == "/debug/integrity":
                    # verdict-integrity plane: golden canary sets,
                    # per-device mismatch ledger, shadow-oracle
                    # counters, corruption-quarantine state
                    # (docs/robustness.md §Verdict integrity)
                    if runner.integrity is not None:
                        payload = json.dumps(
                            runner.integrity.snapshot()
                        ).encode()
                        self.send_response(200)
                    else:
                        payload = (
                            b'{"error": "integrity disabled"}'
                        )
                        self.send_response(404)
                elif self.path == "/healthz":
                    payload = b'{"ok": true}'
                    self.send_response(200)
                elif (
                    runner.enable_profiler
                    and self.path.startswith("/debug/profile")
                ):
                    code, payload = runner._capture_profile(self.path)
                    self.send_response(code)
                else:
                    payload = b'{"error": "not found"}'
                    self.send_response(404)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *args):
                pass

        self._readyz_httpd = ThreadingHTTPServer(
            (self.bind_addr, self.readyz_port or 0), _Handler
        )
        self.readyz_port = self._readyz_httpd.server_address[1]
        threading.Thread(
            target=self._readyz_httpd.serve_forever, daemon=True
        ).start()


def capture_jax_profile(seconds: float) -> Dict[str, Any]:
    """One JAX profiler (XPlane) capture of `seconds` of live device
    work, written to a fresh temp directory (open with TensorBoard /
    xprof). Shared by the Runner's /debug/profile endpoint and
    `bench_webhook.py --profile` (the ladder-rung capture); callers
    own their own single-flight locking — the profiler itself rejects
    nesting."""
    import tempfile
    import time as _time

    seconds = max(0.0, min(float(seconds), 60.0))
    try:
        import jax

        out_dir = tempfile.mkdtemp(prefix="gk-jaxprof-")
        with jax.profiler.trace(out_dir):
            _time.sleep(seconds)
        return {"trace_dir": out_dir, "seconds": seconds}
    except Exception as e:
        return {"error": str(e)}


def load_yaml_dir(cluster: FakeCluster, path: str) -> int:
    """Bootstrap a FakeCluster from a directory tree of YAML manifests
    (the slim standalone stand-in for a live apiserver; SURVEY §7 M5
    allows exactly this for the benchmark configs)."""
    import os

    import yaml

    n = 0
    for root, _dirs, files in os.walk(path):
        for fname in sorted(files):
            if not fname.endswith((".yaml", ".yml")):
                continue
            with open(os.path.join(root, fname)) as f:
                for doc in yaml.safe_load_all(f):
                    if isinstance(doc, dict) and doc.get("kind"):
                        cluster.apply(doc)
                        n += 1
    return n
