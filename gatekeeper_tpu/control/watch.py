"""Watch manager: dynamic multiplexed watches with replay.

Mirrors pkg/watch/manager.go + registrar.go + replay.go:

  * Controllers register interest in GVKs through named `Registrar`s
    (registrar.go:202-247). Watches are reference-counted per GVK
    (recordKeeper, registrar.go:52): the first registrar starts the
    underlying subscription (doAddWatch, manager.go:148), later joiners
    get an async **replay** of the current List instead of a new watch
    (replay.go:36-200); when the last registrar leaves, the subscription
    is torn down (doRemoveWatch, manager.go:209).
  * Events are distributed on a background thread to every registrar's
    sink (eventLoop/distributeEvent, manager.go:311-348) so slow
    consumers never block the source.
  * `replace_watch` swaps a registrar's whole GVK set atomically
    (registrar.go:226, the config controller's path).

The sink contract is a callable taking `Event`; controllers enqueue into
their own work queues.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, List, Optional, Set, Tuple

from .events import DELETED, Event, EventSink, EventSource, GVK, ADDED


class Registrar:
    """One controller's handle on the manager (registrar.go:202)."""

    def __init__(self, name: str, mgr: "WatchManager", sink: EventSink):
        self.name = name
        self._mgr = mgr
        self.sink = sink

    def add_watch(self, gvk: GVK) -> None:
        self._mgr._add_watch(self, gvk)

    def remove_watch(self, gvk: GVK) -> None:
        self._mgr._remove_watch(self, gvk)

    def replace_watch(self, gvks: Set[GVK]) -> None:
        self._mgr._replace_watch(self, set(gvks))

    def watched(self) -> Set[GVK]:
        return self._mgr._watched_by(self)


class WatchManager:
    def __init__(self, source: EventSource, metrics=None):
        self.source = source
        self.metrics = metrics
        self._lock = threading.RLock()
        # gvk -> {registrar name -> Registrar}
        self._interest: Dict[GVK, Dict[str, Registrar]] = {}
        self._unsubs: Dict[GVK, Callable[[], None]] = {}
        self._registrars: Dict[str, Registrar] = {}
        # distribution queue: (event, [sinks]) handled off-thread
        self._q: "queue.Queue[Optional[Tuple[Event, List[EventSink]]]]" = (
            queue.Queue()
        )
        self._inflight = 0
        self._idle = threading.Condition()
        self._thread = threading.Thread(target=self._event_loop, daemon=True)
        self._thread.start()

    # -- registrar lifecycle ---------------------------------------------------

    def new_registrar(self, name: str, sink: EventSink) -> Registrar:
        with self._lock:
            if name in self._registrars:
                raise ValueError(f"registrar {name!r} already exists")
            r = Registrar(name, self, sink)
            self._registrars[name] = r
            return r

    def _watched_by(self, r: Registrar) -> Set[GVK]:
        with self._lock:
            return {g for g, m in self._interest.items() if r.name in m}

    def watched_gvks(self) -> Set[GVK]:
        with self._lock:
            return {g for g, m in self._interest.items() if m}

    # -- watch bookkeeping -----------------------------------------------------

    def _add_watch(self, r: Registrar, gvk: GVK) -> None:
        with self._lock:
            holders = self._interest.setdefault(gvk, {})
            if r.name in holders:
                return
            first = not holders
            holders[r.name] = r
            if first:
                # first registrar: start the real subscription, then feed
                # the initial List through the same pipe (informer start)
                self._unsubs[gvk] = self.source.subscribe(
                    gvk, lambda ev: self._distribute(ev)
                )
                snapshot = self.source.list(gvk)
                for obj in snapshot:
                    self._enqueue(Event(ADDED, gvk, obj), [r.sink])
            else:
                # late joiner: async replay of current state, this
                # registrar only (replay.go:36-200)
                snapshot = self.source.list(gvk)
                for obj in snapshot:
                    self._enqueue(Event(ADDED, gvk, obj), [r.sink])
            self._report()

    def _remove_watch(self, r: Registrar, gvk: GVK) -> None:
        with self._lock:
            holders = self._interest.get(gvk, {})
            holders.pop(r.name, None)
            if not holders:
                unsub = self._unsubs.pop(gvk, None)
                if unsub is not None:
                    unsub()
                self._interest.pop(gvk, None)
            self._report()

    def _replace_watch(self, r: Registrar, gvks: Set[GVK]) -> None:
        current = self._watched_by(r)
        for g in current - gvks:
            self._remove_watch(r, g)
        for g in gvks - current:
            self._add_watch(r, g)

    def _report(self) -> None:
        if self.metrics is not None:
            n = len(self.watched_gvks())
            self.metrics.gauge("watch_manager_watched_gvk", n)
            # intended == watched here: _add_watch starts subscriptions
            # synchronously, so there is no requested-but-not-running
            # gap (the reference tracks the two separately because its
            # informer creation is async, watch/stats_reporter.go)
            self.metrics.gauge("watch_manager_intended_watch_gvk", n)

    # -- event distribution -----------------------------------------------------

    def _distribute(self, ev: Event) -> None:
        with self._lock:
            sinks = [r.sink for r in self._interest.get(ev.gvk, {}).values()]
        if sinks:
            self._enqueue(ev, sinks)

    def _enqueue(self, ev: Event, sinks: List[EventSink]) -> None:
        with self._idle:
            self._inflight += 1
        self._q.put((ev, sinks))

    def _event_loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            ev, sinks = item
            for s in sinks:
                try:
                    s(ev)
                except Exception:
                    pass  # a broken consumer must not stall the fan-out
            with self._idle:
                self._inflight -= 1
                if self._inflight == 0:
                    self._idle.notify_all()

    def wait_idle(self, timeout: float = 10.0) -> bool:
        """Block until the distribution queue fully drains (tests)."""
        import time as _time

        deadline = _time.monotonic() + timeout
        with self._idle:
            while self._inflight > 0:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(remaining)
            return True

    def stop(self) -> None:
        with self._lock:
            for unsub in self._unsubs.values():
                unsub()
            self._unsubs.clear()
            self._interest.clear()
        self._q.put(None)
        self._thread.join(timeout=5)
