"""Control plane: state ingestion, readiness, process runtime.

The reference's equivalents live under pkg/controller/, pkg/watch/,
pkg/readiness/, and main.go; here the same architecture runs against an
`EventSource` (a fake in-memory cluster or a real apiserver adapter):
cluster -> WatchManager -> controllers -> constraint-framework Client,
with the ReadinessTracker gating /readyz and `Runner` as the
main()-equivalent.
"""

from .process import Excluder, PROCESS_AUDIT, PROCESS_SYNC, PROCESS_WEBHOOK, PROCESS_STAR  # noqa: F401
from .readiness import ReadinessTracker  # noqa: F401
from .events import (  # noqa: F401
    ADDED,
    DELETED,
    Event,
    EventSource,
    FakeCluster,
    GVK,
    MODIFIED,
)
from .kubecluster import KubeCluster, KubeError  # noqa: F401
from .watch import Registrar, WatchManager  # noqa: F401
from .controllers import (  # noqa: F401
    CONFIG_GVK,
    ConfigController,
    ConstraintController,
    ControllerSwitch,
    MUTATOR_GVKS,
    MutatorController,
    PROVIDER_GVK,
    ProviderController,
    SyncController,
    TemplateController,
    TEMPLATE_GVK,
    constraint_gvk,
)
from .status import StatusAggregator, StatusWriter  # noqa: F401
from .upgrade import UpgradeManager  # noqa: F401
from .runner import (  # noqa: F401
    ALL_OPERATIONS,
    OPERATION_AUDIT,
    OPERATION_STATUS,
    OPERATION_WEBHOOK,
    Runner,
    load_yaml_dir,
)
