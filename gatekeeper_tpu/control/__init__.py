"""Control-plane pieces that don't need a live cluster: process
exclusion, config handling, readiness tracking.

The reference's equivalents live under pkg/controller/ and pkg/readiness/
and are wired to the K8s API server; here they are plain objects the
runner/webhook/audit layers compose.
"""

from .process import Excluder, PROCESS_AUDIT, PROCESS_SYNC, PROCESS_WEBHOOK, PROCESS_STAR  # noqa: F401
from .readiness import ReadinessTracker  # noqa: F401
