"""Real-apiserver EventSource: list+watch over the Kubernetes HTTP API.

The reference's entire control plane runs against a live apiserver via
client-go informers (pkg/watch/manager.go:280-348, forked dynamiccache
third_party/.../dynamiccache/informer_cache.go:168, manager wiring
main.go:136-146). `KubeCluster` is this framework's native equivalent of
that stack behind the same `EventSource` seam the FakeCluster implements,
so the Runner, controllers, status plane, and audit run UNCHANGED against
a real cluster:

  * discovery — /api/v1 + /apis group lists map GVK -> REST path
    (plural, namespaced) and enumerate listable kinds (the audit
    manager's ServerPreferredResources analog, audit/manager.go:244-272);
  * list/get — plain GETs, with apiVersion/kind re-stamped onto items
    (list responses omit them);
  * subscribe — a watch thread per subscription: chunked
    ?watch=1&allowWatchBookmarks=true streams decoded line-by-line, with
    informer-style RELIST-AND-DIFF recovery on stream errors/410 Gone
    (synthetic ADDED/MODIFIED/DELETED from the per-subscription cache,
    then re-watch from the fresh resourceVersion);
  * apply/delete — POST, falling back to read-modify-PUT on conflict
    (the status plane's CR writes, audit/manager.go:581-639).

Pure stdlib (urllib + ssl): in-cluster config from the service-account
mount, or explicit base_url/token/ca for tests and kubeconfig-less use.
"""

from __future__ import annotations

import json
import os
import ssl
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..logs import null_logger
from .events import (
    ADDED,
    Conflict,
    DELETED,
    Event,
    EventSink,
    EventSource,
    GVK,
    MODIFIED,
    obj_key,
)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeError(Exception):
    def __init__(self, code: int, body: str):
        super().__init__(f"apiserver {code}: {body[:200]}")
        self.code = code
        self.body = body


class KubeCluster(EventSource):
    def __init__(
        self,
        base_url: Optional[str] = None,
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
        verify: bool = True,
        watch_timeout_seconds: int = 300,
        logger=None,
    ):
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise KubeError(0, "no base_url and not running in-cluster")
            base_url = f"https://{host}:{port}"
            if token is None and os.path.exists(f"{SA_DIR}/token"):
                with open(f"{SA_DIR}/token") as f:
                    token = f.read().strip()
            if ca_file is None and os.path.exists(f"{SA_DIR}/ca.crt"):
                ca_file = f"{SA_DIR}/ca.crt"
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.watch_timeout_seconds = watch_timeout_seconds
        self.log = logger if logger is not None else null_logger()
        self._ctx: Optional[ssl.SSLContext] = None
        if self.base_url.startswith("https"):
            self._ctx = ssl.create_default_context(cafile=ca_file)
            if not verify:
                self._ctx.check_hostname = False
                self._ctx.verify_mode = ssl.CERT_NONE
        self._lock = threading.Lock()
        # GVK -> (plural, namespaced); None = not served
        self._rest_info: Dict[GVK, Optional[Tuple[str, bool]]] = {}
        self._stopping = threading.Event()
        self._watchers: List["_Watcher"] = []

    # -- HTTP ----------------------------------------------------------------

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        timeout: float = 30.0,
        stream: bool = False,
    ):
        url = self.base_url + path
        data = None
        headers = {"Accept": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        if body is not None:
            data = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        req = urllib.request.Request(
            url, data=data, method=method, headers=headers
        )
        try:
            resp = urllib.request.urlopen(
                req, timeout=timeout, context=self._ctx
            )
        except urllib.error.HTTPError as e:
            raise KubeError(e.code, e.read().decode(errors="replace"))
        except urllib.error.URLError as e:
            raise KubeError(0, str(e.reason))
        if stream:
            return resp
        with resp:
            return json.loads(resp.read() or b"{}")

    # -- discovery -----------------------------------------------------------

    def _gvk_path(self, gvk: GVK) -> Tuple[str, bool]:
        """-> (collection path prefix, namespaced)."""
        info = self._discover(gvk)
        if info is None:
            raise KubeError(404, f"kind not served: {gvk}")
        plural, namespaced = info
        if gvk.group:
            return f"/apis/{gvk.group}/{gvk.version}/{plural}", namespaced
        return f"/api/{gvk.version}/{plural}", namespaced

    def _discover(self, gvk: GVK) -> Optional[Tuple[str, bool]]:
        with self._lock:
            if gvk in self._rest_info:
                return self._rest_info[gvk]
        base = (
            f"/apis/{gvk.group}/{gvk.version}"
            if gvk.group
            else f"/api/{gvk.version}"
        )
        info: Optional[Tuple[str, bool]] = None
        try:
            rl = self._request("GET", base)
            for r in rl.get("resources", []):
                if r.get("kind") == gvk.kind and "/" not in r.get("name", ""):
                    info = (r["name"], bool(r.get("namespaced")))
                    break
        except KubeError as e:
            if e.code not in (403, 404):
                raise
        # cache POSITIVE results only: a constraint kind's CRD may be
        # established moments after the template ingests, and a cached
        # None would make the watcher's retry loop re-read a stale miss
        # forever (the kind would silently never be enforced)
        if info is not None:
            with self._lock:
                self._rest_info[gvk] = info
        return info

    def known_gvks(self) -> List[GVK]:
        """Every list+watchable kind the server discovers (the audit
        manager's direct-list sweep source; manager.go:244-272)."""
        out: List[GVK] = []
        try:
            core = self._request("GET", "/api/v1")
            for r in core.get("resources", []):
                verbs = set(r.get("verbs") or [])
                if "/" in r.get("name", "") or "list" not in verbs:
                    continue
                out.append(GVK("", "v1", r["kind"]))
        except KubeError:
            pass
        try:
            groups = self._request("GET", "/apis")
            for g in groups.get("groups", []):
                pref = (g.get("preferredVersion") or {}).get("groupVersion")
                if not pref:
                    continue
                try:
                    rl = self._request("GET", f"/apis/{pref}")
                except KubeError:
                    continue
                grp, _, ver = pref.partition("/")
                for r in rl.get("resources", []):
                    verbs = set(r.get("verbs") or [])
                    if "/" in r.get("name", "") or "list" not in verbs:
                        continue
                    out.append(GVK(grp, ver, r["kind"]))
        except KubeError:
            pass
        return out

    # -- reads ---------------------------------------------------------------

    # page size for chunked Lists (the reference's --audit-chunk-size
    # posture, audit/manager.go:50,280-334: big clusters must not be
    # fetched as one giant response)
    list_chunk_size = 500

    def _pages(self, gvk: GVK, limit: int):
        """The limit/continue pagination protocol, shared by list() and
        list_pages(): yields (items, list metadata) per page with
        apiVersion/kind restamped on every item."""
        path, _ = self._gvk_path(gvk)
        cont = ""
        while True:
            qs = f"?limit={limit}"
            if cont:
                from urllib.parse import quote

                qs += f"&continue={quote(cont)}"
            doc = self._request("GET", path + qs)
            items = doc.get("items") or []
            for it in items:
                it.setdefault("apiVersion", gvk.api_version)
                it.setdefault("kind", gvk.kind)
            meta = doc.get("metadata") or {}
            yield items, meta
            cont = meta.get("continue") or ""
            if not cont:
                return

    def _list_raw(self, gvk: GVK) -> Tuple[List[Dict[str, Any]], str]:
        items: List[Dict[str, Any]] = []
        rv = ""
        for page, meta in self._pages(gvk, self.list_chunk_size):
            items.extend(page)
            rv = meta.get("resourceVersion", rv)
        return items, rv

    def list(self, gvk: GVK) -> List[Dict[str, Any]]:
        try:
            return self._list_raw(gvk)[0]
        except KubeError as e:
            if e.code in (403, 404):
                return []
            raise

    def list_pages(self, gvk: GVK, limit: int):
        """Stream the collection page by page at the given limit —
        bounded memory for huge kinds (the reference's paged audit
        listing, --audit-chunk-size + client.List w/ Continue,
        audit/manager.go:277-298). Yields lists of items.

        A continue token that expires mid-stream (410 ResourceExpired:
        etcd compaction outruns a slow consumer) falls back to ONE full
        relist from scratch, like client-go's pager — the caller sees
        the fresh pages after a RESTART marker of None, so it can drop
        partial per-kind state instead of double-counting."""
        try:
            gen = self._pages(gvk, limit)
            restarted = False
            while True:
                try:
                    items, _meta = next(gen)
                except StopIteration:
                    return
                except KubeError as e:
                    if e.code == 410 and not restarted:
                        restarted = True
                        yield None  # RESTART: discard prior pages
                        gen = self._pages(gvk, limit)
                        continue
                    raise
                if items:
                    yield items
        except KubeError as e:
            if e.code in (403, 404):
                return  # kind not (yet) served
            raise

    def _collection_path(self, gvk: GVK, namespace: str = "") -> str:
        """Collection path, namespaced when the kind is and a namespace
        is given (/api/v1/namespaces/<ns>/pods vs /api/v1/pods)."""
        path, namespaced = self._gvk_path(gvk)
        if namespaced and namespace:
            head, plural = path.rsplit("/", 1)
            return f"{head}/namespaces/{namespace}/{plural}"
        return path

    def get(self, gvk: GVK, namespace: str, name: str) -> Optional[dict]:
        path = self._collection_path(gvk, namespace)
        try:
            obj = self._request("GET", f"{path}/{name}")
        except KubeError as e:
            if e.code == 404:
                return None
            raise
        obj.setdefault("apiVersion", gvk.api_version)
        obj.setdefault("kind", gvk.kind)
        return obj

    # -- watch ---------------------------------------------------------------

    def subscribe(self, gvk: GVK, sink: EventSink) -> Callable[[], None]:
        w = _Watcher(self, gvk, sink)
        with self._lock:
            self._watchers.append(w)
        w.start()

        def unsubscribe() -> None:
            w.stop()
            with self._lock:
                if w in self._watchers:
                    self._watchers.remove(w)

        return unsubscribe

    def stop(self) -> None:
        self._stopping.set()
        with self._lock:
            watchers = list(self._watchers)
        for w in watchers:
            w.stop()

    # -- writes --------------------------------------------------------------

    def _obj_path(self, obj: Dict[str, Any]) -> str:
        meta = obj.get("metadata") or {}
        return self._collection_path(
            GVK.from_obj(obj), meta.get("namespace") or ""
        )

    def create(self, obj: Dict[str, Any]) -> None:
        """Create-ONLY write: POST, with the apiserver's 409 surfaced as
        `events.Conflict` instead of retried into a replace. The fleet
        cert store's load-or-create depends on losing this race loudly —
        the loser adopts the winner's Secret rather than clobbering it
        (certs.go:119-181's CreateOrUpdate-with-conflict posture)."""
        coll = self._obj_path(obj)
        try:
            self._request("POST", coll, body=obj)
        except KubeError as e:
            if e.code == 409:
                name = (obj.get("metadata") or {}).get("name", "")
                raise Conflict(f"{coll}/{name} already exists") from e
            raise

    def apply(self, obj: Dict[str, Any]) -> None:
        """Create-or-replace (the status plane's write-with-retry,
        audit/manager.go:581-639)."""
        coll = self._obj_path(obj)
        name = (obj.get("metadata") or {}).get("name", "")
        try:
            self._request("POST", coll, body=obj)
            return
        except KubeError as e:
            if e.code != 409:
                raise
        for _ in range(4):
            cur = self._request("GET", f"{coll}/{name}")
            merged = dict(obj)
            meta = dict(obj.get("metadata") or {})
            meta["resourceVersion"] = (cur.get("metadata") or {}).get(
                "resourceVersion", ""
            )
            merged["metadata"] = meta
            try:
                self._request("PUT", f"{coll}/{name}", body=merged)
                return
            except KubeError as e:
                if e.code != 409:
                    raise
        raise KubeError(409, f"persistent conflict updating {name}")

    def delete(self, obj_or_gvk, namespace: str = "", name: str = "") -> bool:
        if isinstance(obj_or_gvk, GVK):
            gvk = obj_or_gvk
            ns = namespace
        else:
            gvk = GVK.from_obj(obj_or_gvk)
            meta = obj_or_gvk.get("metadata") or {}
            ns = meta.get("namespace") or ""
            name = meta.get("name") or ""
        path = self._collection_path(gvk, ns)
        try:
            self._request("DELETE", f"{path}/{name}")
            return True
        except KubeError as e:
            if e.code == 404:
                return False
            raise


class _Watcher:
    """One subscription's watch loop: stream, decode, dispatch; on any
    stream failure relist-and-diff (informer resync) and re-watch."""

    def __init__(self, cluster: KubeCluster, gvk: GVK, sink: EventSink):
        self.cluster = cluster
        self.gvk = gvk
        self.sink = sink
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._known: Dict[Tuple[str, str], str] = {}  # key -> resourceVersion
        self._rv = ""

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _emit(self, etype: str, obj: Dict[str, Any]) -> None:
        obj.setdefault("apiVersion", self.gvk.api_version)
        obj.setdefault("kind", self.gvk.kind)
        try:
            self.sink(Event(etype, self.gvk, obj))
        except Exception as e:
            self.cluster.log.error(
                "watch sink failed", err=e, event_type=etype
            )

    def _resync(self) -> bool:
        """List and reconcile against the subscription cache — the
        informer's replay after a broken/expired watch."""
        try:
            items, rv = self.cluster._list_raw(self.gvk)
        except KubeError as e:
            if e.code in (403, 404):
                return False  # kind (not yet) served: retry later
            self.cluster.log.error("relist failed", err=e, gvk=str(self.gvk))
            return False
        seen: Dict[Tuple[str, str], str] = {}
        for obj in items:
            key = obj_key(obj)
            orv = (obj.get("metadata") or {}).get("resourceVersion", "")
            seen[key] = orv
            old = self._known.get(key)
            if old is None:
                self._emit(ADDED, obj)
            elif old != orv:
                self._emit(MODIFIED, obj)
        for key in list(self._known):
            if key not in seen:
                ns, name = key
                self._emit(
                    DELETED,
                    {
                        "metadata": {
                            "namespace": ns or None,
                            "name": name,
                        }
                    },
                )
        self._known = seen
        self._rv = rv
        return True

    def _loop(self) -> None:
        backoff = 0.2
        while not self._stop.is_set():
            # relist-and-diff only when there is no resume point: first
            # pass, expired/failed stream. A CLEAN server-side close
            # (the default 300s watch timeout) re-watches straight from
            # the last bookmark rv like the reference's informers —
            # relisting there is O(corpus) list traffic per subscription
            # every few minutes (ADVICE r4).
            if not self._rv:
                if not self._resync():
                    self._stop.wait(min(backoff, 30.0))
                    backoff *= 2
                    continue
                backoff = 0.2
            started = time.monotonic()
            try:
                self._watch_once()
                # clean close: normally re-watch immediately (real
                # servers close every few minutes) — but a stream that
                # died in under a second (draining apiserver, proxy
                # dropping long-lived requests) must not busy-loop
                # watch requests; back off until streams live again
                if time.monotonic() - started >= 0.5:
                    backoff = 0.2
                else:
                    self._stop.wait(min(backoff, 30.0))
                    backoff *= 2
            except KubeError as e:
                if e.code == 410:
                    # expired resourceVersion: only this invalidates the
                    # resume point — transient apiserver errors (500s,
                    # failed establishment) keep _rv and re-watch, no
                    # O(corpus) relist
                    self._rv = ""
                else:
                    self.cluster.log.error(
                        "watch failed", err=e, gvk=str(self.gvk)
                    )
                    self._stop.wait(min(backoff, 30.0))
                    backoff *= 2
            except Exception as e:
                # mid-stream break (decode error, socket reset): events
                # may have been lost — relist-and-diff to reconverge
                self._rv = ""
                self.cluster.log.error(
                    "watch stream error", err=e, gvk=str(self.gvk)
                )
                self._stop.wait(min(backoff, 30.0))
                backoff *= 2

    def _watch_once(self) -> None:
        path, _ = self.cluster._gvk_path(self.gvk)
        qs = (
            f"?watch=1&allowWatchBookmarks=true"
            f"&timeoutSeconds={self.cluster.watch_timeout_seconds}"
            f"&resourceVersion={self._rv}"
        )
        resp = self.cluster._request(
            "GET",
            path + qs,
            timeout=self.cluster.watch_timeout_seconds + 15,
            stream=True,
        )
        with resp:
            while not self._stop.is_set():
                line = resp.readline()
                if not line:
                    return  # server closed (timeout): relist+rewatch
                line = line.strip()
                if not line:
                    continue
                ev = json.loads(line)
                etype = ev.get("type")
                obj = ev.get("object") or {}
                if etype == "BOOKMARK":
                    self._rv = (obj.get("metadata") or {}).get(
                        "resourceVersion", self._rv
                    )
                    continue
                if etype == "ERROR":
                    code = obj.get("code", 0)
                    raise KubeError(code or 500, json.dumps(obj))
                if etype not in (ADDED, MODIFIED, DELETED):
                    continue
                key = obj_key(obj)
                rv = (obj.get("metadata") or {}).get("resourceVersion", "")
                if etype == DELETED:
                    self._known.pop(key, None)
                else:
                    self._known[key] = rv
                self._rv = rv or self._rv
                self._emit(etype, obj)
