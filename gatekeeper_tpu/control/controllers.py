"""State-ingestion controllers: the reconcile plane.

Counterparts of pkg/controller/*: each controller consumes watch events
for its GVKs and drives the constraint-framework Client, so no caller
ever touches the Client directly — exactly the reference's ingestion
architecture (SURVEY §3.4/§3.5 call stacks).

  * `TemplateController` — ConstraintTemplate upsert/delete →
    create_crd + add_template / remove_template, dynamic watch
    registration for the constraint kind, readiness observe, per-pod
    status publication, ingestion metrics
    (constrainttemplate_controller.go:244,398-485,553).
  * `ConstraintController` — one controller for ALL constraint kinds,
    fed dynamically as templates create kinds (the reference packs
    GVK+name into one shared channel, constraint_controller.go:138-189,
    util/pack.go:16; here the Event carries its GVK natively) →
    add_constraint / remove_constraint + status + metrics.
  * `ConfigController` — the singleton Config (gatekeeper-system/config,
    pkg/keys/config.go:24): rebuilds the process excluder, computes the
    sync-only GVK set, wipes all cached data, and swaps the sync
    registrar's watch set — the initial List the watch manager replays
    through the pipe IS replayData (config_controller.go:183,268-331).
  * `SyncController` — data GVK events → add_data / remove_data,
    filtered against the live sync set so stale events from a replaced
    watch are dropped (opadataclient.go FilteredDataClient), readiness
    observe + sync metrics.

Controllers process events inline on the watch manager's distribution
thread (the reference's workqueue concurrency is 1 for config/sync too);
`ControllerSwitch` drains reconciles on shutdown
(watch/controller_switch.go).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Set, Tuple

from ..constraint.handler import WipeData
from .events import DELETED, Event, GVK
from .process import Excluder
from .readiness import ReadinessTracker
from .watch import Registrar, WatchManager

TEMPLATE_GVK = GVK("templates.gatekeeper.sh", "v1beta1", "ConstraintTemplate")
CONFIG_GVK = GVK("config.gatekeeper.sh", "v1alpha1", "Config")
CONSTRAINT_GROUP = "constraints.gatekeeper.sh"
MUTATION_GROUP = "mutations.gatekeeper.sh"
CONFIG_NAMESPACE = "gatekeeper-system"
CONFIG_NAME = "config"

# the three mutator GVKs one MutatorController watches (the reference
# runs one controller per kind; the Event carries its GVK natively here,
# so one sink covers all three — the ConstraintController pattern)
MUTATOR_GVKS = tuple(
    GVK(MUTATION_GROUP, "v1alpha1", kind)
    for kind in ("Assign", "AssignMetadata", "ModifySet")
)

EXTERNALDATA_GROUP = "externaldata.gatekeeper.sh"
PROVIDER_GVK = GVK(EXTERNALDATA_GROUP, "v1alpha1", "Provider")


def constraint_gvk(kind: str) -> GVK:
    return GVK(CONSTRAINT_GROUP, "v1beta1", kind)


class ControllerSwitch:
    """Shutdown gate: reconciles become no-ops once stopped
    (watch/controller_switch.go)."""

    def __init__(self):
        self._on = True
        self._lock = threading.Lock()

    def enter(self) -> bool:
        with self._lock:
            return self._on

    def stop(self) -> None:
        with self._lock:
            self._on = False


class TemplateController:
    def __init__(
        self,
        client,
        watch_mgr: WatchManager,
        constraint_registrar: Registrar,
        tracker: Optional[ReadinessTracker] = None,
        switch: Optional[ControllerSwitch] = None,
        metrics=None,
        status=None,
        constraint_controller: Optional["ConstraintController"] = None,
        logger=None,
    ):
        from ..logs import null_logger

        self.client = client
        self.watch_mgr = watch_mgr
        self.constraint_registrar = constraint_registrar
        self.tracker = tracker
        self.switch = switch
        self.metrics = metrics
        self.status = status
        self.constraint_controller = constraint_controller
        self.log = logger if logger is not None else null_logger()
        self._lock = threading.Lock()
        self._kinds: Dict[str, str] = {}  # template name -> constraint kind
        self.errors: Dict[str, str] = {}  # template name -> last error

    def sink(self, ev: Event) -> None:
        if self.switch is not None and not self.switch.enter():
            return
        meta = ev.obj.get("metadata") or {}
        name = meta.get("name", "")
        t0 = time.perf_counter()
        status = "active"
        try:
            if ev.type == DELETED:
                self._on_delete(name, ev.obj)
            else:
                self._on_upsert(name, ev.obj)
            self.errors.pop(name, None)
        except Exception as e:
            status = "error"
            self.errors[name] = str(e)
            self.log.error(
                "template ingest failed",
                err=e,
                process="controller",
                template_name=name,
            )
        report = None
        if ev.type != DELETED and status == "active":
            getter = getattr(self.client, "template_report", None)
            if getter is not None:
                report = getter(name)
        if self.metrics is not None:
            self.metrics.record(
                "constraint_template_ingestion_count", 1, status=status
            )
            self.metrics.observe(
                "constraint_template_ingestion_duration_seconds",
                time.perf_counter() - t0,
                status=status,
            )
            self._report_count()
            if report is not None:
                # per-template verdict + diagnostic-code counts: the
                # vectorized-vs-interpreter split as a scrapeable fact
                self.metrics.gauge(
                    "template_vectorization",
                    1,
                    kind=report.kind,
                    verdict=report.verdict,
                )
                for code in report.codes:
                    self.metrics.gauge(
                        "template_analysis_diagnostics",
                        sum(
                            1
                            for d in report.diagnostics
                            if d.code == code
                        ),
                        kind=report.kind,
                        code=code,
                    )
        if self.status is not None:
            self.status.publish_template(
                name, status, self.errors.get(name), report=report
            )
        # readiness: observed whether or not compile succeeded — an
        # erroring template must not hold the process unready forever
        # (the reference tracker observes on reconcile, not success)
        if self.tracker is not None:
            self.tracker.templates.observe(name)

    def _on_upsert(self, name: str, obj: dict) -> None:
        crd = self.client.create_crd(obj)
        with self._lock:
            old_kind = self._kinds.get(name)
        self.client.add_template(obj)
        if old_kind is not None and old_kind != crd.kind:
            # case-variant kind rename: add_template succeeded, so the
            # retired kind's modules/constraints are unmounted — only now
            # stop watching it and drop its controller-side state (a
            # failed add_template must leave the old kind watched)
            self._retire_kind(old_kind)
        with self._lock:
            self._kinds[name] = crd.kind
        # dynamic watch: constraints of this kind now flow to the
        # constraint controller (constrainttemplate_controller.go:458)
        self.constraint_registrar.add_watch(constraint_gvk(crd.kind))

    def _retire_kind(self, kind: str) -> None:
        self.constraint_registrar.remove_watch(constraint_gvk(kind))
        if self.constraint_controller is not None:
            # remove_watch delivers no DELETED events, so the constraint
            # controller's status/metrics/readiness for the kind must be
            # dropped explicitly
            self.constraint_controller.drop_kind(kind)

    def _on_delete(self, name: str, obj: dict) -> None:
        with self._lock:
            kind = self._kinds.pop(name, None)
        if kind is not None:
            self._retire_kind(kind)
        self.client.remove_template(obj)
        if self.tracker is not None:
            self.tracker.templates.cancel_expect(name)
        if self.status is not None:
            self.status.delete_template(name)

    def _report_count(self) -> None:
        # active = ingested templates without a live error; error = every
        # template whose last reconcile failed (ingested-before or not)
        with self._lock:
            ingested = set(self._kinds)
        errs = set(self.errors)
        self.metrics.gauge(
            "constraint_templates", len(ingested - errs), status="active"
        )
        self.metrics.gauge("constraint_templates", len(errs), status="error")


class ConstraintController:
    def __init__(
        self,
        client,
        tracker: Optional[ReadinessTracker] = None,
        switch: Optional[ControllerSwitch] = None,
        metrics=None,
        status=None,
    ):
        self.client = client
        self.tracker = tracker
        self.switch = switch
        self.metrics = metrics
        self.status = status
        self._lock = threading.Lock()
        self._by_kind: Dict[str, Set[str]] = {}  # kind -> names
        # "Kind/name" -> (enforcement_action, status) for metric series
        self._series: Dict[str, Tuple[str, str]] = {}
        self.errors: Dict[str, str] = {}  # "Kind/name" -> last error

    def sink(self, ev: Event) -> None:
        if self.switch is not None and not self.switch.enter():
            return
        kind = ev.gvk.kind
        meta = ev.obj.get("metadata") or {}
        name = meta.get("name", "")
        key = f"{kind}/{name}"
        ea = (
            (ev.obj.get("spec") or {}).get("enforcementAction") or "deny"
        )
        status = "active"
        try:
            if ev.type == DELETED:
                self.client.remove_constraint(ev.obj)
                with self._lock:
                    self._by_kind.get(kind, set()).discard(name)
                    self._series.pop(key, None)
                if self.tracker is not None:
                    self.tracker.for_constraint_kind(kind).cancel_expect(name)
                if self.status is not None:
                    self.status.delete_constraint(kind, name)
            else:
                self.client.add_constraint(ev.obj)
                with self._lock:
                    self._by_kind.setdefault(kind, set()).add(name)
            self.errors.pop(key, None)
        except Exception as e:
            status = "error"
            self.errors[key] = str(e)
        if ev.type != DELETED:
            with self._lock:
                self._series[key] = (ea, status)
            if self.tracker is not None:
                self.tracker.for_constraint_kind(kind).observe(name)
            if self.status is not None:
                self.status.publish_constraint(
                    kind, name, status, ea, self.errors.get(key)
                )
        self._report_gauges(extras=[(ea, status)])

    def drop_kind(self, kind: str) -> None:
        """Drop all controller-side state for a retired constraint kind
        (template deleted or kind renamed). The kind's watch is already
        gone, so no DELETED events will ever arrive for its constraints —
        status, metric series, and readiness expectations must be cleared
        here or they report the retired constraints as enforced forever."""
        removed: list = []
        with self._lock:
            names = self._by_kind.pop(kind, set())
            for name in names:
                series = self._series.pop(f"{kind}/{name}", None)
                if series is not None:
                    removed.append(series)
        for name in names:
            self.errors.pop(f"{kind}/{name}", None)
            if self.tracker is not None:
                self.tracker.for_constraint_kind(kind).cancel_expect(name)
            if self.status is not None:
                self.status.delete_constraint(kind, name)
        if removed:
            self._report_gauges(extras=removed)

    def _report_gauges(self, extras=()) -> None:
        if self.metrics is None:
            return
        # per-(enforcement_action, status) counts, with removed series
        # reset to 0 so stale totals never linger
        with self._lock:
            counts: Dict[Tuple[str, str], int] = {}
            for s_ea, s_st in self._series.values():
                counts[(s_ea, s_st)] = counts.get((s_ea, s_st), 0) + 1
        for (s_ea, s_st) in {*extras, *counts}:
            self.metrics.gauge(
                "constraints",
                counts.get((s_ea, s_st), 0),
                enforcement_action=s_ea,
                status=s_st,
            )


class MutatorController:
    """Assign / AssignMetadata / ModifySet ingestion: one sink for all
    three mutator GVKs, feeding the MutationSystem (the mutation
    plane's Client). Invalid specs and schema conflicts surface as
    pod-status errors and metrics, never as webhook failures — the
    system quarantines conflicted mutators itself."""

    def __init__(
        self,
        system,
        switch: Optional[ControllerSwitch] = None,
        metrics=None,
        status=None,
        logger=None,
    ):
        from ..logs import null_logger

        self.system = system
        self.switch = switch
        self.metrics = metrics
        self.status = status
        self.log = logger if logger is not None else null_logger()
        self.errors: Dict[str, str] = {}  # "Kind/name" -> last error

    def sink(self, ev: Event) -> None:
        if self.switch is not None and not self.switch.enter():
            return
        kind = ev.gvk.kind
        name = (ev.obj.get("metadata") or {}).get("name", "")
        key = f"{kind}/{name}"
        status = "active"
        t0 = time.perf_counter()
        try:
            if ev.type == DELETED:
                self.system.remove(key)
                self.errors.pop(key, None)
                if self.status is not None:
                    self.status.delete_mutator(kind, name)
            else:
                self.system.upsert(ev.obj)
                self.errors.pop(key, None)
        except Exception as e:
            status = "error"
            self.errors[key] = str(e)
            self.log.error(
                "mutator ingest failed",
                err=e,
                process="controller",
                mutator_kind=kind,
                mutator_name=name,
            )
        if ev.type != DELETED:
            # schema conflicts are computed set-wide on every upsert:
            # re-publish status for the conflicted ids so a conflict
            # introduced by mutator B shows on mutator A's status too
            conflicts = self.system.conflicts()
            err = self.errors.get(key)
            if key in conflicts:
                status = "error"
                err = (
                    "schema conflict with "
                    + ", ".join(conflicts[key])
                )
            if self.status is not None:
                self.status.publish_mutator(kind, name, status, err)
        if self.metrics is not None:
            self.metrics.record(
                "mutator_ingestion_count", 1, status=status
            )
            self.metrics.observe(
                "mutator_ingestion_duration_seconds",
                time.perf_counter() - t0,
                status=status,
            )
        self.system.report_gauges()


class ProviderController:
    """externaldata.gatekeeper.sh/v1alpha1 Provider ingestion: one sink
    feeding the ExternalDataSystem's registry. Invalid specs surface as
    ProviderPodStatus errors and metrics, never as webhook failures —
    an unregistered provider resolves undefined at evaluation time, and
    a registered one degrades per its failurePolicy."""

    def __init__(
        self,
        system,
        switch: Optional[ControllerSwitch] = None,
        metrics=None,
        status=None,
        logger=None,
    ):
        from ..logs import null_logger

        self.system = system
        self.switch = switch
        self.metrics = metrics
        self.status = status
        self.log = logger if logger is not None else null_logger()
        self.errors: Dict[str, str] = {}  # provider name -> last error

    def sink(self, ev: Event) -> None:
        if self.switch is not None and not self.switch.enter():
            return
        name = (ev.obj.get("metadata") or {}).get("name", "")
        status = "active"
        t0 = time.perf_counter()
        try:
            if ev.type == DELETED:
                self.system.remove(name)
                self.errors.pop(name, None)
                if self.status is not None:
                    self.status.delete_provider(name)
            else:
                self.system.upsert(ev.obj)
                self.errors.pop(name, None)
        except Exception as e:
            status = "error"
            self.errors[name] = str(e)
            self.log.error(
                "provider ingest failed",
                err=e,
                process="controller",
                provider_name=name,
            )
        if ev.type != DELETED and self.status is not None:
            provider = self.system.get(name)
            self.status.publish_provider(
                name,
                status,
                self.errors.get(name),
                failure_policy=(
                    provider.failure_policy if provider is not None else None
                ),
            )
        if self.metrics is not None:
            self.metrics.record(
                "provider_ingestion_count", 1, status=status
            )
            self.metrics.observe(
                "provider_ingestion_duration_seconds",
                time.perf_counter() - t0,
                status=status,
            )
        self.system.report_gauges()


class SyncController:
    def __init__(
        self,
        client,
        tracker: Optional[ReadinessTracker] = None,
        switch: Optional[ControllerSwitch] = None,
        metrics=None,
        excluder: Optional[Excluder] = None,
    ):
        self.client = client
        self.tracker = tracker
        self.switch = switch
        self.metrics = metrics
        self.excluder = excluder
        self._lock = threading.Lock()
        self._sync_set: Set[GVK] = set()

    def set_sync_set(self, gvks: Set[GVK]) -> None:
        with self._lock:
            self._sync_set = set(gvks)

    def sink(self, ev: Event) -> None:
        if self.switch is not None and not self.switch.enter():
            return
        with self._lock:
            if ev.gvk not in self._sync_set:
                return  # FilteredDataClient: stale watch events dropped
        meta = ev.obj.get("metadata") or {}
        ns = meta.get("namespace") or ""
        if (
            ns
            and self.excluder is not None
            and self.excluder.is_namespace_excluded("sync", ns)
        ):
            if self.tracker is not None:
                # the boot lister may have expected this object before
                # the excluder was configured — an excluded object must
                # not wedge /readyz
                self.tracker.for_data(str(ev.gvk)).cancel_expect(
                    (ns, meta.get("name") or "")
                )
            return
        t0 = time.perf_counter()
        if ev.type == DELETED:
            self.client.remove_data(ev.obj)
            if self.tracker is not None:
                # deleted-before-observed data must not wedge readiness
                self.tracker.for_data(str(ev.gvk)).cancel_expect(
                    (ns, meta.get("name") or "")
                )
        else:
            self.client.add_data(ev.obj)
            if self.tracker is not None:
                self.tracker.for_data(str(ev.gvk)).observe(
                    (ns, meta.get("name") or "")
                )
        if self.metrics is not None:
            self.metrics.observe(
                "sync_duration_seconds", time.perf_counter() - t0
            )
            self.metrics.record("sync", 1, kind=ev.gvk.kind)
            self.metrics.gauge(
                "sync_last_run_time", time.time(), kind=ev.gvk.kind
            )


class ConfigController:
    """Singleton Config reconcile: excluder + sync set + wipe/replay
    (config_controller.go:183-331)."""

    def __init__(
        self,
        client,
        sync_registrar: Registrar,
        sync_controller: SyncController,
        excluder: Excluder,
        tracker: Optional[ReadinessTracker] = None,
        switch: Optional[ControllerSwitch] = None,
        metrics=None,
        trace_config=None,
        # mutation wipe/replay partners: on a Config change the mutator
        # set is wiped and its watches torn down/re-added so the
        # initial-List replay rebuilds it from the cluster (the same
        # replayData motion the sync plane gets)
        mutation_system=None,
        mutation_registrar: Optional[Registrar] = None,
        # external-data wipe/replay partners: same motion for the
        # provider registry (and its response cache — a Config change
        # must not leave answers from a retired provider set serving)
        external_data_system=None,
        provider_registrar: Optional[Registrar] = None,
    ):
        self.client = client
        self.sync_registrar = sync_registrar
        self.sync_controller = sync_controller
        self.excluder = excluder
        self.tracker = tracker
        self.switch = switch
        self.metrics = metrics
        self.trace_config = trace_config
        self.mutation_system = mutation_system
        self.mutation_registrar = mutation_registrar
        self.external_data_system = external_data_system
        self.provider_registrar = provider_registrar

    def sink(self, ev: Event) -> None:
        if self.switch is not None and not self.switch.enter():
            return
        meta = ev.obj.get("metadata") or {}
        if (meta.get("namespace"), meta.get("name")) != (
            CONFIG_NAMESPACE,
            CONFIG_NAME,
        ):
            return  # only the keyed singleton is honored (keys/config.go)
        spec = {} if ev.type == DELETED else (ev.obj.get("spec") or {})

        # 1. process excluder from spec.match (excluder.go:43) and the
        # admission trace rules from spec.validation.traces
        # (config_types.go:39-51)
        self.excluder.replace(spec.get("match") or [])
        if self.trace_config is not None:
            self.trace_config.replace(
                (spec.get("validation") or {}).get("traces") or []
            )

        # 2. new sync-only set
        sync_only: Set[GVK] = set()
        for entry in ((spec.get("sync") or {}).get("syncOnly") or []):
            sync_only.add(
                GVK(
                    entry.get("group", "") or "",
                    entry.get("version", ""),
                    entry.get("kind", ""),
                )
            )

        # 3. wipe all cached data BEFORE the watch swap so replayed
        # Lists rebuild from scratch (config_controller.go:268)
        self.client.remove_data(WipeData())

        # 4. swap watches; dropping to the empty set first forces every
        # retained GVK's watch to tear down and re-add, so the initial
        # List replay rebuilds the data we just wiped for ALL GVKs in
        # the new set — the reference's replayData re-lists every
        # watched GVK, not only newly-added ones
        # (config_controller.go:294-331)
        self.sync_controller.set_sync_set(sync_only)
        self.sync_registrar.replace_watch(set())
        self.sync_registrar.replace_watch(sync_only)

        # 5. mutation wipe/replay: the process excluder just changed, so
        # the live mutator set is rebuilt from scratch the same way the
        # data cache is — wipe, then bounce the watches so the replayed
        # Lists re-upsert every mutator CR
        if self.mutation_system is not None:
            self.mutation_system.wipe()
            if self.mutation_registrar is not None:
                self.mutation_registrar.replace_watch(set())
                self.mutation_registrar.replace_watch(set(MUTATOR_GVKS))

        # 6. external-data wipe/replay: the provider registry (and its
        # response cache) rebuilds from the cluster the same way — the
        # bounced watch's initial List re-upserts every Provider CR
        if self.external_data_system is not None:
            self.external_data_system.wipe()
            if self.provider_registrar is not None:
                self.provider_registrar.replace_watch(set())
                self.provider_registrar.replace_watch({PROVIDER_GVK})

        if self.tracker is not None:
            self.tracker.config.observe((CONFIG_NAMESPACE, CONFIG_NAME))
        if self.metrics is not None:
            self.metrics.gauge("sync_gvk_count", len(sync_only))
