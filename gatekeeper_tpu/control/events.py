"""Event fabric: the K8s-API-server stand-in the controllers watch.

The reference's control fabric is the Kubernetes API server — all state
arrives via client-go informers (list+watch per GVK) multiplexed by
pkg/watch and the forked dynamiccache (SURVEY §1 "control/data planes").
This module provides the same contract behind one small interface so the
control plane runs identically against a fake in-memory cluster (tests,
standalone benchmarking) or a real apiserver adapter:

  * `list(gvk)` — current objects of a kind (informer initial List);
  * `subscribe(gvk, sink)` — ADDED/MODIFIED/DELETED events from now on
    (informer Watch); returns an unsubscribe handle;
  * `apply(obj)` / `delete(obj)` — writes (tests / demo drivers).

`FakeCluster` is the in-memory implementation — the moral equivalent of
envtest's local apiserver in the reference's integration tests
(constrainttemplate_controller_suite_test.go:44-66): real list+watch
semantics, no network.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


class GVK(NamedTuple):
    """group/version/Kind key (pkg/watch keys watches by schema.GVK)."""

    group: str
    version: str
    kind: str

    @classmethod
    def from_obj(cls, obj: Dict[str, Any]) -> "GVK":
        api_version = obj.get("apiVersion", "")
        group, _, version = api_version.rpartition("/")
        return cls(group, version, obj.get("kind", ""))

    @classmethod
    def parse(cls, s: str) -> "GVK":
        """"group/version/Kind" or "version/Kind" (core group)."""
        parts = s.split("/")
        if len(parts) == 2:
            return cls("", parts[0], parts[1])
        if len(parts) == 3:
            return cls(parts[0], parts[1], parts[2])
        raise ValueError(f"bad GVK string: {s!r}")

    @property
    def api_version(self) -> str:
        return f"{self.group}/{self.version}" if self.group else self.version

    def __str__(self) -> str:
        return f"{self.api_version}/{self.kind}"


def obj_key(obj: Dict[str, Any]) -> Tuple[str, str]:
    meta = obj.get("metadata") or {}
    return (meta.get("namespace") or "", meta.get("name") or "")


@dataclass(frozen=True)
class Event:
    type: str  # ADDED | MODIFIED | DELETED
    gvk: GVK
    obj: Dict[str, Any]


EventSink = Callable[[Event], None]


class Conflict(Exception):
    """create() lost a create race: the object already exists. The
    fleet plane's load-or-create motions (Secret-backed cert store)
    catch this to adopt the winner's state instead of overwriting it —
    the apiserver's 409 on POST, surfaced identically by the fake."""


class EventSource:
    """The list+watch contract (client-go informer surface)."""

    def list(self, gvk: GVK) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def subscribe(self, gvk: GVK, sink: EventSink) -> Callable[[], None]:
        """Start streaming events for `gvk` to `sink`; returns an
        unsubscribe callable. No initial List replay — callers pair this
        with list() themselves (the watch manager does)."""
        raise NotImplementedError


class FakeCluster(EventSource):
    """In-memory cluster: object store + watch fan-out per GVK."""

    def __init__(self):
        self._lock = threading.RLock()
        self._objs: Dict[GVK, Dict[Tuple[str, str], Dict[str, Any]]] = {}
        self._subs: Dict[GVK, List[Tuple[int, EventSink]]] = {}
        self._next_sub = 0

    # -- reads ---------------------------------------------------------------

    def list(self, gvk: GVK) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._objs.get(gvk, {}).values())

    def get(self, gvk: GVK, namespace: str, name: str) -> Optional[dict]:
        with self._lock:
            return self._objs.get(gvk, {}).get((namespace or "", name))

    def subscribe(self, gvk: GVK, sink: EventSink) -> Callable[[], None]:
        with self._lock:
            sid = self._next_sub
            self._next_sub += 1
            self._subs.setdefault(gvk, []).append((sid, sink))

        def unsubscribe() -> None:
            with self._lock:
                subs = self._subs.get(gvk, [])
                self._subs[gvk] = [(i, s) for i, s in subs if i != sid]

        return unsubscribe

    # -- writes (test/demo surface) ------------------------------------------

    def apply(self, obj: Dict[str, Any]) -> None:
        gvk = GVK.from_obj(obj)
        key = obj_key(obj)
        with self._lock:
            store = self._objs.setdefault(gvk, {})
            etype = MODIFIED if key in store else ADDED
            store[key] = obj
            sinks = [s for _, s in self._subs.get(gvk, [])]
        ev = Event(etype, gvk, obj)
        for s in sinks:
            s(ev)

    def create(self, obj: Dict[str, Any]) -> None:
        """Create-ONLY write: raises `Conflict` when the object already
        exists (the apiserver's 409 on POST). Unlike apply(), two racing
        creators cannot both win — the loser must re-read the winner's
        object, which is exactly the load-or-create contract the fleet
        cert store builds on (certs.go:119-181)."""
        gvk = GVK.from_obj(obj)
        key = obj_key(obj)
        with self._lock:
            store = self._objs.setdefault(gvk, {})
            if key in store:
                raise Conflict(f"{gvk}/{key[0]}/{key[1]} already exists")
            store[key] = obj
            sinks = [s for _, s in self._subs.get(gvk, [])]
        ev = Event(ADDED, gvk, obj)
        for s in sinks:
            s(ev)

    def delete(self, obj_or_gvk, namespace: str = "", name: str = "") -> bool:
        if isinstance(obj_or_gvk, GVK):
            gvk = obj_or_gvk
            key = (namespace or "", name)
        else:
            gvk = GVK.from_obj(obj_or_gvk)
            key = obj_key(obj_or_gvk)
        with self._lock:
            store = self._objs.get(gvk, {})
            obj = store.pop(key, None)
            if obj is None:
                return False
            sinks = [s for _, s in self._subs.get(gvk, [])]
        ev = Event(DELETED, gvk, obj)
        for s in sinks:
            s(ev)
        return True

    def known_gvks(self) -> List[GVK]:
        with self._lock:
            return [g for g, store in self._objs.items() if store]
