"""Process excluder: per-process namespace exclusion lists.

Mirrors pkg/controller/config/process/excluder.go: the Config CRD's
spec.match entries name processes ({audit, sync, webhook, *}) and
namespaces to exclude from them (excluder.go:12-17,43-79); `*` expands
to every process (excluder.go:60-66).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Set

PROCESS_AUDIT = "audit"
PROCESS_SYNC = "sync"
PROCESS_WEBHOOK = "webhook"
PROCESS_STAR = "*"

_ALL = (PROCESS_AUDIT, PROCESS_SYNC, PROCESS_WEBHOOK)


class Excluder:
    def __init__(self):
        self._lock = threading.Lock()
        self._excluded: Dict[str, Set[str]] = {p: set() for p in _ALL}

    def add(self, match_entries: Iterable[dict]) -> None:
        """Ingest Config spec.match entries:
        [{"processes": [...], "excludedNamespaces": [...]}]."""
        with self._lock:
            for entry in match_entries or []:
                processes = entry.get("processes") or []
                namespaces = entry.get("excludedNamespaces") or []
                targets: Set[str] = set()
                for p in processes:
                    if p == PROCESS_STAR:
                        targets.update(_ALL)
                    elif p in self._excluded:
                        targets.add(p)
                for p in targets:
                    self._excluded[p].update(
                        ns for ns in namespaces if isinstance(ns, str)
                    )

    def replace(self, match_entries: Iterable[dict]) -> None:
        """Swap in a new exclusion config atomically (the config
        controller rebuilds the excluder on every Config change)."""
        fresh = Excluder()
        fresh.add(match_entries)
        with self._lock:
            self._excluded = fresh._excluded

    def is_namespace_excluded(self, process: str, namespace: str) -> bool:
        with self._lock:
            return namespace in self._excluded.get(process, set())

    def equals(self, other: "Excluder") -> bool:
        with self._lock:
            mine = {p: set(s) for p, s in self._excluded.items()}
        with other._lock:
            theirs = {p: set(s) for p, s in other._excluded.items()}
        return mine == theirs
