"""Stored-version upgrade manager.

Mirrors pkg/upgrade/manager.go (:80 `upgrade`, :94 `upgradeGroupVersion`):
on process start, every gatekeeper object still stored at a deprecated
API version is touched with a no-op update so the store re-serializes it
at the preferred version. The reference walks
`constraints.gatekeeper.sh/v1alpha1` and `templates.gatekeeper.sh/
v1alpha1` via the discovery client and issues empty updates; here the
cluster abstraction re-applies each object at the preferred version and
removes the deprecated-version entry (the FakeCluster keys objects by
GVK, so a version bump is a move).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from .events import GVK

# (group, deprecated version) -> preferred version
UPGRADE_GROUPS: Dict[Tuple[str, str], str] = {
    ("templates.gatekeeper.sh", "v1alpha1"): "v1beta1",
    ("constraints.gatekeeper.sh", "v1alpha1"): "v1beta1",
}


class UpgradeManager:
    def __init__(self, cluster):
        self.cluster = cluster
        self.upgraded: List[str] = []

    def upgrade(self) -> int:
        """Migrate every object of the deprecated group-versions to the
        preferred version; returns the number migrated."""
        n = 0
        for gvk in list(self.cluster.known_gvks()):
            preferred = UPGRADE_GROUPS.get((gvk.group, gvk.version))
            if preferred is None:
                continue
            pref_gvk = GVK(gvk.group, preferred, gvk.kind)
            for obj in list(self.cluster.list(gvk)):
                meta = obj.get("metadata") or {}
                ns = meta.get("namespace") or ""
                name = meta.get("name") or ""
                # never clobber an object already stored at the
                # preferred version — it is newer by definition; just
                # drop the stale deprecated copy
                if self.cluster.get(pref_gvk, ns, name) is None:
                    new = dict(obj)
                    new["apiVersion"] = f"{gvk.group}/{preferred}"
                    self.cluster.apply(new)
                self.cluster.delete(gvk, ns, name)
                self.upgraded.append(
                    f"{gvk}/{meta.get('name', '')}"
                )
                n += 1
        return n
