"""Readiness tracker: the boot-time barrier.

Mirrors pkg/readiness/ready_tracker.go + object_tracker.go: at startup
the expected templates/constraints/config/data objects are registered as
expectations; ingestion paths call observe() as state lands in the
driver; the process reports Ready only when every expectation is
satisfied. Satisfaction is a one-way circuit breaker
(ready_tracker.go:138-173) — once satisfied, later churn never flips it
back.
"""

from __future__ import annotations

import threading
from typing import Dict, Hashable, Set


class ObjectTracker:
    """Expectations for one class of objects (object_tracker.go:36-213)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._expected: Set[Hashable] = set()
        self._observed: Set[Hashable] = set()
        self._populated = False
        self._satisfied = False

    def expect(self, key: Hashable) -> None:
        with self._lock:
            if self._satisfied:
                return
            self._expected.add(key)

    def cancel_expect(self, key: Hashable) -> None:
        """Deleted-before-observed objects stop blocking readiness."""
        with self._lock:
            if self._satisfied:
                return
            self._expected.discard(key)
            self._observed.discard(key)

    def observe(self, key: Hashable) -> None:
        with self._lock:
            if self._satisfied:
                return
            self._observed.add(key)

    def expectations_done(self) -> None:
        """Population phase over: the expected set is final."""
        with self._lock:
            self._populated = True

    def satisfied(self) -> bool:
        with self._lock:
            if self._satisfied:
                return True
            if self._populated and self._expected <= self._observed:
                self._satisfied = True  # one-way circuit breaker
                return True
            return False

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "expected": len(self._expected),
                "observed": len(self._observed & self._expected),
            }


class ReadinessTracker:
    """Aggregated readiness across templates, constraints (per kind),
    config, and synced data (per GVK) — ready_tracker.go:53-173."""

    def __init__(self):
        self.templates = ObjectTracker()
        self.config = ObjectTracker()
        self._lock = threading.Lock()
        self._constraints: Dict[str, ObjectTracker] = {}
        self._data: Dict[str, ObjectTracker] = {}
        # named subsystem trackers (lazy — only gate readiness once
        # requested): the fleet plane registers under "fleet" so a
        # replica is not Ready before the shared cert store resolved
        # and the state plane synced (docs/fleet.md)
        self._components: Dict[str, ObjectTracker] = {}

    def for_constraint_kind(self, kind: str) -> ObjectTracker:
        with self._lock:
            t = self._constraints.get(kind)
            if t is None:
                t = self._constraints[kind] = ObjectTracker()
            return t

    def for_data(self, gvk: str) -> ObjectTracker:
        with self._lock:
            t = self._data.get(gvk)
            if t is None:
                t = self._data[gvk] = ObjectTracker()
            return t

    def for_component(self, name: str) -> ObjectTracker:
        with self._lock:
            t = self._components.get(name)
            if t is None:
                t = self._components[name] = ObjectTracker()
            return t

    def satisfied(self) -> bool:
        with self._lock:
            trackers = (
                [self.templates, self.config]
                + list(self._constraints.values())
                + list(self._data.values())
                + list(self._components.values())
            )
        return all(t.satisfied() for t in trackers)

    def stats(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            out = {
                "templates": self.templates.stats(),
                "config": self.config.stats(),
            }
            for k, t in self._constraints.items():
                out[f"constraint/{k}"] = t.stats()
            for k, t in self._data.items():
                out[f"data/{k}"] = t.stats()
            for k, t in self._components.items():
                out[f"component/{k}"] = t.stats()
        return out
