"""TTL response cache with negative caching + stale-while-revalidate.

The cache is what turns external data from a per-request RPC into a
batch-plane concern: per micro-batch the system classifies every
deduped key against this cache, fetches ONLY the misses in one outbound
call, and serves everything else from memory. Three entry classes:

  * positive (value, `cache_ttl_s`) — a provider answer for a key;
  * negative (error, `negative_ttl_s`) — the provider *said* the key is
    bad (unsigned image, unknown record); caching the error keeps a
    storm of failing admissions from refetching the same doomed key
    every batch;
  * stale (expired positive within `stale_ttl_s`) — served immediately
    while the batch's single fetch revalidates it; if the fetch fails,
    the stale value still answers (counted as a stale-serve).

The clock is injectable so TTL/stale windows are testable without
sleeping.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

# classification outcomes (also the cache_lookups_total result tag)
HIT = "hit"
NEGATIVE_HIT = "negative_hit"
STALE = "stale"
MISS = "miss"


@dataclass
class Entry:
    value: Any = None
    error: Optional[str] = None  # set => negative entry
    fetched_at: float = 0.0
    ttl: float = 0.0
    stale_ttl: float = 0.0
    # "" = this process fetched it; a replica id = adopted from that
    # peer via the fleet plane. Only local-origin entries are published
    # (docs/fleet.md) — otherwise two replicas would echo each other's
    # entries back and forth forever.
    origin: str = ""

    def state(self, now: float) -> str:
        age = now - self.fetched_at
        if self.error is not None:
            return NEGATIVE_HIT if age < self.ttl else MISS
        if age < self.ttl:
            return HIT
        if age < self.ttl + self.stale_ttl:
            return STALE
        return MISS


class ResponseCache:
    """Per-(provider, key) entry store. Thread-safe; bounded
    (`max_entries`) with true LRU eviction — reads refresh recency, so
    a soak's hot key set survives while a high-cardinality cold tail is
    what gets evicted; a run can never grow this map without bound.
    Evictions are counted (`evictions`, and
    `externaldata_cache_evictions_total` when metrics are wired) so a
    leak check can tell "bounded and churning" from "growing"."""

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        max_entries: int = 65536,
        metrics=None,
    ):
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        self._clock = clock
        self.max_entries = max_entries
        self.metrics = metrics
        self._lock = threading.Lock()
        # ordered oldest-access-first: the LRU order
        self._entries: "OrderedDict[Tuple[str, str], Entry]" = OrderedDict()
        self.evictions = 0  # lifetime count (soak leak evidence)
        # bumped on every write: lets consumers key derived state (e.g.
        # precomputed row-feature bits) on cache content
        self.generation = 0

    def now(self) -> float:
        return self._clock()

    # -- reads ---------------------------------------------------------------

    def classify(
        self, provider: str, keys: List[str], now: Optional[float] = None
    ) -> Dict[str, Tuple[str, Optional[Entry]]]:
        """{key -> (state, entry|None)} for a key list, one lock hold."""
        if now is None:
            now = self._clock()
        out: Dict[str, Tuple[str, Optional[Entry]]] = {}
        with self._lock:
            for k in keys:
                e = self._entries.get((provider, k))
                if e is None:
                    out[k] = (MISS, None)
                else:
                    # LRU touch: a read of a live entry refreshes its
                    # recency so the hot working set outlives cold tails
                    self._entries.move_to_end((provider, k))
                    out[k] = (e.state(now), e)
        return out

    # -- writes --------------------------------------------------------------

    def put(
        self,
        provider: str,
        key: str,
        value: Any = None,
        error: Optional[str] = None,
        ttl: float = 0.0,
        stale_ttl: float = 0.0,
    ) -> None:
        with self._lock:
            self._entries[(provider, key)] = Entry(
                value=value,
                error=error,
                fetched_at=self._clock(),
                ttl=ttl,
                stale_ttl=stale_ttl,
            )
            self._entries.move_to_end((provider, key))
            self.generation += 1
            if len(self._entries) > self.max_entries:
                self._evict_locked()

    def _evict_locked(self) -> None:
        # pop least-recently-used until back at the bound; counted per
        # provider so an eviction storm names the key space causing it
        by_provider: Dict[str, int] = {}
        while len(self._entries) > self.max_entries:
            (prov, _k), _e = self._entries.popitem(last=False)
            self.evictions += 1
            by_provider[prov] = by_provider.get(prov, 0) + 1
        if self.metrics is not None:
            for prov, n in by_provider.items():
                self.metrics.record(
                    "externaldata_cache_evictions_total", n, provider=prov
                )

    # -- fleet sync (docs/fleet.md) ------------------------------------------

    def export_fresh(self, max_entries: int = 512) -> List[Dict[str, Any]]:
        """Local-origin, still-live entries as publishable records.
        Ages are relative (`age_s`) because replicas do not share a
        clock epoch — the merging side re-anchors against its own
        clock, preserving the TTL / negative / stale-while-revalidate
        windows exactly. Newest first, capped at `max_entries` (the
        shared-state CR must stay bounded; the tail is the oldest and
        closest to expiry anyway)."""
        with self._lock:
            now = self._clock()
            out = []
            for (p, k), e in self._entries.items():
                if e.origin:
                    continue
                if e.state(now) == MISS:
                    continue  # nothing live to share
                out.append(
                    {
                        "provider": p,
                        "key": k,
                        "value": e.value,
                        "error": e.error,
                        "age_s": round(now - e.fetched_at, 3),
                        "ttl": e.ttl,
                        "stale_ttl": e.stale_ttl,
                    }
                )
        out.sort(key=lambda r: r["age_s"])
        return out[:max_entries]

    def merge(self, record: Dict[str, Any], origin: str) -> bool:
        """Adopt a peer-published record iff it is fresher than what we
        hold (by effective fetch time under OUR clock). Expired records
        and stale-er-than-ours records are dropped; adopted entries keep
        the publisher's TTL windows and carry its replica id as origin
        so they are never re-published from here. Returns True when the
        entry was adopted."""
        provider = str(record.get("provider") or "")
        key = str(record.get("key") or "")
        if not provider or not key:
            return False
        ttl = float(record.get("ttl") or 0.0)
        stale_ttl = float(record.get("stale_ttl") or 0.0)
        age_s = max(0.0, float(record.get("age_s") or 0.0))
        if age_s >= ttl + stale_ttl:
            return False  # dead on arrival
        with self._lock:
            now = self._clock()
            fetched_at = now - age_s
            cur = self._entries.get((provider, key))
            if cur is not None and cur.fetched_at >= fetched_at:
                return False  # ours is as fresh or fresher
            self._entries[(provider, key)] = Entry(
                value=record.get("value"),
                error=record.get("error"),
                fetched_at=fetched_at,
                ttl=ttl,
                stale_ttl=stale_ttl,
                origin=origin,
            )
            self._entries.move_to_end((provider, key))
            self.generation += 1
            if len(self._entries) > self.max_entries:
                self._evict_locked()
        return True

    def drop_provider(self, provider: str) -> None:
        """Invalidate every entry of a provider (spec change/removal —
        a new URL must not serve the old endpoint's answers)."""
        with self._lock:
            for k in [k for k in self._entries if k[0] == provider]:
                del self._entries[k]
            self.generation += 1

    def wipe(self) -> None:
        with self._lock:
            self._entries.clear()
            self.generation += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
