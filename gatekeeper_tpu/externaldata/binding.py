"""Process-level binding from the `external_data` Rego builtin to the
live ExternalDataSystem.

The interpreter's builtin table is stateless functions; external_data
needs the provider registry + cache. The Runner binds its system here
at boot (one system per process, like the faults registry); tests that
need isolation either rebind or use the `use_system` thread-local
override so parallel suites cannot cross-talk.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Optional

_lock = threading.Lock()
_system: Optional[Any] = None
_local = threading.local()


def set_system(system: Optional[Any]) -> None:
    """Bind the process-wide system (None unbinds)."""
    global _system
    with _lock:
        _system = system


def get_system() -> Optional[Any]:
    override = getattr(_local, "system", None)
    if override is not None:
        return override
    with _lock:
        return _system


@contextmanager
def use_system(system: Any):
    """Thread-local override for the duration of a with-block."""
    prev = getattr(_local, "system", None)
    _local.system = system
    try:
        yield system
    finally:
        _local.system = prev
