"""ExternalDataSystem: the batch plane for out-of-band lookups.

One system per process holds the Provider registry, the TTL response
cache, a per-provider circuit breaker, and the HTTP fetcher. The design
invariant — enforced by tests/test_externaldata.py — is that lookups
ride the micro-batch, not break it:

  * per batch, callers dedupe keys across every pending request and
    call `prefetch()` once; the system issues at most ONE outbound
    fetch per (provider, batch) covering all cold misses (stale keys
    ride along for revalidation);
  * repeat keys answer from the cache (positive, negative, or
    stale-while-revalidate entries — cache.py);
  * `resolve()` (the `external_data` builtin's entry) then serves
    purely from memory in the common case; a provider whose batch fetch
    already failed this epoch is NOT refetched per request — failure
    semantics follow the provider's failurePolicy instead:
      - fail-open: missing keys silently resolve to nothing and the
        response carries `system_error` (error-gated templates allow);
      - fail-closed: missing keys resolve to per-key errors
        (error-gated templates deny with the provider error in the
        admission message — the fail-closed webhook envelope).

Robustness reuses the PR-4 toolkit wholesale: `faults.CircuitBreaker`
per provider (CLOSED→OPEN→HALF_OPEN with probe fetches), named
injection points `externaldata.fetch` / `externaldata.cache`, and the
injectable clock threading through cache TTLs and breaker recovery.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ..faults import CircuitBreaker, fire
from .cache import HIT, MISS, NEGATIVE_HIT, STALE, ResponseCache
from .provider import (
    EXTERNALDATA_GROUP,
    EXTERNALDATA_VERSION,
    Provider,
    ProviderError,
    provider_from_obj,
)


class UnknownProviderError(KeyError):
    """external_data named a provider that is not registered — the
    builtin surfaces this as an undefined expression (plus a counted
    metric) so a typo'd provider is visible without denying the world."""


class _BreakerMetricsShim:
    """Renames the breaker's device_breaker_* series to the provider
    plane's externaldata_breaker_* (tagged by provider) so provider
    outages never masquerade as device failures on a dashboard."""

    def __init__(self, metrics, provider: str):
        self._m = metrics
        self._p = provider

    def record(self, name: str, value, **tags) -> None:
        tags.pop("plane", None)
        if name == "device_breaker_transitions_total":
            self._m.record(
                "externaldata_breaker_transitions_total", value,
                provider=self._p, **tags,
            )
        elif name == "device_breaker_probes_total":
            self._m.record(
                "externaldata_breaker_probes_total", value,
                provider=self._p, **tags,
            )

    def gauge(self, name: str, value, **tags) -> None:
        if name == "device_breaker_state":
            self._m.gauge(
                "externaldata_breaker_state", value, provider=self._p
            )


class HttpFetcher:
    """Stdlib ProviderRequest/ProviderResponse POST client."""

    def fetch(
        self, provider: Provider, keys: List[str]
    ) -> Tuple[List[Dict[str, Any]], str]:
        """-> (items, system_error). Raises on transport errors."""
        body = json.dumps(
            {
                "apiVersion": f"{EXTERNALDATA_GROUP}/{EXTERNALDATA_VERSION}",
                "kind": "ProviderRequest",
                "request": {"keys": list(keys)},
            }
        ).encode()
        req = urllib.request.Request(
            provider.url,
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(
            req, timeout=provider.timeout_s
        ) as resp:
            payload = json.loads(resp.read().decode())
        response = (payload or {}).get("response") or {}
        items = response.get("items") or []
        if not isinstance(items, list):
            raise ValueError("provider returned malformed items")
        return items, str(response.get("systemError") or "")


class ExternalDataSystem:
    """Provider registry + batch-plane lookup engine."""

    def __init__(
        self,
        metrics=None,
        tracer=None,
        logger=None,
        fetcher=None,
        clock: Callable[[], float] = time.monotonic,
        breaker_threshold: int = 3,
        breaker_recovery_s: float = 30.0,
        # response-cache bound (LRU; docs/externaldata.md): a soak
        # against a high-cardinality key space must evict, never grow
        cache_max_entries: int = 65536,
    ):
        from ..logs import null_logger

        self.metrics = metrics
        self.tracer = tracer
        self.log = logger if logger is not None else null_logger()
        self.fetcher = fetcher if fetcher is not None else HttpFetcher()
        self._clock = clock
        self.breaker_threshold = breaker_threshold
        self.breaker_recovery_s = breaker_recovery_s
        self.cache = ResponseCache(
            clock=clock, max_entries=cache_max_entries, metrics=metrics
        )
        self._lock = threading.Lock()
        self._providers: Dict[str, Provider] = {}
        self._breakers: Dict[str, CircuitBreaker] = {}
        # batch-epoch bookkeeping: a provider whose fetch failed in the
        # current epoch is not refetched until the next begin_batch() —
        # the one-fetch-per-(provider, batch) contract holds under
        # failure too (a flapping endpoint must not be hammered once
        # per flagged row)
        self._epoch = 0
        self._failed_epoch: Dict[str, Tuple[int, str]] = {}
        # stale-while-revalidate: at most one background refresh
        # in-flight per provider
        self._refreshing: Set[str] = set()
        self.fetch_count = 0  # lifetime outbound fetches (tests/bench)
        self.stale_serves = 0
        # fleet.FleetPlane when attached: fresh cache entries publish to
        # peers and per-provider breakers gossip (docs/fleet.md)
        self.fleet = None

    # -- fleet plane (docs/fleet.md) ------------------------------------------

    def set_fleet(self, plane) -> None:
        """Attach the fleet state plane: cache fills wake its publisher
        and every per-provider breaker (current and future) gossips
        trips under `provider:<name>`."""
        self.fleet = plane
        with self._lock:
            breakers = list(self._breakers.items())
        for name, breaker in breakers:
            plane.register_breaker(f"provider:{name}", breaker)

    # -- registry ------------------------------------------------------------

    def upsert(self, obj: Dict[str, Any]) -> Provider:
        p = provider_from_obj(obj)
        new_breaker = None
        with self._lock:
            old = self._providers.get(p.name)
            self._providers[p.name] = p
            if p.name not in self._breakers:
                new_breaker = self._breakers[p.name] = CircuitBreaker(
                    failure_threshold=self.breaker_threshold,
                    recovery_seconds=self.breaker_recovery_s,
                    plane="externaldata",
                    name=f"provider:{p.name}",
                    metrics=(
                        _BreakerMetricsShim(self.metrics, p.name)
                        if self.metrics is not None
                        else None
                    ),
                    tracer=self.tracer,
                    clock=self._clock,
                )
        if new_breaker is not None and self.fleet is not None:
            self.fleet.register_breaker(f"provider:{p.name}", new_breaker)
        if old is not None and old.raw.get("spec") != p.raw.get("spec"):
            # a changed spec (new URL, new TTLs) must not keep serving
            # the old endpoint's cached answers
            self.cache.drop_provider(p.name)
        self.report_gauges()
        return p

    def remove(self, name: str) -> None:
        with self._lock:
            self._providers.pop(name, None)
            self._breakers.pop(name, None)
            self._failed_epoch.pop(name, None)
        if self.fleet is not None:
            self.fleet.unregister_breaker(f"provider:{name}")
        self.cache.drop_provider(name)
        self.report_gauges()

    def wipe(self) -> None:
        """Config wipe/replay partner (the control plane's replayData
        motion): drop every provider; the bounced watches re-upsert."""
        with self._lock:
            names = list(self._breakers)
            self._providers.clear()
            self._breakers.clear()
            self._failed_epoch.clear()
        if self.fleet is not None:
            for name in names:
                self.fleet.unregister_breaker(f"provider:{name}")
        self.cache.wipe()
        self.report_gauges()

    def get(self, name: str) -> Optional[Provider]:
        with self._lock:
            return self._providers.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._providers)

    def breaker(self, name: str) -> Optional[CircuitBreaker]:
        with self._lock:
            return self._breakers.get(name)

    # -- batch plane ---------------------------------------------------------

    def begin_batch(self) -> None:
        """Open a new micro-batch epoch: a provider that failed last
        epoch becomes fetchable again (exactly once)."""
        with self._lock:
            self._epoch += 1

    def prefetch(self, wants: Dict[str, Set[str]]) -> None:
        """The batch plane's entry: {provider -> deduped keys} for one
        micro-batch. Issues at most one outbound fetch per provider
        (cold misses + stale revalidations); never raises — failures
        are recorded for resolve() to answer per failurePolicy."""
        for name, keys in wants.items():
            p = self.get(name)
            if p is None or not keys:
                continue
            self._ensure_fetched(p, sorted(keys))

    def _classify(self, p: Provider, keys: List[str]):
        fire("externaldata.cache")
        states = self.cache.classify(p.name, keys)
        if self.metrics is not None:
            by_state: Dict[str, int] = {}
            for st, _ in states.values():
                by_state[st] = by_state.get(st, 0) + 1
            for st, n in by_state.items():
                self.metrics.record(
                    "externaldata_cache_lookups_total", n,
                    provider=p.name, result=st,
                )
        return states

    def _ensure_fetched(self, p: Provider, keys: List[str]) -> None:
        """Fetch whatever this key set needs, within the epoch budget:
        cold misses fetch synchronously (the batch depends on them);
        stale-only refreshes revalidate in the background while the
        stale values serve the batch now."""
        states = self._classify(p, keys)
        misses = [k for k, (st, _) in states.items() if st == MISS]
        stale = [k for k, (st, _) in states.items() if st == STALE]
        if misses:
            with self._lock:
                failed = self._failed_epoch.get(p.name)
                if failed is not None and failed[0] == self._epoch:
                    return  # this batch already paid the failure
            # one outbound fetch covers the misses AND revalidates any
            # stale keys — they're on the wire anyway
            self._fetch(p, sorted(set(misses) | set(stale)))
        elif stale:
            self._refresh_async(p, sorted(stale))

    def _refresh_async(self, p: Provider, keys: List[str]) -> None:
        with self._lock:
            if p.name in self._refreshing:
                return
            self._refreshing.add(p.name)

        def run():
            try:
                self._fetch(p, keys)
            finally:
                with self._lock:
                    self._refreshing.discard(p.name)

        threading.Thread(
            target=run, name=f"gk-extdata-refresh-{p.name}", daemon=True
        ).start()

    def _fetch(self, p: Provider, keys: List[str]) -> bool:
        """One outbound ProviderRequest; populates the cache. Returns
        True on success, records the failure epoch otherwise."""
        from ..obs import start_span

        breaker = self.breaker(p.name)
        if breaker is not None and not breaker.allow():
            self._note_failure(p, "circuit breaker open")
            return False
        keys = keys[: p.max_keys]
        t0 = time.perf_counter()
        try:
            fire("externaldata.fetch")
            with start_span(
                self.tracer, "external_fetch",
                provider=p.name, keys=len(keys),
            ):
                items, system_error = self.fetcher.fetch(p, keys)
            if system_error:
                raise RuntimeError(f"provider systemError: {system_error}")
        except Exception as e:
            if breaker is not None:
                breaker.record_failure()
            if self.metrics is not None:
                self.metrics.record(
                    "externaldata_fetches_total", 1,
                    provider=p.name, result="error",
                )
                self.metrics.observe(
                    "externaldata_fetch_seconds",
                    time.perf_counter() - t0,
                    provider=p.name, result="error",
                )
            self._note_failure(p, str(e))
            self.log.error(
                "external data fetch failed",
                process="externaldata",
                provider=p.name,
                keys=len(keys),
                err=e,
            )
            return False
        if breaker is not None:
            breaker.record_success()
        with self._lock:
            self.fetch_count += 1
            self._failed_epoch.pop(p.name, None)
        by_key = {}
        for item in items:
            if isinstance(item, dict) and "key" in item:
                by_key[str(item["key"])] = item
        for k in keys:
            item = by_key.get(k)
            if item is None:
                # the provider contract is an item per requested key; a
                # silent omission is cached as an error (negative) so it
                # cannot flap between miss-and-refetch every batch
                self.cache.put(
                    p.name, k,
                    error="provider returned no entry for key",
                    ttl=p.negative_ttl_s,
                )
            elif item.get("error"):
                self.cache.put(
                    p.name, k,
                    error=str(item["error"]),
                    ttl=p.negative_ttl_s,
                )
            else:
                self.cache.put(
                    p.name, k,
                    value=item.get("value"),
                    ttl=p.cache_ttl_s,
                    stale_ttl=p.stale_ttl_s,
                )
        if self.metrics is not None:
            self.metrics.record(
                "externaldata_fetches_total", 1,
                provider=p.name, result="ok",
            )
            self.metrics.observe(
                "externaldata_fetch_seconds",
                time.perf_counter() - t0,
                provider=p.name, result="ok",
            )
            self.metrics.observe(
                "externaldata_batch_keys", len(keys), provider=p.name
            )
        if self.fleet is not None:
            # freshly fetched entries are publishable: wake the fleet
            # publisher so peers stop paying this cold fetch
            self.fleet.notify_cache_update()
        return True

    def _note_failure(self, p: Provider, err: str) -> None:
        with self._lock:
            self._failed_epoch[p.name] = (self._epoch, err)

    # -- resolution (the builtin's entry) -------------------------------------

    def probe_clean(self, provider_name: str, key: str) -> bool:
        """Row-feature probe: True iff the key is a usable NON-error
        cache entry (fresh hit or stale-serveable). The fused screen's
        per-row bit is `not all(probe_clean)` — sound for error-gated
        templates because a clean key can never contribute an error
        entry to the resolved response."""
        p = self.get(provider_name)
        if p is None:
            return False
        st, _ = self.cache.classify(p.name, [key])[key]
        return st in (HIT, STALE)

    def resolve(self, provider_name: str, keys: List[str]) -> Dict[str, Any]:
        """Serve one external_data call. Cache-first; leftover misses
        fetch at most once per (provider, epoch); failures answer per
        the provider's failurePolicy. Returns the upstream response
        shape: {responses, errors, status_code, system_error}."""
        from ..obs import start_span

        p = self.get(provider_name)
        if p is None:
            if self.metrics is not None:
                self.metrics.record(
                    "externaldata_requests_total", 1,
                    provider=provider_name, result="unknown_provider",
                )
            raise UnknownProviderError(
                f"external data provider {provider_name!r} is not "
                "registered"
            )
        keys = sorted(set(str(k) for k in keys))
        with start_span(
            self.tracer, "cache_lookup", provider=p.name, keys=len(keys)
        ):
            states = self._classify(p, keys)
        if any(st in (MISS, STALE) for st, _ in states.values()):
            # misses fetch synchronously (the answer depends on them);
            # stale-only key sets revalidate in the background while
            # the stale values serve below
            self._ensure_fetched(p, keys)
            states = self.cache.classify(p.name, keys)
        responses: List[List[Any]] = []
        errors: List[List[str]] = []
        system_error = ""
        result = "ok"
        with self._lock:
            failed = self._failed_epoch.get(p.name)
            fetch_err = (
                failed[1]
                if failed is not None and failed[0] == self._epoch
                else None
            )
        for k in keys:
            st, entry = states[k]
            if st == HIT:
                responses.append([k, entry.value])
            elif st == STALE:
                # stale-while-revalidate: the value answers now; the
                # revalidation already rode this batch's fetch (or a
                # background refresh)
                responses.append([k, entry.value])
                with self._lock:
                    self.stale_serves += 1
                if self.metrics is not None:
                    self.metrics.record(
                        "externaldata_stale_serves_total", 1,
                        provider=p.name,
                    )
            elif st == NEGATIVE_HIT:
                errors.append([k, entry.error])
            else:  # MISS after the fetch attempt: the provider is down
                err = fetch_err or "provider unavailable"
                system_error = err
                result = "unavailable"
                if not p.fail_open:
                    # fail-closed: the missing fact becomes a per-key
                    # error — error-gated templates deny, and the
                    # admission message names the provider and cause
                    errors.append(
                        [k, f"provider {p.name} unavailable "
                            f"(fail-closed): {err}"]
                    )
        if errors and result == "ok":
            result = "error"
        if self.metrics is not None:
            self.metrics.record(
                "externaldata_requests_total", 1,
                provider=p.name, result=result,
            )
        return {
            "responses": responses,
            "errors": errors,
            "status_code": 200 if not system_error else 500,
            "system_error": system_error,
        }

    # -- introspection ---------------------------------------------------------

    def report_gauges(self) -> None:
        if self.metrics is None:
            return
        with self._lock:
            n = len(self._providers)
        self.metrics.gauge("externaldata_providers", n)

    def snapshot(self) -> Dict[str, Any]:
        """Readyz/debug view: per-provider policy + breaker state."""
        with self._lock:
            providers = dict(self._providers)
            breakers = dict(self._breakers)
            failed = dict(self._failed_epoch)
            epoch = self._epoch
        return {
            "providers": {
                name: {
                    "failure_policy": p.failure_policy,
                    "cache_ttl_s": p.cache_ttl_s,
                    "breaker": (
                        breakers[name].snapshot()
                        if name in breakers
                        else None
                    ),
                    "failed_this_epoch": (
                        failed.get(name, (None,))[0] == epoch
                    ),
                }
                for name, p in sorted(providers.items())
            },
            "cache_entries": len(self.cache),
            "cache_evictions": self.cache.evictions,
            "fetches": self.fetch_count,
            "stale_serves": self.stale_serves,
        }
