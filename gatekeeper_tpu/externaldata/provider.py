"""Provider model: the `externaldata.gatekeeper.sh/v1alpha1` CRD-alike.

Gatekeeper v3's external-data Provider names an out-of-cluster HTTP
endpoint that answers key lookups (image signatures, CMDB records,
allowlists). The TPU build keeps the upstream spec surface (url,
timeout, caBundle) and adds the caching/failure knobs the batch plane
needs: per-provider response TTLs (positive, negative,
stale-while-revalidate) and an explicit failurePolicy that decides what
an *unreachable* provider means for admission — fail-open (lookups
resolve empty, error-gated templates allow) or fail-closed (lookups
resolve to per-key errors, error-gated templates deny).

The wire protocol mirrors upstream's ProviderRequest/ProviderResponse:

    POST <url>
    {"apiVersion": "externaldata.gatekeeper.sh/v1alpha1",
     "kind": "ProviderRequest", "request": {"keys": [...]}}

    {"apiVersion": "externaldata.gatekeeper.sh/v1alpha1",
     "kind": "ProviderResponse",
     "response": {"items": [{"key": ..., "value": ..., "error": ...}],
                  "systemError": ""}}
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

EXTERNALDATA_GROUP = "externaldata.gatekeeper.sh"
EXTERNALDATA_VERSION = "v1alpha1"
PROVIDER_KIND = "Provider"

FAIL_OPEN = "open"
FAIL_CLOSED = "closed"

# accepted spellings -> canonical policy (upstream webhook vocabulary
# plus the explicit forms docs/externaldata.md documents)
_POLICY_ALIASES = {
    "ignore": FAIL_OPEN,
    "fail": FAIL_CLOSED,
    "open": FAIL_OPEN,
    "closed": FAIL_CLOSED,
    "fail-open": FAIL_OPEN,
    "fail-closed": FAIL_CLOSED,
}

DEFAULT_TIMEOUT_S = 3.0
DEFAULT_CACHE_TTL_S = 30.0
DEFAULT_NEGATIVE_TTL_S = 5.0
DEFAULT_STALE_TTL_S = 0.0
DEFAULT_MAX_KEYS = 512


class ProviderError(ValueError):
    """Invalid Provider spec (ingest-time rejection; the controller
    surfaces it on the ProviderPodStatus CR instead of crashing)."""


@dataclass
class Provider:
    """One validated provider. Timeouts/TTLs are seconds."""

    name: str
    url: str
    timeout_s: float = DEFAULT_TIMEOUT_S
    failure_policy: str = FAIL_OPEN
    cache_ttl_s: float = DEFAULT_CACHE_TTL_S
    negative_ttl_s: float = DEFAULT_NEGATIVE_TTL_S
    stale_ttl_s: float = DEFAULT_STALE_TTL_S
    max_keys: int = DEFAULT_MAX_KEYS
    ca_bundle: Optional[str] = None
    raw: Dict[str, Any] = field(default_factory=dict)

    @property
    def fail_open(self) -> bool:
        return self.failure_policy == FAIL_OPEN


def _num(spec: Dict[str, Any], key: str, default: float) -> float:
    v = spec.get(key, default)
    if v is None:
        return default
    try:
        out = float(v)
    except (TypeError, ValueError):
        raise ProviderError(f"spec.{key} must be a number, got {v!r}")
    if out < 0:
        raise ProviderError(f"spec.{key} must be >= 0, got {v!r}")
    return out


def provider_from_obj(obj: Dict[str, Any]) -> Provider:
    """Parse + validate a Provider CR dict. Raises ProviderError on any
    spec problem (the GK-P lint codes in lint.py key off these
    messages)."""
    if not isinstance(obj, dict):
        raise ProviderError("provider must be an object")
    api = str(obj.get("apiVersion", ""))
    if api and not api.startswith(EXTERNALDATA_GROUP):
        raise ProviderError(
            f"apiVersion must be in group {EXTERNALDATA_GROUP}, got {api!r}"
        )
    if obj.get("kind") not in (None, PROVIDER_KIND):
        raise ProviderError(f"kind must be {PROVIDER_KIND}")
    name = ((obj.get("metadata") or {}).get("name")) or ""
    if not name:
        raise ProviderError("provider has no metadata.name")
    spec = obj.get("spec") or {}
    if not isinstance(spec, dict):
        raise ProviderError("spec must be an object")
    url = spec.get("url")
    if not isinstance(url, str) or not url:
        raise ProviderError("spec.url is required")
    scheme = url.split("://", 1)[0].lower() if "://" in url else ""
    if scheme not in ("http", "https"):
        raise ProviderError(
            f"spec.url scheme {scheme or '<none>'!r} is unreachable "
            "(want http or https)"
        )
    raw_policy = str(spec.get("failurePolicy", "Ignore")).lower()
    policy = _POLICY_ALIASES.get(raw_policy)
    if policy is None:
        raise ProviderError(
            f"spec.failurePolicy {spec.get('failurePolicy')!r} is not one "
            "of Ignore|Fail|fail-open|fail-closed"
        )
    timeout_s = _num(spec, "timeout", DEFAULT_TIMEOUT_S)
    if timeout_s == 0:
        raise ProviderError("spec.timeout must be > 0 seconds")
    max_keys = int(_num(spec, "maxKeysPerRequest", DEFAULT_MAX_KEYS))
    if max_keys < 1:
        raise ProviderError("spec.maxKeysPerRequest must be >= 1")
    return Provider(
        name=name,
        url=url,
        timeout_s=timeout_s,
        failure_policy=policy,
        cache_ttl_s=_num(spec, "cacheTTLSeconds", DEFAULT_CACHE_TTL_S),
        negative_ttl_s=_num(
            spec, "negativeCacheTTLSeconds", DEFAULT_NEGATIVE_TTL_S
        ),
        stale_ttl_s=_num(
            spec, "staleWhileRevalidateSeconds", DEFAULT_STALE_TTL_S
        ),
        max_keys=max_keys,
        ca_bundle=spec.get("caBundle"),
        raw=obj,
    )


def is_provider_doc(doc: Any) -> bool:
    return (
        isinstance(doc, dict)
        and doc.get("kind") == PROVIDER_KIND
        and str(doc.get("apiVersion", "")).startswith(EXTERNALDATA_GROUP)
    )
