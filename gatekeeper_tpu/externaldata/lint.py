"""Offline provider diagnostics with stable GK-P0xx codes.

Shared by the analysis CLI's `providers` mode and CI (mirrors the
mutator linter's GK-M0xx contract — docs/externaldata.md documents the
codes):

  GK-P001  unreachable URL scheme (not http/https) or missing URL
  GK-P002  missing/zero timeout (a provider without a deadline can
           stall the batch fetch to the webhook's own deadline)
  GK-P003  fail-open without a cache (cacheTTLSeconds=0): every outage
           silently allows with no stale fallback — pair fail-open with
           a TTL or accept blind spots explicitly
  GK-P004  invalid failurePolicy value
  GK-P005  stale-while-revalidate window without a positive TTL
  GK-P006  spec parse error (bad types, missing name)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Tuple

from .provider import ProviderError, provider_from_obj


@dataclass
class ProviderLint:
    """One provider's lint outcome."""

    id: str
    source: str = ""
    codes: List[str] = field(default_factory=list)
    messages: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.codes

    def add(self, code: str, message: str) -> None:
        if code not in self.codes:
            self.codes.append(code)
        self.messages.append(f"{code}: {message}")

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "source": self.source,
            "codes": list(self.codes),
            "messages": list(self.messages),
            "ok": self.ok,
        }

    def render(self) -> str:
        if self.ok:
            return f"{self.id}: OK"
        return f"{self.id}: " + "; ".join(self.messages)


def _classify_error(err: ProviderError) -> str:
    msg = str(err)
    if "scheme" in msg or "spec.url" in msg:
        return "GK-P001"
    if "timeout" in msg:
        return "GK-P002"
    if "failurePolicy" in msg:
        return "GK-P004"
    return "GK-P006"


def lint_providers(
    docs: List[Tuple[str, Dict[str, Any]]],
) -> List[ProviderLint]:
    """[(source, provider dict)] -> per-provider lint results. Parse
    errors carry their classified code; valid providers are additionally
    checked for the operational footguns (GK-P002/3/5)."""
    out: List[ProviderLint] = []
    for source, doc in docs:
        name = (
            ((doc.get("metadata") or {}).get("name") or "?")
            if isinstance(doc, dict)
            else "?"
        )
        lint = ProviderLint(id=f"Provider/{name}", source=source)
        out.append(lint)
        try:
            p = provider_from_obj(doc)
        except ProviderError as e:
            lint.add(_classify_error(e), str(e))
            continue
        spec = (doc.get("spec") or {})
        if "timeout" not in spec:
            lint.add(
                "GK-P002",
                "no spec.timeout: the default applies, but an explicit "
                "deadline is required for reviewable provider rollouts",
            )
        if p.fail_open and p.cache_ttl_s <= 0:
            lint.add(
                "GK-P003",
                "failurePolicy fail-open with cacheTTLSeconds=0: every "
                "provider outage is a silent allow with no cached or "
                "stale fallback",
            )
        if p.stale_ttl_s > 0 and p.cache_ttl_s <= 0:
            lint.add(
                "GK-P005",
                "staleWhileRevalidateSeconds without a positive "
                "cacheTTLSeconds never serves stale (nothing is ever "
                "cached to go stale)",
            )
    return out
