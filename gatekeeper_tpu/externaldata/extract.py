"""Static key extraction for the batch plane.

The micro-batch contract — one outbound fetch per (provider, batch) —
requires knowing every key a batch will look up BEFORE evaluation. The
analyzer records each template's `external_data` call sites
(`analysis.report.ExternalDataCall`); when a call's keys expression is
*input-derived* (built from `input.review` walks, literals, and
comprehension-local bindings only), this module evaluates just that
expression per review with the Rego interpreter — a micro-evaluation
orders of magnitude cheaper than the template body — and the union of
keys across the batch feeds `ExternalDataSystem.prefetch`.

Calls whose keys cannot be statically extracted (parameters-dependent,
flowing through helpers) degrade gracefully: no prefetch, the coarse
all-rows screen, and per-call fetches at resolve time (still one fetch
per distinct missing key set per epoch).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Set


def extract_keys(interp, call, review: Any) -> Optional[Set[str]]:
    """Evaluate one recorded call's keys expression against a review.

    -> set of string keys, or None when the expression is undefined or
    errors for this review (callers treat None as "route the row" —
    coarse, sound)."""
    from ..rego.interp import _eval_term
    from ..rego.values import type_name

    if call.keys_term is None or call.module is None:
        return None
    try:
        ctx = interp.make_context({"review": review}, {})
        keys: Set[str] = set()
        found = False
        for v, _env in _eval_term(ctx, call.module, call.keys_term, {}):
            found = True
            if type_name(v) not in ("array", "set"):
                return None
            for k in v:
                if not isinstance(k, str):
                    return None
                keys.add(k)
        return keys if found else None
    except Exception:
        return None


def batch_wants(
    interp, calls: Sequence[Any], reviews: Sequence[Any]
) -> Optional[Dict[str, Set[str]]]:
    """{provider -> deduped keys} across a whole batch, or None when
    any call is unextractable (prefetch impossible)."""
    wants: Dict[str, Set[str]] = {}
    for call in calls:
        if not getattr(call, "extractable", False) or not call.provider:
            return None
        for review in reviews:
            keys = extract_keys(interp, call, review)
            if keys:
                wants.setdefault(call.provider, set()).update(keys)
    return wants
