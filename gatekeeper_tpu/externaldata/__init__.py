"""External-data providers: batched, cached, fault-aware out-of-band
lookups (docs/externaldata.md).

The Gatekeeper v3 capability that most stresses the TPU-native design:
admission verdicts depending on facts outside the cluster must consult
them WITHOUT abandoning the fused fast path. The subsystem's layers:

  * `provider.py`   — the externaldata.gatekeeper.sh/v1alpha1 Provider
                      CRD-alike (url/timeout/failurePolicy/TTLs);
  * `cache.py`      — TTL response cache with negative caching and
                      stale-while-revalidate;
  * `system.py`     — the batch plane: one outbound fetch per
                      (provider, micro-batch), per-provider circuit
                      breakers, failurePolicy semantics;
  * `binding.py`    — the process binding the `external_data` Rego
                      builtin resolves through;
  * `extract.py`    — static key extraction feeding batch prefetch;
  * `lint.py`       — GK-P0xx offline provider lint
                      (`python -m gatekeeper_tpu.analysis providers`).
"""

from .binding import get_system, set_system, use_system
from .cache import HIT, MISS, NEGATIVE_HIT, STALE, Entry, ResponseCache
from .provider import (
    EXTERNALDATA_GROUP,
    EXTERNALDATA_VERSION,
    PROVIDER_KIND,
    Provider,
    ProviderError,
    is_provider_doc,
    provider_from_obj,
)
from .system import ExternalDataSystem, HttpFetcher, UnknownProviderError

__all__ = [
    "EXTERNALDATA_GROUP",
    "EXTERNALDATA_VERSION",
    "Entry",
    "ExternalDataSystem",
    "HIT",
    "HttpFetcher",
    "MISS",
    "NEGATIVE_HIT",
    "PROVIDER_KIND",
    "Provider",
    "ProviderError",
    "ResponseCache",
    "STALE",
    "UnknownProviderError",
    "get_system",
    "is_provider_doc",
    "provider_from_obj",
    "set_system",
    "use_system",
]
