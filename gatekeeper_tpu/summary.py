"""The bench SUMMARY line contract, shared by every lane.

Every bench entry point (`bench_webhook.py --ladder/--attribution/
--partitions/--fleet/--chaos/--churn/--external/--mutate/--soak/
--slo/--sched`, `bench.py`)
ends its run with one compact driver-parseable line:

    SUMMARY: {"mode": "<lane>", ...headline numbers...}

The full JSON artifact has outgrown capture buffers before (BENCH_r05's
`parsed: null`); the SUMMARY line is the part that must survive
truncation — which only helps if its schema cannot silently drift from
the readers (`bench_compare.py`, the soak report tests, the BENCH_r*
trajectory tooling). This module is the one place the contract lives:

  * `REQUIRED_FIELDS` — per-mode headline keys a summary MUST carry;
  * `format_summary` — the writer every lane emits through;
  * `parse_summary_line` — the strict reader (raises on an unknown
    mode or a missing required field);
  * `check_summary` — the lint form (problem list, empty = valid).

tests/test_summary_contract.py drives every bench mode's summarizer
through the strict reader so a new headline field — or a dropped one —
fails CI instead of a future postmortem.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

__all__ = [
    "REQUIRED_FIELDS",
    "SUMMARY_PREFIX",
    "check_summary",
    "format_summary",
    "parse_summary_line",
]

SUMMARY_PREFIX = "SUMMARY: "

# per-mode headline keys every SUMMARY line must carry. A key listed
# here may be null (a truncated run reports what it has) but must be
# PRESENT — presence is what the readers key on.
REQUIRED_FIELDS: Dict[str, tuple] = {
    "webhook": ("p50_ms", "p99_ms", "throughput_rps"),
    "ladder": ("rungs", "last"),
    "attribution": (
        "rungs", "sums_ok", "attribution_ratio", "dispatch_efficiency",
        "partitions_touched_p50", "partitions_touched_max",
    ),
    "partitions": (
        "parity_ok", "healthy_subset_degraded",
        "degraded_coverage_fraction", "recovery_s", "home_restored",
    ),
    "fleet": (
        "fetches_per_key_n1", "fetches_per_key_n2_isolated",
        "fetches_per_key_n2_fleet", "cold_fetch_amplification",
    ),
    "chaos": ("phases", "p50_ms", "p99_ms", "shed_rate"),
    "churn": (
        "waves", "ingest_to_serve_ms", "degraded_dispatches",
        "http_5xx",
    ),
    "external": ("phases", "cache_hit_rate", "fetches_per_batch"),
    "mutate": ("p50_ms", "p99_ms", "throughput_rps"),
    "soak": (
        "slo_attainment", "shed_rate", "leak_flagged", "checks",
    ),
    # the live SLO plane lane (obs/slo.py): streaming attainment +
    # burn rate through a fault/recover cycle, plus the autoscaler
    # signals (saturation up-bad, headroom) bench_compare.py gates
    "slo": (
        "slo_attainment", "saturation", "burn_rate_fast",
        "headroom_rps", "breaches",
    ),
    # the admission-scheduler lane (gatekeeper_tpu/sched/): the same
    # two-tenant overload through FIFO then the deadline scheduler —
    # per-class latency/attainment split, the worst per-tenant
    # attainment (bench_compare watches it down-bad), and predictive
    # (predicted_miss) vs blind (FIFO queue_full) shed counts
    "sched": (
        "quiet_p50_ms", "quiet_p99_ms", "noisy_p50_ms", "noisy_p99_ms",
        "quiet_attainment", "noisy_attainment", "tenant_attainment_min",
        "predicted_miss_shed", "blind_shed",
    ),
    # the wire-speed ingest lane (docs/ingest.md): one open-loop
    # arrival schedule through conn-per-request HTTP/1, HTTP/1.1
    # keep-alive, and the framed stream listener — goodput inside one
    # shared deadline per phase, the framed/legacy ratio, and the
    # zero-copy scanner's p50 (bench_compare watches rps_sustained
    # down-bad and decode_p50_ms up-bad)
    "ingest": (
        "offered_rps", "rps_sustained", "framed_vs_http1",
        "http1_rps_sustained", "keepalive_rps_sustained",
        "framed_attainment", "http1_attainment", "p50_ms", "p99_ms",
        "decode_p50_ms", "decode_span_share", "conns_per_1k_framed",
        "conns_per_1k_http1",
    ),
    # the verdict-integrity lane (docs/robustness.md §Verdict
    # integrity): clean → injected-SDC → self-test-healed. Divergence
    # rate and canary overhead are bench_compare WATCHED (both
    # up-bad); detection latency is arm -> corruption quarantine
    "integrity": (
        "phases", "divergence_rate", "canary_overhead_frac",
        "detection_latency_s", "selftest_healed",
    ),
}


def format_summary(mode: str, head: Dict[str, Any]) -> str:
    """Render one SUMMARY line. `mode` is stamped first so a truncated
    tail still names its lane; values serialize via default=str so an
    exotic object costs readability, never the line."""
    doc = {"mode": mode}
    doc.update(head)
    return SUMMARY_PREFIX + json.dumps(doc, default=str)


def check_summary(doc: Dict[str, Any]) -> List[str]:
    """Problem list for a parsed summary doc (empty = valid)."""
    problems: List[str] = []
    mode = doc.get("mode")
    if mode is None:
        return ["missing field: mode"]
    required = REQUIRED_FIELDS.get(mode)
    if required is None:
        return [f"unknown summary mode: {mode!r}"]
    if doc.get("error"):
        # a summarizer that caught an exception reports it instead of
        # the headline set; the reader surfaces that, not a field lint
        return []
    for f in required:
        if f not in doc:
            problems.append(f"{mode} summary missing {f!r}")
    return problems


def parse_summary_line(
    line: str, mode: Optional[str] = None
) -> Dict[str, Any]:
    """Strict SUMMARY reader: raises ValueError on a non-summary line,
    an unknown/unexpected mode, or a missing required headline field.
    `mode` narrows to one lane (the soak reader passes "soak")."""
    line = line.strip()
    if not line.startswith(SUMMARY_PREFIX):
        raise ValueError(f"not a SUMMARY line: {line[:40]!r}")
    doc = json.loads(line[len(SUMMARY_PREFIX):])
    if not isinstance(doc, dict):
        raise ValueError("SUMMARY payload is not an object")
    if mode is not None and doc.get("mode") != mode:
        raise ValueError(
            f"not a {mode} summary: mode={doc.get('mode')!r}"
        )
    problems = check_summary(doc)
    if problems:
        raise ValueError("; ".join(problems))
    return doc


def find_summary(text: str, mode: Optional[str] = None) -> Optional[
    Dict[str, Any]
]:
    """Last parseable SUMMARY line in a blob of captured output (the
    bench_compare.py input path for raw run logs); None when absent."""
    found = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith(SUMMARY_PREFIX):
            continue
        try:
            found = parse_summary_line(line, mode=mode)
        except ValueError:
            continue
    return found
