"""Process entry point: `python -m gatekeeper_tpu.run` — the main.go
analog (reference main.go:80-308).

Builds the in-cluster KubeCluster EventSource (service-account config),
the TPU driver behind the constraint-framework Client, and the Runner
with the selected operations; flags mirror the reference's surface:

  --operation            webhook|audit|status (repeatable; default all)
  --port                 webhook HTTPS port (policy.go:73)
  --health-addr-port     readyz/healthz port (main.go:87)
  --prometheus-port      /metrics exposition port (exporter.go:26)
  --audit-interval       seconds between sweeps (audit/manager.go:48)
  --audit-from-cache     sweep the synced cache instead of listing
  --audit-chunk-size     discovery-sweep review batch size (manager.go:50)
  --constraint-violations-limit  per-constraint cap (manager.go:49)
  --log-denies           structured deny logs (policy.go:73)
  --emit-admission-events / --emit-audit-events
  --exempt-namespace     ns-label webhook exemption (repeatable)
  --cert-dir             local TLS artifact cache dir ("" = private
                         temp dir; with --cert-secret this is ONLY a
                         cache — the Secret is the store)
  --cert-secret          name of the Secret backing the SHARED fleet
                         cert store (docs/fleet.md; "" = pod-local
                         certs, single-replica only)
  --fleet-namespace      namespace holding the cert Secret + FleetState
                         CRs (the gossip plane for cache/breaker state)
  --vwh-name             ValidatingWebhookConfiguration to keep
                         injected with the rotating CA bundle
  --enable-pprof         JAX profiler endpoint on the health server
  --fail-policy          open|closed — what a shed/expired/unevaluable
                         request gets (docs/robustness.md)
  --max-queue            admission queue bound (0 = unbounded)
  --sched-policy         fifo|deadline — admission scheduling policy
                         (docs/operations.md §Admission scheduling);
                         "deadline" enables EDF batch formation,
                         per-tenant fair-share quotas, and predictive
                         shedding; "fifo" is the bit-compatible legacy
                         queue and the rollback path
  --drain-grace          seconds /readyz reports not-ready before the
                         webhook listener closes on SIGTERM (graceful
                         drain, docs/robustness.md)
  --no-integrity         disable the verdict-integrity plane (canary
                         rows, sampled shadow oracle, SDC quarantine —
                         docs/robustness.md §Verdict integrity); on by
                         default, this is the rollback path
  --kube-url/--kube-token/--kube-ca  out-of-cluster apiserver access
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="gatekeeper-tpu")
    p.add_argument("--operation", action="append", default=None,
                   choices=["webhook", "audit", "status"])
    p.add_argument("--port", type=int, default=8443)
    p.add_argument("--health-addr-port", type=int, default=9090)
    # Prometheus exposition (exporter.go:26 / --prometheus-port 8888 in
    # the reference); 0 disables
    p.add_argument("--prometheus-port", type=int, default=8888)
    p.add_argument("--audit-interval", type=float, default=60.0)
    p.add_argument("--audit-from-cache", action="store_true")
    p.add_argument("--audit-chunk-size", type=int, default=512)
    # --log-level (main.go:81-83; this logger's levels)
    p.add_argument(
        "--log-level", default="info",
        choices=["debug", "info", "error"],
    )
    p.add_argument("--constraint-violations-limit", type=int, default=20)
    p.add_argument("--log-denies", action="store_true")
    p.add_argument("--emit-admission-events", action="store_true")
    p.add_argument("--emit-audit-events", action="store_true")
    p.add_argument("--exempt-namespace", action="append", default=[])
    p.add_argument("--cert-dir", default="")
    # the fleet plane (docs/fleet.md): Secret-backed shared certs ON by
    # default — HA replicas must serve one CA; opt out with ""
    p.add_argument(
        "--cert-secret", default="gatekeeper-webhook-server-cert"
    )
    p.add_argument("--fleet-namespace", default="gatekeeper-system")
    p.add_argument("--vwh-name", default="")
    p.add_argument("--enable-pprof", action="store_true")
    # overload/degradation envelope (docs/robustness.md): the response
    # a shed/expired/unevaluable request gets, and the admission queue
    # bound (0 = unbounded). Chaos faults arm via GATEKEEPER_TPU_FAULTS.
    p.add_argument("--fail-policy", default="open",
                   choices=["open", "closed"])
    p.add_argument("--max-queue", type=int, default=2048)
    # SLO-aware admission scheduling (docs/operations.md §Admission
    # scheduling): deadline = EDF batch formation + fair-share quotas
    # + predictive shedding; fifo = legacy queue (rollback path)
    p.add_argument("--sched-policy", default="fifo",
                   choices=["fifo", "deadline"])
    p.add_argument(
        "--partitions", type=int, default=0,
        help="split the constraint corpus into N device fault domains "
        "(per-device breakers + quarantine; 0 = monolithic dispatch)",
    )
    # wire-speed ingest plane (docs/ingest.md): framed streaming
    # listener next to the legacy HTTP port. "off" is the rollback
    # path — the HTTP front door is identical either way.
    p.add_argument("--ingest", default="off",
                   choices=["off", "on", "json"],
                   help="framed-stream listener: on = zero-copy "
                   "decode, json = framed transport with plain "
                   "json.loads decode, off = legacy HTTP only")
    p.add_argument("--ingest-port", type=int, default=0,
                   help="stream listener port (0 = ephemeral)")
    # graceful drain: seconds /readyz reports not-ready while the
    # webhook listener still accepts (SIGTERM flips readiness first,
    # the LB routes away, THEN the listener closes and in-flight
    # requests complete — docs/robustness.md)
    p.add_argument("--drain-grace", type=float, default=1.0)
    # verdict-integrity plane (docs/robustness.md §Verdict integrity):
    # on by default; the flag exists so an operator can bisect a
    # regression back to the plane without a rebuild
    p.add_argument("--no-integrity", dest="integrity",
                   action="store_false", default=True)
    # agent-action admission (docs/targets.md): registers the
    # AgentActionTarget so agent templates ingest and the webhook
    # serves POST /v1/agent/review
    p.add_argument("--agent-review", action="store_true")
    p.add_argument("--kube-url", default=None)
    p.add_argument("--kube-token", default=None)
    p.add_argument("--kube-ca", default=None)
    p.add_argument("--kube-insecure", action="store_true")
    p.add_argument("--pod-name", default=None)
    return p


def build_runner(args, log=None, webhook_tls: bool = True):
    """(cluster, runner) from parsed flags — factored out of main so
    tests can drive the REAL entry wiring against a mock apiserver."""
    import os

    from .constraint import Backend, K8sValidationTarget, TpuDriver
    from .control import KubeCluster, Runner
    from .logs import StructuredLogger

    if log is None:
        log = StructuredLogger(level=getattr(args, "log_level", "info"))
    cluster = KubeCluster(
        base_url=args.kube_url,
        token=args.kube_token,
        ca_file=args.kube_ca,
        verify=not args.kube_insecure,
        logger=log,
    )
    targets = [K8sValidationTarget()]
    if getattr(args, "agent_review", False):
        from .agentaction import AgentActionTarget

        targets.append(AgentActionTarget())
    client = Backend(TpuDriver()).new_client(*targets)
    operations = tuple(args.operation) if args.operation else (
        "webhook", "audit", "status"
    )
    runner = Runner(
        cluster,
        client,
        "admission.k8s.gatekeeper.sh",
        operations=operations,
        pod_name=args.pod_name
        or os.environ.get("POD_NAME", "gatekeeper-tpu"),
        audit_interval=args.audit_interval,
        webhook_port=args.port,
        readyz_port=args.health_addr_port,
        exempt_namespaces=args.exempt_namespace,
        webhook_tls=webhook_tls,
        emit_admission_events=args.emit_admission_events,
        emit_audit_events=args.emit_audit_events,
        audit_from_cache=args.audit_from_cache,
        audit_chunk_size=args.audit_chunk_size,
        enable_profiler=args.enable_pprof,
        log_denies=args.log_denies,
        logger=log,
        vwh_name=args.vwh_name or None,
        cert_dir=args.cert_dir or None,  # "" = process-private temp dir
        cert_secret=getattr(args, "cert_secret", "") or None,
        fleet_namespace=getattr(
            args, "fleet_namespace", "gatekeeper-system"
        ),
        fail_policy=getattr(args, "fail_policy", "open"),
        max_queue=(
            getattr(args, "max_queue", 2048) or None
        ),  # 0 -> unbounded
        partitions=getattr(args, "partitions", 0),
        sched_policy=getattr(args, "sched_policy", "fifo"),
        ingest=getattr(args, "ingest", "off"),
        ingest_port=getattr(args, "ingest_port", 0),
        integrity=getattr(args, "integrity", True),
        drain_grace_s=getattr(args, "drain_grace", 0.0),
        bind_addr="0.0.0.0",  # kubelet probes and the apiserver dial
        # the pod IP, not loopback
    )
    return cluster, runner


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from .logs import StructuredLogger

    log = StructuredLogger(level=args.log_level)
    cluster, runner = build_runner(args, log=log)
    log.info(
        "starting gatekeeper-tpu",
        operations=sorted(runner.operations),
        webhook_port=args.port,
        health_port=args.health_addr_port,
    )
    runner.start()

    # try/finally from here: the runner owns NON-daemon threads (the
    # warm compiler), so an exception that skips runner.stop() would
    # leave the process hanging instead of crashing-and-restarting
    metrics_httpd = None
    try:
        if args.prometheus_port:
            from .metrics import serve_metrics

            metrics_httpd = serve_metrics(
                runner.metrics,
                port=args.prometheus_port,
                bind_addr="0.0.0.0",
                # the debug surface rides the metrics plane too (the
                # health server serves the same routes; either port
                # works for an operator with port-forward access)
                tracer=runner.tracer,
                attributor=runner.attributor,
                recorder=runner.recorder,
                decisions=runner.decisions,
                partitions=getattr(
                    runner.webhook, "partitioner", None
                ),
                slo=runner.slo,
                sched=getattr(
                    runner.webhook, "sched_snapshot", None
                ),
            )
            log.info(
                "metrics serving", prometheus_port=args.prometheus_port
            )

        stop = threading.Event()

        def _sig(signum, frame):
            log.info("signal received, draining", signum=signum)
            stop.set()

        signal.signal(signal.SIGTERM, _sig)
        signal.signal(signal.SIGINT, _sig)
        stop.wait()
    finally:
        if metrics_httpd is not None:
            metrics_httpd.shutdown()
        runner.stop()
        cluster.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
