"""gatekeeper_tpu — a TPU-native policy-enforcement framework.

A ground-up rebuild of OPA Gatekeeper's capabilities (reference:
/root/reference, an OPA Gatekeeper v3 snapshot) designed for TPU hardware:
ConstraintTemplate Rego is compiled into vectorized JAX evaluators operating
on columnar encodings of flattened Kubernetes objects, so that full-cluster
audit (resources x constraints) runs as batched XLA computations, with a
Python Rego interpreter serving as the semantics oracle and CPU fallback
driver (reference parity boundary: the constraint-framework Driver interface,
/root/reference/vendor/github.com/open-policy-agent/frameworks/constraint/
pkg/client/drivers/interface.go:21-39).
"""

__version__ = "0.1.0"
