"""Sharded, fused audit dispatch: match-kernel ∧ template-programs over a
device mesh.

Design (SURVEY §2.4 rows 1/4; reference counterpart: the per-pod
replicated OPA state + per-object serial loop in pkg/audit/manager.go:
277-335, which has no intra-query parallelism at all):

  * 2-D mesh ``("c", "n")`` — constraints × resources. The resource axis
    ("n") is the big one and the default shard target; the constraint
    axis ("c") is available for very large constraint populations
    (c_shards=1 gives the plain 1-D resource shard).
  * Policy-side tensors (match specs, program consts, string tables) are
    replicated — they are small. Resource-side tensors (token table,
    review features) are sharded on "n".
  * The match matrix and every compiled template program evaluate in ONE
    jitted dispatch; XLA partitions the elementwise [C, N] work with no
    communication, and the only collective is the reduction that
    produces per-constraint violation totals (an all-reduce over the "n"
    axis inserted by GSPMD). Violation *indices* leave the device as the
    sparse (c, n) set — the all-gather the north star prescribes.

Everything is shape-padded to mesh-divisible sizes host-side; padded
constraint rows are all-pad (-1) kind selectors which match nothing, and
padded resource rows are sliced off after gather.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.matchkernel import match_matrix
from ..engine.programs import Program
from ..engine.patterns import PatternRegistry
from ..engine.tables import StrTables


def audit_mesh(
    n_devices: Optional[int] = None, c_shards: int = 1
) -> Mesh:
    """A ("c", "n") mesh over the first n_devices devices; c_shards
    splits the constraint axis (1 = resource-axis sharding only)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    if len(devs) % c_shards != 0:
        raise ValueError(
            f"{len(devs)} devices not divisible by c_shards={c_shards}"
        )
    arr = np.array(devs).reshape(c_shards, len(devs) // c_shards)
    return Mesh(arr, ("c", "n"))


def _pad_axis(a: np.ndarray, axis: int, mult: int, fill) -> np.ndarray:
    n = a.shape[axis]
    target = ((n + mult - 1) // mult) * mult
    if target == n:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, target - n)
    return np.pad(a, widths, constant_values=fill)


@dataclass
class StagedPolicy:
    """Constraint-side tensors resident on device (staged once per
    constraint-set change): match specs, grouped program consts, and the
    compiled-constraint mask."""

    ms_dev: Dict[str, Any]
    group_exprs: List[Any]
    group_rows: List[List[int]]
    stacked_consts: List[Dict[str, Any]]
    compiled_mask: Any  # [C_pad] bool device
    prog_rows: List[int]
    c: int  # true constraint count
    c_pad: int
    key: Tuple


@dataclass
class StagedBatch:
    """Resource-side tensors resident on device (staged once per corpus
    chunk): review features, token table, and the row-fallback mask."""

    fb_dev: Dict[str, Any]
    tok_dev: Dict[str, Any]
    row_fb: Any  # [N_pad] bool device
    n_valid: int  # true rows in this chunk
    key: Tuple


class FusedAuditKernel:
    """One-dispatch audit: [C, N] match ∧ per-program violation counts.

    With a mesh, inputs are placed with NamedShardings and GSPMD
    partitions the compute; without one, it is the plain single-device
    fused dispatch (what TpuDriver uses for its steady-state sweep).

    Two dispatch forms:
      * `run`/`prepare` — full [C, N] outputs (dryrun/entry/tests);
      * `stage_policy`/`stage_batch`/`dispatch_need` — device-resident
        operands + sparse output: only the flat indices of pairs that
        need host-side interpreter work leave the device (the all-gather
        of violation indices the north star prescribes; gathering the
        full matrices over the chip link is what made sweeps slow).
    """

    def __init__(
        self,
        patterns: PatternRegistry,
        tables: StrTables,
        mesh: Optional[Mesh] = None,
    ):
        self.patterns = patterns
        self.tables = tables
        self.mesh = mesh
        # key -> [closure, jitted|None]: one entry per distinct
        # (group-set, shapes, n, g) specialization
        self._jit_cache: Dict[Tuple, List[Any]] = {}
        self._table_cache: Optional[Tuple[Tuple[int, int], Dict[str, Any]]] = None

    # -- shardings -----------------------------------------------------------

    def _spec(self, *axes) -> Optional[NamedSharding]:
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, P(*axes))

    def _put(self, x, *axes):
        arr = jnp.asarray(x)
        s = self._spec(*axes)
        return arr if s is None else jax.device_put(arr, s)

    def _tables_device(self) -> Dict[str, Any]:
        self.patterns.sync()
        self.tables.sync()
        gen = (self.patterns.generation, self.tables.generation)
        if self._table_cache is None or self._table_cache[0] != gen:
            arrs = {
                "pat_member": self.patterns.member,
                "pat_capture": self.patterns.capture,
                **self.tables.arrays(),
            }
            # replicated policy-side tensors
            arrs = {k: self._put(v) for k, v in arrs.items()}
            self._table_cache = (gen, arrs)
        return self._table_cache[1]

    # -- staged sparse dispatch ---------------------------------------------

    def stage_policy(
        self,
        programs: Sequence[Optional[Program]],
        ms: Dict[str, np.ndarray],
    ) -> StagedPolicy:
        c = next(iter(ms.values())).shape[0]
        c_mult = self.mesh.shape["c"] if self.mesh else 1
        ms_dev = {
            k: self._put(_pad_axis(np.asarray(v), 0, c_mult, _ms_fill(k)), "c")
            for k, v in ms.items()
        }
        c_pad = ms_dev["kind_rows"].shape[0]
        compiled = [p for p in programs if p is not None]
        prog_rows = []
        row = 0
        for p in programs:
            prog_rows.append(row if p is not None else -1)
            row += p is not None
        compiled_mask = np.zeros((c_pad,), bool)
        compiled_mask[: len(programs)] = [p is not None for p in programs]
        groups: Dict[Tuple, Dict[str, Any]] = {}
        for ci, p in enumerate(programs):
            if p is None:
                continue
            gkey = (
                p.signature,
                tuple(sorted((k, v.shape) for k, v in p.consts.items())),
            )
            grp = groups.setdefault(
                gkey, {"expr": p.expr, "rows": [], "consts": []}
            )
            grp["rows"].append(ci)  # constraint-row index
            grp["consts"].append(p.consts)
        group_list = list(groups.values())
        stacked_consts = [
            {
                k: self._put(np.stack([cd[k] for cd in grp["consts"]]))
                for k in grp["consts"][0]
            }
            for grp in group_list
        ]
        key = (
            tuple(groups),
            tuple(tuple(grp["rows"]) for grp in group_list),
            c,
            c_pad,
            id(self.mesh),
        )
        return StagedPolicy(
            ms_dev=ms_dev,
            group_exprs=[grp["expr"] for grp in group_list],
            group_rows=[list(grp["rows"]) for grp in group_list],
            stacked_consts=stacked_consts,
            compiled_mask=self._put(compiled_mask, "c"),
            prog_rows=prog_rows,
            c=c,
            c_pad=c_pad,
            key=key,
        )

    def stage_batch(
        self,
        fb: Dict[str, np.ndarray],
        tok: Dict[str, np.ndarray],
        row_fb: np.ndarray,
        n_valid: int,
    ) -> StagedBatch:
        n_mult = self.mesh.shape["n"] if self.mesh else 1
        fb_dev = {
            k: self._put(_pad_axis(np.asarray(v), 0, n_mult, _fb_fill(k)), "n")
            for k, v in fb.items()
        }
        tok_dev = {
            k: self._put(
                _pad_axis(np.asarray(v), 0, n_mult, 0.0 if k == "vnum" else -1),
                "n",
            )
            for k, v in tok.items()
        }
        n_pad = tok_dev["spath"].shape[0]
        rf = np.zeros((n_pad,), bool)
        rf[: len(row_fb)] = row_fb
        return StagedBatch(
            fb_dev=fb_dev,
            tok_dev=tok_dev,
            row_fb=self._put(rf, "n"),
            n_valid=n_valid,
            key=(tok_dev["spath"].shape, fb_dev["group_id"].shape, n_pad),
        )

    def dispatch_need(
        self,
        policy: StagedPolicy,
        batch: StagedBatch,
        g: int,
        k_cap: int = 1 << 14,
    ) -> Tuple[np.ndarray, int, int, int]:
        """-> (flat pair indices [<=k_cap], n_need, compiled_pairs,
        interp_pairs) for one staged chunk.

        Flat index = n_local * c_pad + c (review-major). n_need may
        exceed k_cap (truncated indices): callers re-dispatch with a
        larger cap. Stats count matched pairs on the compiled vs
        interpreter routes (valid rows only).
        """
        n_pad = batch.tok_dev["spath"].shape[0]
        if policy.c_pad * n_pad >= 2**31:
            # the flat pair index is int32; over-scale populations must
            # fail loudly, not silently corrupt pair decoding
            raise OverflowError(
                f"pair space c_pad({policy.c_pad}) x n_pad({n_pad}) "
                f"overflows int32 flat indexing; shrink the chunk size"
            )
        key = ("need", policy.key, batch.key, g, batch.n_valid, k_cap)
        entry = self._jit_cache.get(key)
        if entry is None:
            group_exprs = policy.group_exprs
            group_rows = policy.group_rows
            n_valid = batch.n_valid

            def run_need(ms_in, fb_in, tok_in, tabs_in, consts_in,
                         compiled_mask, row_fb):
                from ..engine.exprs import EvalCtx

                match = match_matrix(ms_in, fb_in)  # [C, N]
                str_tabs = {
                    k: v
                    for k, v in tabs_in.items()
                    if k not in ("pat_member", "pat_capture")
                }
                viol = jnp.zeros(match.shape, bool)
                for expr, grows, consts_k in zip(
                    group_exprs, group_rows, consts_in
                ):

                    def eval_one(consts):
                        ctx = EvalCtx(
                            np=jnp,
                            tok=tok_in,
                            pat_member=tabs_in["pat_member"],
                            pat_capture=tabs_in["pat_capture"],
                            str_tables=str_tabs,
                            consts=consts,
                            g0=g,
                            g1=g,
                        )
                        return expr.emit(ctx).astype(jnp.int32)

                    if consts_k:
                        out_k = jax.vmap(eval_one)(consts_k) > 0
                    else:
                        one = eval_one({}) > 0
                        out_k = jnp.broadcast_to(
                            one, (len(grows),) + one.shape
                        )
                    viol = viol.at[jnp.asarray(grows)].set(out_k)

                valid_n = jnp.arange(match.shape[1]) < n_valid
                fallback = (~compiled_mask[:, None]) | row_fb[None, :]
                need = match & (viol | fallback) & valid_n[None, :]
                stat_c = jnp.sum(
                    match & compiled_mask[:, None] & ~row_fb[None, :]
                    & valid_n[None, :]
                )
                stat_i = jnp.sum(match & fallback & valid_n[None, :])
                need_t = need.T.reshape(-1)  # review-major flat
                idx = jnp.nonzero(need_t, size=k_cap, fill_value=-1)[0]
                return (
                    idx.astype(jnp.int32),
                    need_t.sum().astype(jnp.int32),
                    stat_c.astype(jnp.int32),
                    stat_i.astype(jnp.int32),
                )

            entry = [run_need, jax.jit(run_need)]
            self._jit_cache[key] = entry
        tabs = self._tables_device()
        idx, n_need, stat_c, stat_i = entry[1](
            policy.ms_dev,
            batch.fb_dev,
            batch.tok_dev,
            tabs,
            policy.stacked_consts,
            policy.compiled_mask,
            batch.row_fb,
        )
        return (
            np.asarray(idx),
            int(n_need),
            int(stat_c),
            int(stat_i),
        )

    # -- dispatch ------------------------------------------------------------

    def prepare(
        self,
        programs: Sequence[Optional[Program]],
        ms: Dict[str, np.ndarray],
        fb: Dict[str, np.ndarray],
        tok: Dict[str, np.ndarray],
        g: int,
    ):
        """Build (fn, args, (c, n)) for one dispatch: `fn(*args)` returns
        (match, counts, totals) padded; fn is an un-jitted closure so
        callers (the harness entry point) may compile-check it themselves.
        """
        c = next(iter(ms.values())).shape[0]
        n = next(iter(fb.values())).shape[0]
        compiled = [p for p in programs if p is not None]
        prog_c_rows = [i for i, p in enumerate(programs) if p is not None]

        # Group programs by structural signature (same template control
        # flow + const shapes): one traced subgraph per group, vmapped
        # over the stacked const tensors. A 500-constraint population of
        # ~8 templates traces ~8 subgraphs, not 500 — constraints differ
        # only in the consts they pass (engine/programs.py docstring).
        groups: Dict[Tuple, Dict[str, Any]] = {}
        for out_row, p in enumerate(compiled):
            gkey = (
                p.signature,
                tuple(sorted((k, v.shape) for k, v in p.consts.items())),
            )
            grp = groups.setdefault(
                gkey, {"expr": p.expr, "rows": [], "consts": []}
            )
            grp["rows"].append(out_row)
            grp["consts"].append(p.consts)

        c_mult = self.mesh.shape["c"] if self.mesh else 1
        n_mult = self.mesh.shape["n"] if self.mesh else 1

        ms_dev = {
            k: self._put(_pad_axis(np.asarray(v), 0, c_mult, _ms_fill(k)), "c")
            for k, v in ms.items()
        }
        fb_dev = {
            k: self._put(_pad_axis(np.asarray(v), 0, n_mult, _fb_fill(k)), "n")
            for k, v in fb.items()
        }
        tok_dev = {
            k: self._put(
                _pad_axis(np.asarray(v), 0, n_mult, 0.0 if k == "vnum" else -1),
                "n",
            )
            for k, v in tok.items()
        }
        tabs = self._tables_device()
        # per-group stacked consts: dict name -> [K, ...] device array
        group_list = list(groups.values())
        stacked_consts = [
            {
                k: self._put(np.stack([cd[k] for cd in grp["consts"]]))
                for k in grp["consts"][0]
            }
            for grp in group_list
        ]

        key = (
            tuple(gk for gk in groups),
            tuple(tuple(grp["rows"]) for grp in group_list),
            tuple(prog_c_rows),
            g,
            n,
            tok_dev["spath"].shape,
            fb_dev["group_id"].shape,
            ms_dev["kind_rows"].shape,
            id(self.mesh),
        )
        entry = self._jit_cache.get(key)
        fn = entry[0] if entry is not None else None
        if fn is None:
            n_compiled = len(compiled)
            group_exprs = [grp["expr"] for grp in group_list]
            group_rows = [list(grp["rows"]) for grp in group_list]
            rows = list(prog_c_rows)

            def run_fused(ms_in, fb_in, tok_in, tabs_in, consts_in):
                from ..engine.exprs import EvalCtx

                match = match_matrix(ms_in, fb_in)  # [C, N]
                str_tabs = {
                    k: v
                    for k, v in tabs_in.items()
                    if k not in ("pat_member", "pat_capture")
                }
                if group_exprs:
                    n_pad = tok_in["spath"].shape[0]
                    counts = jnp.zeros((n_compiled, n_pad), jnp.int32)
                    for expr, grows, consts_k in zip(
                        group_exprs, group_rows, consts_in
                    ):

                        def eval_one(consts):
                            ctx = EvalCtx(
                                np=jnp,
                                tok=tok_in,
                                pat_member=tabs_in["pat_member"],
                                pat_capture=tabs_in["pat_capture"],
                                str_tables=str_tabs,
                                consts=consts,
                                g0=g,
                                g1=g,
                            )
                            return expr.emit(ctx).astype(jnp.int32)

                        if consts_k:
                            out_k = jax.vmap(eval_one)(consts_k)  # [K, N]
                        else:
                            # const-free program: every constraint in the
                            # group computes the same counts
                            one = eval_one({})
                            out_k = jnp.broadcast_to(
                                one, (len(grows),) + one.shape
                            )
                        counts = counts.at[jnp.asarray(grows)].set(out_k)
                    # scatter compiled counts back onto constraint rows so
                    # totals line up with the full constraint set
                    viol = jnp.zeros(match.shape, jnp.int32)
                    viol = viol.at[jnp.asarray(rows)].set(counts)
                else:
                    counts = None
                    viol = jnp.zeros(match.shape, jnp.int32)
                # mask padded resource rows (wildcard constraints match
                # the all-pad feature rows) before reducing
                valid_n = jnp.arange(match.shape[1]) < n
                # the one collective: per-constraint totals reduce over
                # the sharded "n" axis (GSPMD all-reduce)
                totals = jnp.sum(
                    (jnp.where(match, viol, 0) > 0) & valid_n[None, :], axis=1
                ).astype(jnp.int32)
                return match, counts, totals

            fn = run_fused
            self._jit_cache[key] = [fn, None]
        return fn, (ms_dev, fb_dev, tok_dev, tabs, stacked_consts), (c, n, key)

    def run(
        self,
        programs: Sequence[Optional[Program]],
        ms: Dict[str, np.ndarray],
        fb: Dict[str, np.ndarray],
        tok: Dict[str, np.ndarray],
        g: int,
    ) -> Tuple[np.ndarray, Optional[np.ndarray], np.ndarray]:
        """-> (match [C, N] bool, counts [Cc, N] int32 | None,
                totals [C] int32 per-constraint compiled-path violation
                totals).

        `programs` is index-aligned with the C constraint rows; None
        entries (interpreter-fallback templates) contribute no counts and
        no totals."""
        fn, args, (c, n, key) = self.prepare(programs, ms, fb, tok, g)
        entry = self._jit_cache[key]
        if entry[1] is None:
            entry[1] = jax.jit(fn)
        match_p, counts_p, totals_p = entry[1](*args)
        match = np.asarray(match_p)[:c, :n]
        counts = None if counts_p is None else np.asarray(counts_p)[:, :n]
        totals = np.asarray(totals_p)[:c]
        return match, counts, totals


def _ms_fill(key: str):
    """Pad constraint rows so they match nothing: all-pad kind selectors
    (-1 rows are invalid) and inert selector/scope fields."""
    if key in ("ns_has", "excl_has", "nssel_has", "nssel_matches_empty",
               "lab_invalid", "nssel_invalid"):
        return False
    if key == "scope":
        return 0  # SCOPE_ABSENT
    return -1


def _fb_fill(key: str):
    if key in (
        "kind_defined",
        "is_ns",
        "has_namespace",
        "obj_present",
        "old_present",
        "nssel_defined",
        "nssel_empty",
        "label_overflow",
    ):
        return False
    return -1
