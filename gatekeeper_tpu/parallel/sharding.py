"""Sharded, fused audit dispatch: match-kernel ∧ template-programs over a
device mesh.

Design (SURVEY §2.4 rows 1/4; reference counterpart: the per-pod
replicated OPA state + per-object serial loop in pkg/audit/manager.go:
277-335, which has no intra-query parallelism at all):

  * 2-D mesh ``("c", "n")`` — constraints × resources. The resource axis
    ("n") is the big one and the default shard target; the constraint
    axis ("c") is available for very large constraint populations
    (c_shards=1 gives the plain 1-D resource shard).
  * Policy-side tensors (match specs, program consts, string tables) are
    replicated — they are small. Resource-side tensors (token table,
    review features) are sharded on "n".
  * The match matrix and every compiled template program evaluate in ONE
    jitted dispatch; XLA partitions the elementwise [C, N] work with no
    communication, and the only collective is the reduction that
    produces per-constraint violation totals (an all-reduce over the "n"
    axis inserted by GSPMD). Violation *indices* leave the device as the
    sparse (c, n) set — the all-gather the north star prescribes.

Everything is shape-padded to mesh-divisible sizes host-side; padded
constraint rows are all-pad (-1) kind selectors which match nothing, and
padded resource rows are sliced off after gather.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.matchkernel import match_matrix
from ..engine.programs import Program
from ..engine.patterns import PatternRegistry
from ..engine.tables import StrTables


def audit_mesh(
    n_devices: Optional[int] = None, c_shards: int = 1
) -> Mesh:
    """A ("c", "n") mesh over the first n_devices devices; c_shards
    splits the constraint axis (1 = resource-axis sharding only)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    if len(devs) % c_shards != 0:
        raise ValueError(
            f"{len(devs)} devices not divisible by c_shards={c_shards}"
        )
    arr = np.array(devs).reshape(c_shards, len(devs) // c_shards)
    return Mesh(arr, ("c", "n"))


def _get_overlapped(out):
    """Fetch a pytree of device arrays in ONE round trip: start every
    device->host copy async, then materialize. jax.device_get alone
    copies leaf-by-leaf, paying the tunnel RTT once per leaf (~150ms x
    5 outputs per dispatch dominated the webhook batch path)."""
    for x in jax.tree_util.tree_leaves(out):
        try:
            x.copy_to_host_async()
        except Exception:
            pass
    return jax.device_get(out)


def _g01(g) -> Tuple[int, int]:
    """Fanout argument: an int (g0 == g1 == g, legacy) or (g0, g1)."""
    if isinstance(g, tuple):
        return int(g[0]), int(g[1])
    return int(g), int(g)


def _pad_len(n: int) -> int:
    """Padded vocab-axis capacity: next power of two with headroom for
    at least one full delta chunk, so growth stays in-bucket for a
    while and jit shapes stay stable."""
    p = 4096
    while p < n + FusedAuditKernel._DELTA_ROWS:
        p *= 2
    return p


def _pad_axis(a: np.ndarray, axis: int, mult: int, fill) -> np.ndarray:
    n = a.shape[axis]
    target = ((n + mult - 1) // mult) * mult
    if target == n:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, target - n)
    return np.pad(a, widths, constant_values=fill)


class ColdKernel(Exception):
    """Raised by dispatch with require_compiled=True when the needed jit
    entry does not exist yet — the caller serves on the interpreter and
    compiles in the background (serve-while-compiling)."""


@dataclass
class StagedPolicy:
    """Constraint-side tensors resident on device (staged once per
    constraint-set change): match specs, grouped program consts, and the
    compiled-constraint mask.

    Both constraint-side inputs are content-deduplicated: `ms_dev` holds
    only the U distinct match-spec rows (+1 match-nothing row for padded
    constraints) with `spec_map` [C_pad] scattering them back, and each
    program group's `stacked_consts` holds only its distinct const sets
    with `group_cmaps` mapping constraint rows to them. Gatekeeper
    populations are dedup-friendly by construction — constraints of one
    template share spec.match blocks and parameter sets — so the O(C x N)
    selector/program work collapses to O(U x N) + a row gather."""

    ms_dev: Dict[str, Any]  # [U+1, ...] replicated
    spec_map: Any  # [C_pad] int32 device, "c"-sharded
    n_specs: int  # U (excluding the match-nothing row)
    group_exprs: List[Any]
    group_rows: List[List[int]]
    group_cmaps: List[List[int]]  # per group: row -> unique-const index
    stacked_consts: List[Dict[str, Any]]
    compiled_mask: Any  # [C_pad] bool device
    prog_rows: List[int]
    c: int  # true constraint count
    c_pad: int
    key: Tuple


@dataclass
class StagedBatch:
    """Resource-side tensors resident on device (staged once per corpus
    chunk): review features, token table, and the row-fallback mask."""

    fb_dev: Dict[str, Any]
    tok_dev: Dict[str, Any]
    row_fb: Any  # [N_pad] bool device
    n_valid: int  # true rows in this chunk
    key: Tuple


@dataclass
class StackedCorpus:
    """The whole corpus resident on device as [K, chunk, ...] stacked
    tensors, so a full sweep is ONE device execution (a lax.map over the
    chunk axis) and ONE host fetch. Per-chunk dispatches each pay a
    ~70-100ms host<->device round trip on a tunneled chip; at 4+ chunks
    per sweep that round-trip tax dominated the entire audit."""

    fb_dev: Dict[str, Any]  # [K, chunk, ...]
    tok_dev: Dict[str, Any]  # [K, chunk, ...]
    row_fb: Any  # [K, chunk] bool device
    n_valid: Any  # [K] int32 device (runtime occupancy per chunk)
    n_valids: List[int]  # host copy
    k: int
    chunk: int
    key: Tuple
    # per-row feature planes ([K, chunk] bool device), e.g. the
    # inventory join-key duplication bits (stage_row_feats)
    row_dev: Dict[str, Any] = None
    # ephemeral vocab-overlay blocks (webhook batches): "member"/
    # "capture" [B, P] + per-kind [B, T] slabs; ids >= v_base resolve
    # against these instead of the resident tables
    ov_dev: Optional[Dict[str, Any]] = None
    v_base: int = 0


class FusedAuditKernel:
    """One-dispatch audit: [C, N] match ∧ per-program violation counts.

    With a mesh, inputs are placed with NamedShardings and GSPMD
    partitions the compute; without one, it is the plain single-device
    fused dispatch (what TpuDriver uses for its steady-state sweep).

    Two dispatch forms:
      * `run`/`prepare` — full [C, N] outputs (dryrun/entry/tests);
      * `stage_policy`/`stage_corpus_stacked`/`dispatch_need_all` —
        device-resident
        operands + sparse output: only the flat indices of pairs that
        need host-side interpreter work leave the device (the all-gather
        of violation indices the north star prescribes; gathering the
        full matrices over the chip link is what made sweeps slow).
    """

    def __init__(
        self,
        patterns: PatternRegistry,
        tables: StrTables,
        mesh: Optional[Mesh] = None,
    ):
        self.patterns = patterns
        self.tables = tables
        self.mesh = mesh
        # optional MetricsRegistry: per-dispatch program-cache hit/miss
        # counters + compile-time distributions (TpuDriver.set_metrics
        # wires this; kernel telemetry is how a p99 cliff gets blamed
        # on XLA compiles vs device execution)
        self.metrics = None
        # key -> [closure, jitted|None]: one entry per distinct
        # (group-set, shapes, n, g) specialization
        self._jit_cache: Dict[Tuple, List[Any]] = {}
        self._table_cache: Optional[Tuple[Tuple[int, int], Dict[str, Any]]] = None
        self._fused_cols: Dict[str, Dict[Any, int]] = {}
        # delta-resident table buffers: name -> [device buf (vocab axis
        # padded), filled rows, non-vocab dims]. Steady-state vocab
        # growth (every admission batch interns new object names) ships
        # only the NEW rows to the device and leaves jit shapes stable —
        # without this, each webhook batch re-uploaded every table
        # (~1s on a tunneled chip) and recompiled on the changed shapes
        self._resident: Dict[str, Tuple[Any, int, Tuple]] = {}

    # -- shardings -----------------------------------------------------------

    # delta-upload granularity for vocab-axis table growth: deltas pad
    # to multiples of this, so at most a handful of distinct jit shapes.
    # Small on purpose: the tunnel h2d path moves ~5-8MB/s, and a
    # webhook batch interns only a few hundred new vocab entries
    _DELTA_ROWS = 512

    def _note_cache(self, op: str, result: str) -> None:
        """program_cache_total{op, result=hit|miss|cold}: every jit
        specialization lookup. `cold` = require_compiled found no entry
        (the serve-while-compiling bounce to the interpreter)."""
        if self.metrics is not None:
            self.metrics.record(
                "program_cache_total", 1, op=op, result=result
            )

    def _note_compile(self, op: str, seconds: float) -> None:
        """First-call wall time of a fresh jit entry — trace + XLA
        compile (jax.jit compiles synchronously inside the first call;
        result arrays come back async, so execution is excluded)."""
        if self.metrics is not None:
            self.metrics.observe("program_compile_seconds", seconds, op=op)

    def _spec(self, *axes) -> Optional[NamedSharding]:
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, P(*axes))

    def _put(self, x, *axes):
        arr = jnp.asarray(x)
        s = self._spec(*axes)
        return arr if s is None else jax.device_put(arr, s)

    def _put_group(self, arrays, *axes):
        """Device-put a dict of host arrays minimizing TRANSFERS:
        same-(dtype, shape) entries ship as ONE stacked buffer and
        unstack into device views. Host->device hops dominate the
        webhook batch staging (each put is a separate latency-bound
        transfer; a review batch stages ~20 small arrays)."""
        out = {}
        groups: Dict[Tuple, List[str]] = {}
        for k, v in arrays.items():
            a = np.asarray(v)
            groups.setdefault((str(a.dtype), a.shape), []).append(k)
        for (_, _shape), names in groups.items():
            if len(names) == 1:
                out[names[0]] = self._put(arrays[names[0]], *axes)
                continue
            stacked = np.stack([np.asarray(arrays[n]) for n in names])
            buf = self._put(stacked, None, *axes)
            for i, n in enumerate(names):
                out[n] = buf[i]
        return out

    def _tables_device(self) -> Dict[str, Any]:
        self.patterns.sync()
        self.tables.sync()
        gen = (self.patterns.generation, self.tables.generation)
        if self._table_cache is None or self._table_cache[0] != gen:
            str_arrs = self.tables.arrays()
            # (host array, vocab axis): the vocab axis is padded to a
            # stable bucket and extended by delta uploads
            host: Dict[str, Tuple[np.ndarray, int]] = {
                "pat_member": (np.asarray(self.patterns.member), 1),
                "pat_capture": (np.asarray(self.patterns.capture), 1),
            }
            for name, tab in str_arrs.items():
                host[name] = (np.asarray(tab), 0)
            # fused transposed copies: a TPU gather op costs ~10ms
            # regardless of width, so the sweep gathers every column in
            # a handful of [V, T] row-gathers instead of one op per
            # pattern/table (the transpose is host-side; device bool
            # transposes are themselves ~100ms-class)
            fused_cols: Dict[str, Dict[Any, int]] = {}
            pm = np.asarray(self.patterns.member)
            if pm.size:
                host["pat_member!T"] = (np.ascontiguousarray(pm.T), 0)
                fused_cols["pat_member"] = {
                    i: i for i in range(pm.shape[0])
                }
                pc = np.asarray(self.patterns.capture)
                host["pat_capture!T"] = (np.ascontiguousarray(pc.T), 0)
                fused_cols["pat_capture"] = {
                    i: i for i in range(pc.shape[0])
                }
            by_kind: Dict[str, List[Tuple[str, np.ndarray]]] = {}
            for name, tab in str_arrs.items():
                t = np.asarray(tab)
                kind = (
                    "vid_bool" if t.dtype == np.bool_
                    else "vid_i32" if np.issubdtype(t.dtype, np.integer)
                    else "vid_f32"
                )
                by_kind.setdefault(kind, []).append((name, t))
            for kind, items in by_kind.items():
                dt = {"vid_bool": np.bool_, "vid_i32": np.int32,
                      "vid_f32": np.float32}[kind]
                host[kind + "!T"] = (
                    np.ascontiguousarray(
                        np.stack([t for _, t in items], axis=1).astype(dt)
                    ),
                    0,
                )
                fused_cols[kind] = {
                    name: i for i, (name, _) in enumerate(items)
                }
            pending: Dict[str, Tuple[Any, np.ndarray, int, int]] = {}
            arrs = {
                k: self._stage_table(k, a, ax, pending)
                for k, (a, ax) in host.items()
            }
            if pending:
                # apply EVERY table's delta in ONE jitted call — one
                # device dispatch per batch instead of one per table
                # (each dispatch pays tunnel overhead)
                for name, buf in self._flush_deltas(pending).items():
                    vlen, other = arrs[name]
                    arrs[name] = buf
                    self._resident[name] = (buf, vlen, other)
            for stale in set(self._resident) - set(host):
                del self._resident[stale]
            self._fused_cols = fused_cols
            self._table_cache = (gen, arrs)
        return self._table_cache[1]

    def _stage_table(self, name: str, a: np.ndarray, ax: int, pending):
        """Device-resident table with vocab-axis padding: vocab growth
        within the padded bucket ships only the new rows (queued into
        `pending` for one fused fixed-shape dynamic_update_slice — no
        recompiles, no full re-upload); structural changes (new
        patterns/tables, bucket overflow) fall back to a full padded
        upload. Returns the device buffer, or (vlen, other) when the
        result comes from the pending flush."""
        vlen = a.shape[ax]
        other = a.shape[:ax] + a.shape[ax + 1:]
        ent = self._resident.get(name)
        if ent is not None:
            buf, fill, other0 = ent
            cap = buf.shape[ax]
            if (
                other0 == other
                and str(buf.dtype) == str(a.dtype)
                and fill <= vlen
            ):
                if fill == vlen:
                    return buf
                dl = vlen - fill
                dpad = -(-dl // self._DELTA_ROWS) * self._DELTA_ROWS
                if fill + dpad <= cap:
                    sl = [slice(None)] * a.ndim
                    sl[ax] = slice(fill, vlen)
                    delta = a[tuple(sl)]
                    if dpad != dl:
                        pad_shape = list(delta.shape)
                        pad_shape[ax] = dpad - dl
                        delta = np.concatenate(
                            [delta, np.zeros(pad_shape, a.dtype)], axis=ax
                        )
                    pending[name] = (buf, delta, fill, ax)
                    return (vlen, other)
        cap = _pad_len(vlen)
        pad_shape = list(a.shape)
        pad_shape[ax] = cap - vlen
        padded = np.concatenate(
            [a, np.zeros(pad_shape, a.dtype)], axis=ax
        ) if cap != vlen else a
        buf = self._put(padded)
        self._resident[name] = (buf, vlen, other)
        return buf

    def _flush_deltas(self, pending) -> Dict[str, Any]:
        names = sorted(pending)
        key = (
            "tabdelta",
            tuple(
                (
                    n,
                    pending[n][0].shape,
                    str(pending[n][0].dtype),
                    pending[n][1].shape,
                    pending[n][3],
                )
                for n in names
            ),
        )
        ent = self._jit_cache.get(key)
        if ent is None:
            axes = {n: pending[n][3] for n in names}

            def upd(bufs, deltas, offs):
                out = {}
                for n in names:
                    b = bufs[n]
                    starts = [jnp.int32(0)] * b.ndim
                    starts[axes[n]] = offs[n]
                    out[n] = jax.lax.dynamic_update_slice(
                        b, deltas[n].astype(b.dtype), tuple(starts)
                    )
                return out

            ent = self._jit_cache[key] = [upd, jax.jit(upd)]
        return ent[1](
            {n: pending[n][0] for n in names},
            {n: jnp.asarray(pending[n][1]) for n in names},
            {n: jnp.int32(pending[n][2]) for n in names},
        )

    # -- staged sparse dispatch ---------------------------------------------

    def stage_policy(
        self,
        programs: Sequence[Optional[Program]],
        ms: Dict[str, np.ndarray],
    ) -> StagedPolicy:
        c = next(iter(ms.values())).shape[0]
        c_mult = self.mesh.shape["c"] if self.mesh else 1
        c_pad = ((c + c_mult - 1) // c_mult) * c_mult

        # content-dedup the match-spec rows: the selector kernel runs over
        # the U distinct rows; a [C_pad] gather rebuilds the full matrix
        ms_np = {k: np.asarray(v) for k, v in ms.items()}
        uniq: Dict[bytes, int] = {}
        reps: List[int] = []
        spec_map = np.empty((c_pad,), np.int32)
        ms_keys = sorted(ms_np)
        for i in range(c):
            sig = b"|".join(ms_np[k][i].tobytes() for k in ms_keys)
            j = uniq.get(sig)
            if j is None:
                j = uniq[sig] = len(reps)
                reps.append(i)
            spec_map[i] = j
        u = len(reps)
        spec_map[c:] = u  # padded constraints -> the match-nothing row
        rep_idx = np.asarray(reps, np.int64)
        ms_dev = {}
        for k, v in ms_np.items():
            null_row = np.full((1,) + v.shape[1:], _ms_fill(k), v.dtype)
            ms_dev[k] = self._put(
                np.concatenate([v[rep_idx], null_row], axis=0)
            )  # [U+1, ...] replicated — small after dedup

        compiled = [p for p in programs if p is not None]
        prog_rows = []
        row = 0
        for p in programs:
            prog_rows.append(row if p is not None else -1)
            row += p is not None
        compiled_mask = np.zeros((c_pad,), bool)
        compiled_mask[: len(programs)] = [p is not None for p in programs]
        groups: Dict[Tuple, Dict[str, Any]] = {}
        for ci, p in enumerate(programs):
            if p is None:
                continue
            gkey = (
                p.signature,
                tuple(sorted((k, v.shape) for k, v in p.consts.items())),
            )
            grp = groups.setdefault(
                gkey,
                {"expr": p.expr, "rows": [], "consts": [], "cmap": [],
                 "cuniq": {}},
            )
            grp["rows"].append(ci)  # constraint-row index
            # dedup identical const sets within the group (constraints of
            # one template frequently share parameters)
            csig = b"|".join(
                k.encode() + b"=" + np.asarray(p.consts[k]).tobytes()
                for k in sorted(p.consts)
            )
            cj = grp["cuniq"].get(csig)
            if cj is None:
                cj = grp["cuniq"][csig] = len(grp["consts"])
                grp["consts"].append(p.consts)
            grp["cmap"].append(cj)
        group_list = list(groups.values())
        stacked_consts = [
            {
                k: self._put(np.stack([cd[k] for cd in grp["consts"]]))
                for k in grp["consts"][0]
            }
            for grp in group_list
        ]
        key = (
            tuple(groups),
            tuple(tuple(grp["rows"]) for grp in group_list),
            tuple(tuple(grp["cmap"]) for grp in group_list),
            c,
            c_pad,
            u,
            id(self.mesh),
        )
        return StagedPolicy(
            ms_dev=ms_dev,
            spec_map=self._put(spec_map, "c"),
            n_specs=u,
            group_exprs=[grp["expr"] for grp in group_list],
            group_rows=[list(grp["rows"]) for grp in group_list],
            group_cmaps=[list(grp["cmap"]) for grp in group_list],
            stacked_consts=stacked_consts,
            compiled_mask=self._put(compiled_mask, "c"),
            prog_rows=prog_rows,
            c=c,
            c_pad=c_pad,
            key=key,
        )

    def stage_corpus_stacked(
        self,
        chunks: Sequence[Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray],
                               np.ndarray, int]],
        ov: Optional[Dict[str, Any]] = None,
        v_base: int = 0,
    ) -> StackedCorpus:
        """Stack per-chunk (fb, tok, row_fb, n_valid) onto a leading
        chunk axis and ship to device once. All chunks must share the
        padded chunk shape.

        `ov` (ephemeral batches): {"member": [B, P] bool, "capture":
        [B, P] i32, "tabs": {name: [B]}} — the batch's vocab-overlay
        rows. Per-kind slabs are stacked here in the SAME column order
        as the resident fused tables so one col mapping serves both."""
        k = len(chunks)
        fb_dev = self._put_group(
            {
                key: np.stack([c[0][key] for c in chunks])
                for key in chunks[0][0]
            },
            None,
            "n",
        )
        tok_dev = self._put_group(
            {
                key: np.stack([c[1][key] for c in chunks])
                for key in chunks[0][1]
            },
            None,
            "n",
        )
        chunk = tok_dev["spath"].shape[1]
        row_fb = np.zeros((k, chunk), bool)
        for i, c in enumerate(chunks):
            row_fb[i, : len(c[2])] = c[2]
        n_valids = [c[3] for c in chunks]
        ov_dev = None
        ov_key: Tuple = ()
        if ov is not None:
            self._tables_device()  # ensure _fused_cols is current
            ov_host: Dict[str, np.ndarray] = {
                "member": np.asarray(ov["member"]),
                "capture": np.asarray(ov["capture"]),
            }
            b_pad = ov["member"].shape[0]
            tabs = ov.get("tabs") or {}
            for kind, cols in self._fused_cols.items():
                if kind in ("pat_member", "pat_capture"):
                    continue
                dt = {"vid_bool": np.bool_, "vid_i32": np.int32,
                      "vid_f32": np.float32}[kind]
                slab = np.zeros((b_pad, len(cols)), dt)
                for name, col in cols.items():
                    t = tabs.get(name)
                    if t is not None:
                        slab[:, col] = t.astype(dt)
                ov_host[kind] = slab
            ov_dev = self._put_group(ov_host)
            ov_key = (b_pad, tuple(sorted(ov_dev)))
        return StackedCorpus(
            fb_dev=fb_dev,
            tok_dev=tok_dev,
            row_fb=self._put(row_fb, None, "n"),
            n_valid=self._put(np.asarray(n_valids, np.int32)),
            n_valids=n_valids,
            k=k,
            chunk=chunk,
            key=(
                k,
                chunk,
                tok_dev["spath"].shape,
                fb_dev["group_id"].shape,
                ov_key,
            ),
            row_dev={},
            ov_dev=ov_dev,
            v_base=v_base,
        )

    def stage_row_feats(
        self, corpus: StackedCorpus, feats: Dict[str, np.ndarray],
        volatile: Sequence[str] = (),
    ) -> None:
        """Ship per-row feature bits ([N] bool each) to device as
        [K, chunk] planes alongside the stacked corpus. Names already
        staged are skipped (invdup bits are per-corpus-constant) unless
        listed in `volatile` — external-data bits track the live
        response cache, so a persistent audit corpus restages them
        every dispatch."""
        for name, arr in feats.items():
            if name in corpus.row_dev and name not in volatile:
                continue
            plane = np.zeros((corpus.k, corpus.chunk), bool)
            flat = np.asarray(arr, bool)
            for ci in range(corpus.k):
                start = ci * corpus.chunk
                end = min(start + corpus.chunk, flat.shape[0])
                if end > start:
                    plane[ci, : end - start] = flat[start:end]
            corpus.row_dev[name] = self._put(plane, None, "n")

    def dispatch_need_all(
        self,
        policy: StagedPolicy,
        corpus: StackedCorpus,
        g: int,
        r_cap: int = 1024,
        require_compiled: bool = False,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Whole-corpus sweep in ONE device execution + ONE fetch.

        lax.map runs the per-chunk need computation (match x programs x
        hot-row compaction — see dispatch_need) over the stacked chunk
        axis; outputs come back stacked: packed [K, C_pad*R/8] uint8,
        hot [K, R] int32, n_hot [K], compiled/interp pair stats [K].
        Chunks whose n_hot exceeds r_cap are re-dispatched individually
        by the caller (rare: violating rows are sparse in steady state).

        require_compiled=True raises ColdKernel instead of compiling
        when this (policy, shape-bucket) has no jit entry yet — the
        serve-while-compiling admission path uses it so a novel batch
        bucket serves on the interpreter rather than stalling every
        in-flight request on an inline XLA compile.
        """
        r_cap = min(r_cap, corpus.chunk)
        row_dev = corpus.row_dev or {}
        key = (
            "need_all", policy.key, corpus.key, g, r_cap,
            tuple(sorted(row_dev)),
        )
        entry = self._jit_cache.get(key)
        if entry is None and require_compiled:
            self._note_cache("need_all", "cold")
            raise ColdKernel(f"no compiled entry for {key[:3]}")
        was_miss = entry is None
        self._note_cache("need_all", "miss" if was_miss else "hit")
        if entry is None:
            need_chunk = self._need_chunk_fn(policy, g, r_cap)

            def run_all(ms_in, spec_map, fb_in, tok_in, tabs_in,
                        consts_in, compiled_mask, row_fb, n_valid,
                        row_in, ov_in, vb):
                def body(xs):
                    fb_c, tok_c, rf_c, nv_c, row_c = xs
                    return need_chunk(
                        ms_in, spec_map, fb_c, tok_c, tabs_in,
                        consts_in, compiled_mask, rf_c, nv_c, row_c,
                        ov_in=ov_in, v_base=vb,
                    )

                packed, hot, n_hot, sc, si = jax.lax.map(
                    body, (fb_in, tok_in, row_fb, n_valid, row_in)
                )
                # fuse the five outputs into ONE int32 buffer: a
                # device->host fetch pays the tunnel RTT per ARRAY (the
                # copies do not overlap), so five leaves cost five RTTs.
                # Bytes pack into words with EXPLICIT little-endian
                # shifts (bitcast_convert_type's byte assembly is
                # platform-defined; the host unpack views '<u4')
                k_chunks, p8 = packed.shape
                pad = (-p8) % 4
                pw = (
                    jnp.pad(packed, ((0, 0), (0, pad)))
                    .reshape(k_chunks, (p8 + pad) // 4, 4)
                    .astype(jnp.int32)
                )
                pwords = (
                    pw[..., 0]
                    | (pw[..., 1] << 8)
                    | (pw[..., 2] << 16)
                    | (pw[..., 3] << 24)
                )
                return jnp.concatenate(
                    [
                        pwords,
                        hot,
                        n_hot[:, None],
                        sc[:, None],
                        si[:, None],
                    ],
                    axis=1,
                )

            entry = [run_all, jax.jit(run_all)]
            self._jit_cache[key] = entry
        tabs = self._tables_device()
        t_call = time.perf_counter()
        out = entry[1](
            policy.ms_dev,
            policy.spec_map,
            corpus.fb_dev,
            corpus.tok_dev,
            tabs,
            policy.stacked_consts,
            policy.compiled_mask,
            corpus.row_fb,
            corpus.n_valid,
            row_dev,
            corpus.ov_dev or {},
            jnp.int32(corpus.v_base),
        )
        if was_miss:
            self._note_compile("need_all", time.perf_counter() - t_call)
        buf = np.asarray(out)  # ONE transfer for the whole sweep
        # unpack (see run_all): [pwords | hot | n_hot | sc | si]
        r_eff = min(r_cap, corpus.chunk)
        p8 = -(-policy.c_pad * r_eff // 8)
        w4 = -(-p8 // 4)
        packed = (
            np.ascontiguousarray(buf[:, :w4])
            .astype("<u4")
            .view(np.uint8)
            .reshape(corpus.k, -1)[:, :p8]
        )
        hot = buf[:, w4:w4 + r_eff]
        n_hot = buf[:, w4 + r_eff]
        sc = buf[:, w4 + r_eff + 1]
        si = buf[:, w4 + r_eff + 2]
        return packed, hot, n_hot, sc, si

    def _need_chunk_fn(self, policy: StagedPolicy, g, r_cap: int):
        """The shared per-chunk need computation (trace-time closure
        over the policy's program groups)."""
        g0_, g1_ = _g01(g)
        group_exprs = policy.group_exprs
        group_rows = policy.group_rows
        group_cmaps = policy.group_cmaps

        def need_chunk(ms_in, spec_map, fb_in, tok_in, tabs_in,
                       consts_in, compiled_mask, row_fb, n_valid,
                       row_in=None, ov_in=None, v_base=None):
            from ..engine.exprs import EvalCtx

            # [U+1, N] over distinct specs, gathered back to [C_pad, N]
            match_u = match_matrix(ms_in, fb_in)
            match = match_u[spec_map]
            str_tabs = {
                k: v
                for k, v in tabs_in.items()
                if k not in ("pat_member", "pat_capture")
                and not k.endswith("!T")
            }
            has_ov = bool(ov_in)

            def two_level(base_tab, ov_tab, ids):
                """Gather rows by id: base table below v_base, the
                batch's overlay block above (ephemeral vocab ids)."""
                rows = base_tab.shape[0]
                base = base_tab[jnp.clip(ids, 0, rows - 1)]
                if not has_ov or ov_tab is None:
                    return base
                loc = ids - v_base
                b = ov_tab.shape[0]
                ov = ov_tab[jnp.clip(loc, 0, b - 1)]
                return jnp.where((loc >= 0)[..., None], ov, base)

            # fused pre-gathers, ONCE per chunk in the outer trace and
            # shared by every group and vmap lane (each expression node
            # slices its column); XLA DCEs any slab no node touches
            slabs = {}
            if "pat_member!T" in tabs_in:
                safe_sp = jnp.maximum(tok_in["spath"], 0)
                slabs["pat_member"] = two_level(
                    tabs_in["pat_member!T"],
                    ov_in.get("member") if has_ov else None,
                    safe_sp,
                )
                slabs["pat_capture"] = two_level(
                    tabs_in["pat_capture!T"],
                    ov_in.get("capture") if has_ov else None,
                    safe_sp,
                )
            safe_vid = jnp.maximum(tok_in["vid"], 0)
            for kind in ("vid_bool", "vid_i32", "vid_f32"):
                if kind + "!T" in tabs_in:
                    slabs[kind] = two_level(
                        tabs_in[kind + "!T"],
                        ov_in.get(kind) if has_ov else None,
                        safe_vid,
                    )
            slab_cols = self._fused_cols
            ov_cols = None
            if has_ov:
                ov_cols = {
                    name: (kind, col)
                    for kind, cols in self._fused_cols.items()
                    if kind not in ("pat_member", "pat_capture")
                    for name, col in cols.items()
                }
            viol = jnp.zeros(match.shape, bool)
            for expr, grows, cmap, consts_k in zip(
                group_exprs, group_rows, group_cmaps, consts_in
            ):

                def eval_one(consts):
                    ctx = EvalCtx(
                        np=jnp,
                        tok=tok_in,
                        pat_member=tabs_in["pat_member"],
                        pat_capture=tabs_in["pat_capture"],
                        str_tables=str_tabs,
                        consts=consts,
                        g0=g0_,
                        g1=g1_,
                        slabs=slabs,
                        slab_cols=slab_cols,
                        row=row_in,
                        v_base=v_base if has_ov else None,
                        ov_slabs=ov_in if has_ov else None,
                        ov_cols=ov_cols,
                    )
                    return expr.emit(ctx).astype(jnp.int32)

                if consts_k:
                    # [Ku, N] over distinct const sets, gathered out
                    # to the group's constraint rows
                    out_u = jax.vmap(eval_one)(consts_k) > 0
                    out_k = out_u[jnp.asarray(cmap)]
                else:
                    one = eval_one({}) > 0
                    out_k = jnp.broadcast_to(
                        one, (len(grows),) + one.shape
                    )
                viol = viol.at[jnp.asarray(grows)].set(out_k)

            valid_n = jnp.arange(match.shape[1]) < n_valid
            fallback = (~compiled_mask[:, None]) | row_fb[None, :]
            need = match & (viol | fallback) & valid_n[None, :]
            stat_c = jnp.sum(
                match & compiled_mask[:, None] & ~row_fb[None, :]
                & valid_n[None, :]
            )
            stat_i = jnp.sum(match & fallback & valid_n[None, :])
            # hot-row compaction: nonzero over [N] is cheap; the
            # full-matrix nonzero/transpose is not
            rowany = need.any(axis=0)  # [N]
            n_hot = rowany.sum().astype(jnp.int32)
            hot = jnp.nonzero(rowany, size=r_cap, fill_value=-1)[0]
            sub = need[:, jnp.maximum(hot, 0)] & (hot >= 0)[None, :]
            return (
                jnp.packbits(sub.reshape(-1)),  # c-major over R cols
                hot.astype(jnp.int32),
                n_hot,
                stat_c.astype(jnp.int32),
                stat_i.astype(jnp.int32),
            )

        return need_chunk

    def dispatch_need(
        self,
        policy: StagedPolicy,
        batch: StagedBatch,
        g: int,
        block: bool = True,
        r_cap: int = 4096,
        row_in: Optional[Dict[str, Any]] = None,
        ov_in: Optional[Dict[str, Any]] = None,
        v_base: int = 0,
        require_compiled: bool = False,
    ) -> Tuple[Any, Any, Any, Any, Any]:
        """-> (packed hot-row need bits [C_pad x R / 8] uint8 c-major,
        hot row ids [R] int32, n_hot, compiled_pairs, interp_pairs) for
        one staged chunk.

        The need matrix is compacted on device to the rows that have any
        needing pair (violating reviews are sparse in steady state):
        a [N]-sized nonzero picks the hot rows, a gather extracts their
        [C_pad, R] need columns, and only that bitmap leaves the device
        (~C_pad*R/8 bytes — the full [C_pad, N] bitmap is a multi-MB
        transfer and device-side full nonzero costs a ~150ms scatter
        pass plus a ~400ms transpose per chunk on v5e). `n_hot` may
        exceed r_cap: callers re-dispatch with a larger cap
        (TpuDriver._need_pairs does). Stats count matched pairs on the
        compiled vs interpreter routes (valid rows only).

        With block=False the outputs come back as device arrays without
        synchronizing — callers dispatch every chunk first, then resolve
        with one device_get each, so chunk k+1's compute overlaps chunk
        k's host decode. `n_valid` rides as a runtime scalar: any chunk
        occupancy reuses one compiled program per (policy, shape-bucket,
        r_cap).
        """
        n_pad = batch.tok_dev["spath"].shape[0]
        r_cap = min(r_cap, n_pad)
        row_in = row_in or {}
        ov_in = ov_in or {}
        key = ("need", policy.key, batch.key, g, r_cap,
               tuple(sorted(row_in)), tuple(sorted(ov_in)))
        entry = self._jit_cache.get(key)
        if entry is None and require_compiled:
            self._note_cache("need", "cold")
            raise ColdKernel(f"no compiled entry for {key[:3]}")
        was_miss = entry is None
        self._note_cache("need", "miss" if was_miss else "hit")
        if entry is None:
            run_need = self._need_chunk_fn(policy, g, r_cap)
            entry = [run_need, jax.jit(run_need)]
            self._jit_cache[key] = entry
        tabs = self._tables_device()
        t_call = time.perf_counter()
        out = entry[1](
            policy.ms_dev,
            policy.spec_map,
            batch.fb_dev,
            batch.tok_dev,
            tabs,
            policy.stacked_consts,
            policy.compiled_mask,
            batch.row_fb,
            jnp.int32(batch.n_valid),
            row_in,
            ov_in,
            jnp.int32(v_base),
        )
        if was_miss:
            self._note_compile("need", time.perf_counter() - t_call)
        if not block:
            return out
        packed, hot, n_hot, stat_c, stat_i = _get_overlapped(out)
        return packed, hot, int(n_hot), int(stat_c), int(stat_i)

    # -- dispatch ------------------------------------------------------------

    def prepare(
        self,
        programs: Sequence[Optional[Program]],
        ms: Dict[str, np.ndarray],
        fb: Dict[str, np.ndarray],
        tok: Dict[str, np.ndarray],
        g: int,
    ):
        """Build (fn, args, (c, n)) for one dispatch: `fn(*args)` returns
        (match, counts, totals) padded; fn is an un-jitted closure so
        callers (the harness entry point) may compile-check it themselves.
        """
        c = next(iter(ms.values())).shape[0]
        n = next(iter(fb.values())).shape[0]
        compiled = [p for p in programs if p is not None]
        prog_c_rows = [i for i, p in enumerate(programs) if p is not None]

        # Group programs by structural signature (same template control
        # flow + const shapes): one traced subgraph per group, vmapped
        # over the stacked const tensors. A 500-constraint population of
        # ~8 templates traces ~8 subgraphs, not 500 — constraints differ
        # only in the consts they pass (engine/programs.py docstring).
        groups: Dict[Tuple, Dict[str, Any]] = {}
        for out_row, p in enumerate(compiled):
            gkey = (
                p.signature,
                tuple(sorted((k, v.shape) for k, v in p.consts.items())),
            )
            grp = groups.setdefault(
                gkey, {"expr": p.expr, "rows": [], "consts": []}
            )
            grp["rows"].append(out_row)
            grp["consts"].append(p.consts)

        c_mult = self.mesh.shape["c"] if self.mesh else 1
        n_mult = self.mesh.shape["n"] if self.mesh else 1

        ms_dev = {
            k: self._put(_pad_axis(np.asarray(v), 0, c_mult, _ms_fill(k)), "c")
            for k, v in ms.items()
        }
        fb_dev = {
            k: self._put(_pad_axis(np.asarray(v), 0, n_mult, _fb_fill(k)), "n")
            for k, v in fb.items()
        }
        tok_dev = {
            k: self._put(
                _pad_axis(np.asarray(v), 0, n_mult, 0.0 if k == "vnum" else -1),
                "n",
            )
            for k, v in tok.items()
        }
        tabs = self._tables_device()
        # per-group stacked consts: dict name -> [K, ...] device array
        group_list = list(groups.values())
        stacked_consts = [
            {
                k: self._put(np.stack([cd[k] for cd in grp["consts"]]))
                for k in grp["consts"][0]
            }
            for grp in group_list
        ]

        key = (
            tuple(gk for gk in groups),
            tuple(tuple(grp["rows"]) for grp in group_list),
            tuple(prog_c_rows),
            g,
            n,
            tok_dev["spath"].shape,
            fb_dev["group_id"].shape,
            ms_dev["kind_rows"].shape,
            id(self.mesh),
        )
        entry = self._jit_cache.get(key)
        fn = entry[0] if entry is not None else None
        if fn is None:
            n_compiled = len(compiled)
            group_exprs = [grp["expr"] for grp in group_list]
            group_rows = [list(grp["rows"]) for grp in group_list]
            rows = list(prog_c_rows)

            def run_fused(ms_in, fb_in, tok_in, tabs_in, consts_in):
                from ..engine.exprs import EvalCtx

                match = match_matrix(ms_in, fb_in)  # [C, N]
                str_tabs = {
                    k: v
                    for k, v in tabs_in.items()
                    if k not in ("pat_member", "pat_capture")
                }
                if group_exprs:
                    n_pad = tok_in["spath"].shape[0]
                    counts = jnp.zeros((n_compiled, n_pad), jnp.int32)
                    for expr, grows, consts_k in zip(
                        group_exprs, group_rows, consts_in
                    ):

                        def eval_one(consts):
                            g0_, g1_ = _g01(g)
                            ctx = EvalCtx(
                                np=jnp,
                                tok=tok_in,
                                pat_member=tabs_in["pat_member"],
                                pat_capture=tabs_in["pat_capture"],
                                str_tables=str_tabs,
                                consts=consts,
                                g0=g0_,
                                g1=g1_,
                            )
                            return expr.emit(ctx).astype(jnp.int32)

                        if consts_k:
                            out_k = jax.vmap(eval_one)(consts_k)  # [K, N]
                        else:
                            # const-free program: every constraint in the
                            # group computes the same counts
                            one = eval_one({})
                            out_k = jnp.broadcast_to(
                                one, (len(grows),) + one.shape
                            )
                        counts = counts.at[jnp.asarray(grows)].set(out_k)
                    # scatter compiled counts back onto constraint rows so
                    # totals line up with the full constraint set
                    viol = jnp.zeros(match.shape, jnp.int32)
                    viol = viol.at[jnp.asarray(rows)].set(counts)
                else:
                    counts = None
                    viol = jnp.zeros(match.shape, jnp.int32)
                # mask padded resource rows (wildcard constraints match
                # the all-pad feature rows) before reducing
                valid_n = jnp.arange(match.shape[1]) < n
                # the one collective: per-constraint totals reduce over
                # the sharded "n" axis (GSPMD all-reduce)
                totals = jnp.sum(
                    (jnp.where(match, viol, 0) > 0) & valid_n[None, :], axis=1
                ).astype(jnp.int32)
                return match, counts, totals

            fn = run_fused
            self._jit_cache[key] = [fn, None]
        return fn, (ms_dev, fb_dev, tok_dev, tabs, stacked_consts), (c, n, key)

    def run(
        self,
        programs: Sequence[Optional[Program]],
        ms: Dict[str, np.ndarray],
        fb: Dict[str, np.ndarray],
        tok: Dict[str, np.ndarray],
        g: int,
    ) -> Tuple[np.ndarray, Optional[np.ndarray], np.ndarray]:
        """-> (match [C, N] bool, counts [Cc, N] int32 | None,
                totals [C] int32 per-constraint compiled-path violation
                totals).

        `programs` is index-aligned with the C constraint rows; None
        entries (interpreter-fallback templates) contribute no counts and
        no totals."""
        fn, args, (c, n, key) = self.prepare(programs, ms, fb, tok, g)
        entry = self._jit_cache[key]
        if entry[1] is None:
            entry[1] = jax.jit(fn)
        match_p, counts_p, totals_p = entry[1](*args)
        match = np.asarray(match_p)[:c, :n]
        counts = None if counts_p is None else np.asarray(counts_p)[:, :n]
        totals = np.asarray(totals_p)[:c]
        return match, counts, totals


def decode_need(
    packed: np.ndarray, hot: np.ndarray, c_pad: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Packed hot-row need bits -> (n_idx, c_idx) arrays sorted
    review-major (matching the interpreter driver's emit order)."""
    hot = np.asarray(hot)
    r = hot.shape[0]
    bits = np.unpackbits(np.asarray(packed))[: c_pad * r]
    c_is, j_is = np.nonzero(bits.reshape(c_pad, r))
    n_loc = hot[j_is]
    order = np.lexsort((c_is, n_loc))
    return n_loc[order], c_is[order]


def _ms_fill(key: str):
    """Pad constraint rows so they match nothing: all-pad kind selectors
    (-1 rows are invalid) and inert selector/scope fields."""
    if key in ("ns_has", "excl_has", "nssel_has", "nssel_matches_empty",
               "lab_invalid", "nssel_invalid"):
        return False
    if key == "scope":
        return 0  # SCOPE_ABSENT
    return -1


def _fb_fill(key: str):
    if key in (
        "kind_defined",
        "is_ns",
        "has_namespace",
        "obj_present",
        "old_present",
        "nssel_defined",
        "nssel_empty",
        "label_overflow",
    ):
        return False
    return -1
