"""Device fault domains: partitioned program dispatch.

One monolithic fused program made every device fault a whole-plane
event: the single per-plane breaker tripped and EVERY request degraded
to the host interpreter (docs/robustness.md §Fault domains). This
module splits the staged constraint corpus into K independently
compilable/dispatchable sub-programs — partitions — each homed on a
logical device and guarded by its own per-(device, plane)
`CircuitBreaker`, so one sick chip sheds exactly its constraint subset
and nothing else:

  * `PartitionPlan` — a deterministic split of the constraint corpus
    (the driver's sorted `<kind>/<name>` identities, round-robin over K
    partitions) with a device assignment per partition. The plan
    rebuilds on constraint churn and on device-health changes, and
    `to_dict()` is surfaced in `/readyz` and the partition metrics.
  * `PartitionDispatcher` — the quarantine manager: lazily creates the
    per-device breakers, re-homes a quarantined device's partitions
    onto healthy devices (restage with exponential backoff through the
    `driver.restage[device=N]` fault point), runs half-open probes
    against quarantined devices on the breaker's own recovery
    schedule, and degrades to the existing whole-plane host mode only
    when every device is dead.
  * `merge_partition_results` — the parity-preserving merge: combined
    per-partition verdicts are bit-identical to the monolithic dispatch
    (autorejects first, then evaluation results, both in the global
    constraint order; pinned by the partition parity battery in
    tests/test_partition.py).

Devices here are *logical* fault domains (ids into the plan's device
slots). On provisioned multi-chip hardware (ROADMAP item 3) the slots
map to real chips; on a single-device host they still buy deterministic
fault isolation because every device-attributed code path — dispatch,
restage, probe — flows through the device-labeled fault points in
`faults/injection.py`. The partition boundary this creates is the same
one per-batch constraint pruning (ROADMAP item 1) dispatches over.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..faults import CLOSED, CircuitBreaker

__all__ = [
    "Partition",
    "PartitionPlan",
    "PartitionDispatcher",
    "build_plan",
    "merge_partition_results",
]


@dataclass(frozen=True)
class Partition:
    """One fault domain's constraint subset + device placement."""

    index: int
    home_device: int  # the deterministic assignment
    device: int  # where it actually runs (≠ home while re-homed)
    keys: Tuple[str, ...]  # constraint identities, global-sorted
    subset: frozenset  # frozenset(keys) — the driver-facing form

    def to_dict(self) -> Dict[str, Any]:
        return {
            "index": self.index,
            "home_device": self.home_device,
            "device": self.device,
            "constraints": len(self.keys),
        }


@dataclass
class PartitionPlan:
    """A deterministic constraint-corpus split with device placement."""

    generation: int
    constraint_gen: Any
    partitions: List[Partition]
    # constraint key -> global index: the merge order (the driver's
    # sorted (kind, name) iteration order — exactly what the monolith
    # emits in)
    order: Dict[str, int]
    devices: Tuple[int, ...]
    all_dead: bool = False
    # constraint keys the corpus analyzer proved dead (and free of the
    # ns-selector autoreject path) — excluded from every dispatch row;
    # verdict-safe by the corpus parity battery (docs/analysis.md)
    excluded_static: Tuple[str, ...] = ()

    def to_dict(self) -> Dict[str, Any]:
        return {
            "generation": self.generation,
            "constraints": len(self.order),
            "devices": list(self.devices),
            "all_dead": self.all_dead,
            "excluded_static": list(self.excluded_static),
            "partitions": [p.to_dict() for p in self.partitions],
        }


def _guided_split(
    keys: Sequence[str],
    k_eff: int,
    costs: Dict[str, float],
    locality: Dict[str, str],
    order: Dict[str, int],
) -> List[Tuple[str, ...]]:
    """Cost/locality-guided corpus split. Keys sharing a locality token
    (constraints whose match blocks are identical — they fire on exactly
    the same reviews) are co-located so a batch with namespace/kind
    affinity touches 1-2 hot partitions instead of all K; groups are
    packed into K bins by greedy LPT on cost (measured device seconds
    when available, static compile cost otherwise). Deterministic: ties
    break on global key order, so same inputs always give the same plan.
    """
    grouped: Dict[str, List[str]] = {}
    for key in keys:
        # a key without a locality token is its own group (no false
        # co-location); "!key:" cannot collide with JSON match tokens
        grouped.setdefault(
            locality.get(key, f"!key:{key}"), []
        ).append(key)

    def g_cost(gkeys: List[str]) -> float:
        return max(sum(costs.get(k2, 1.0) for k2 in gkeys), 1e-9)

    def g_first(gkeys: List[str]) -> int:
        return min(order[k2] for k2 in gkeys)

    groups: List[List[str]] = sorted(
        grouped.values(), key=g_first
    )
    # fewer locality groups than partitions: split the costliest
    # multi-key group (alternating keys, preserving internal balance)
    # until every partition slot has a group — degenerates to ~round-
    # robin when the whole corpus shares one match block
    while len(groups) < k_eff:
        cand = max(
            (g for g in groups if len(g) > 1),
            key=lambda g: (g_cost(g), -g_first(g)),
            default=None,
        )
        if cand is None:
            break
        groups.remove(cand)
        groups.extend([cand[0::2], cand[1::2]])
    # greedy LPT: heaviest group first onto the lightest bin
    bins: List[List[str]] = [[] for _ in range(k_eff)]
    loads = [0.0] * k_eff
    for g in sorted(groups, key=lambda g: (-g_cost(g), g_first(g))):
        i = min(range(k_eff), key=lambda j: (loads[j], j))
        bins[i].extend(g)
        loads[i] += g_cost(g)
    bins = [b for b in bins if b]
    bins.sort(key=lambda b: min(order[k2] for k2 in b))
    return [
        tuple(sorted(b, key=lambda k2: order[k2])) for b in bins
    ]


def build_plan(
    keys: Sequence[str],
    k: int,
    devices: Sequence[int],
    healthy: frozenset,
    constraint_gen: Any = None,
    generation: int = 0,
    costs: Optional[Dict[str, float]] = None,
    locality: Optional[Dict[str, str]] = None,
) -> PartitionPlan:
    """Deterministic plan. Without planner inputs, partition p takes
    every k-th key of the sorted identity list (`keys[p::k]` — balanced
    within one constraint and rebalanced by construction on churn).
    With `costs`/`locality` (the dispatcher supplies both from the
    driver + CostAttributor), the split is cost/locality-guided instead
    (_guided_split) so mask-gated pruning can skip cold partitions.
    Either way a partition homes on `devices[p % len(devices)]`; a
    partition whose home device is not healthy re-homes onto the
    healthy device chosen round-robin by partition index — same inputs,
    same plan, always."""
    keys = list(keys)
    order = {key: i for i, key in enumerate(keys)}
    k_eff = min(max(1, int(k)), len(keys)) if keys else 0
    healthy_list = sorted(d for d in devices if d in healthy)
    if (costs is None and locality is None) or not k_eff:
        key_sets = [tuple(keys[p::k_eff]) for p in range(k_eff)]
    else:
        key_sets = _guided_split(
            keys, k_eff, costs or {}, locality or {}, order
        )
    partitions: List[Partition] = []
    for p, pkeys in enumerate(key_sets):
        home = devices[p % len(devices)]
        if home in healthy:
            device = home
        elif healthy_list:
            device = healthy_list[p % len(healthy_list)]
        else:
            device = home  # all dead: flagged below, never dispatched
        partitions.append(
            Partition(
                index=p,
                home_device=home,
                device=device,
                keys=pkeys,
                subset=frozenset(pkeys),
            )
        )
    return PartitionPlan(
        generation=generation,
        constraint_gen=constraint_gen,
        partitions=partitions,
        order=order,
        devices=tuple(devices),
        all_dead=not healthy_list,
    )


def _blend_costs(
    keys: Sequence[str],
    static: Optional[Dict[str, float]],
    measured: Optional[Dict[str, float]],
) -> Optional[Dict[str, float]]:
    """Planner cost blend: measured per-constraint device seconds (the
    CostAttributor's table) win where available; constraints without a
    measurement fall back to static compile cost, rescaled so the two
    populations are comparable (static mean matched to measured mean).
    None when neither source has anything — build_plan then stays
    round-robin."""
    if not static and not measured:
        return None
    static = static or {}
    pos = {
        k: v for k, v in (measured or {}).items() if v > 0.0
    }
    if not pos:
        return dict(static) or None
    m_mean = sum(pos.values()) / len(pos)
    s_vals = [static.get(k, 1.0) for k in pos]
    s_mean = (sum(s_vals) / len(s_vals)) or 1.0
    scale = m_mean / s_mean
    return {
        key: pos[key] if key in pos else static.get(key, 1.0) * scale
        for key in keys
    }


def merge_partition_results(
    result_lists: Sequence[Sequence[Any]], order: Dict[str, int]
) -> List[Any]:
    """Merge one request's per-partition Result lists back into the
    monolithic emit order: autoreject results first, then evaluation
    results, each group in global constraint order; within one
    (request, constraint) pair the partition's own result order is
    preserved (stable sort). The partition parity battery pins
    merged == monolith across constraint/partition counts."""
    from ..constraint.driver import AUTOREJECT_MSG, constraint_key

    merged = [r for results in result_lists for r in results]
    fallback = len(order)

    def sort_key(r):
        c = getattr(r, "constraint", None) or {}
        return (
            0 if getattr(r, "msg", None) == AUTOREJECT_MSG else 1,
            order.get(constraint_key(c), fallback),
        )

    merged.sort(key=sort_key)
    return merged


class PartitionDispatcher:
    """Plan + per-device breakers + quarantine lifecycle for one
    admission plane (the MicroBatcher's `partitioner`).

    Thread-safety: the plan/breaker registry is lock-protected;
    breaker transition listeners only write plain flags (never take
    this lock — the breaker calls listeners under ITS lock, and plan
    builds read breaker state under ours, so a listener acquiring our
    lock would be an AB-BA deadlock). Device health is derived from
    breaker state at plan-build time instead of being pushed from the
    listener for exactly that reason.
    """

    def __init__(
        self,
        client,
        target: str,
        k: int,
        devices: Optional[Sequence[int]] = None,
        plane: str = "validation",
        metrics=None,
        tracer=None,
        failure_threshold: int = 3,
        recovery_seconds: float = 30.0,
        restage_backoff_s: float = 0.5,
        restage_backoff_max_s: float = 30.0,
        clock=time.monotonic,
        # called once per lazily created device breaker (the soak
        # harness subscribes its transition ledger here)
        breaker_listener=None,
        probe_batch: int = 8,
        # obs.FlightRecorder: per-device breaker OPENs and operator
        # quarantines trip a postmortem capture (docs/observability.md)
        recorder=None,
        # obs.CostAttributor: measured per-constraint device seconds
        # feed the cost/locality planner (and /debug/partitions shares)
        attributor=None,
        # replica name stamped on /debug/partitions, like /debug/costs
        replica: Optional[str] = None,
        # analysis.corpus.CorpusPlane: provably-dead constraint keys are
        # excluded from dispatch rows (generation-matched; a stale or
        # absent corpus report prunes nothing)
        corpus=None,
    ):
        self.client = client
        self.target = target
        self.k = max(1, int(k))
        if devices is None:
            devices = range(self.k)
        elif isinstance(devices, int):
            devices = range(devices)
        self.devices: Tuple[int, ...] = tuple(int(d) for d in devices)
        if not self.devices:
            raise ValueError("partition dispatch needs >= 1 device")
        self.plane = plane
        self.metrics = metrics
        self.tracer = tracer
        self.failure_threshold = failure_threshold
        self.recovery_seconds = recovery_seconds
        self.restage_backoff_s = restage_backoff_s
        self.restage_backoff_max_s = restage_backoff_max_s
        self.probe_batch = probe_batch
        self._clock = clock
        self._breaker_listener = breaker_listener
        self.recorder = recorder
        self.attributor = attributor
        self.replica = replica
        self.corpus = corpus
        self._lock = threading.RLock()
        self._touched: List[int] = []  # per-batch partitions touched
        self._plan_costs: Dict[str, Dict[str, float]] = {}
        self._breakers: Dict[int, CircuitBreaker] = {}
        self._manual_quarantine: set = set()
        # why each manually-quarantined device is out ("manual" |
        # "corruption"); corruption entries only clear through heal()
        self._quarantine_reasons: Dict[int, str] = {}
        self._plan: Optional[PartitionPlan] = None
        self._plan_key: Any = None
        self._plan_gen = 0
        # staged tokens: (subset, device, signature) when the driver
        # exposes content signatures (docs/compile.md — churn that
        # changes a partition's signature invalidates exactly that
        # token), else the legacy (plan_gen, partition idx, device)
        self._staged: set = set()
        self._staged_parts: set = set()  # partition indexes ever staged
        self._staging: set = set()  # (subset, device) restages in flight
        self._retry_at: Dict[int, float] = {}  # device -> next restage
        self._backoff: Dict[int, float] = {}
        self._executor: Optional[ThreadPoolExecutor] = None
        self._closed = False
        self.fleet = None
        # accounting (snapshot/readyz/bench)
        self.rehomes = 0
        self.probes = 0
        self.restage_failures = 0
        self.dispatches: Dict[str, int] = {
            "fused": 0, "host": 0, "failed": 0, "skipped": 0,
        }

    # -- breakers --------------------------------------------------------------

    def breaker(self, device: int) -> CircuitBreaker:
        """The per-(device, plane) breaker, created lazily — named
        `device:<plane>:<device_id>`, the same key it registers under
        in the fleet plane so a chip sick on one replica pre-opens the
        SAME device's breaker on peers."""
        created = None
        with self._lock:
            b = self._breakers.get(device)
            if b is None:
                b = created = CircuitBreaker(
                    failure_threshold=self.failure_threshold,
                    recovery_seconds=self.recovery_seconds,
                    plane=self.plane,
                    device=device,
                    metrics=self.metrics,
                    tracer=self.tracer,
                    clock=self._clock,
                    recorder=self.recorder,
                )
                self._breakers[device] = b
        if created is not None:
            if self._breaker_listener is not None:
                try:
                    self._breaker_listener(created)
                except Exception:
                    pass
            if self.fleet is not None:
                try:
                    self.fleet.register_breaker(created.name, created)
                except Exception:
                    pass
        return b

    def set_fleet(self, fleet) -> None:
        """Gossip per-device breaker state: register every breaker —
        existing and future — under its `device:<plane>:<device_id>`
        key (docs/fleet.md; the ROADMAP item 2 follow-up)."""
        self.fleet = fleet
        with self._lock:
            existing = list(self._breakers.values())
        for b in existing:
            try:
                fleet.register_breaker(b.name, b)
            except Exception:
                pass

    def _device_healthy(self, device: int) -> bool:
        if device in self._manual_quarantine:
            return False
        b = self._breakers.get(device)
        # HALF_OPEN stays quarantined: the device rejoins the pool only
        # after its probe (run_probes) actually closes the breaker
        return b is None or b.state == CLOSED

    def quarantine(self, device: int, reason: str = "manual") -> None:
        """Operator/scenario/integrity quarantine: take the device out
        of the pool immediately (its partitions re-home on the next
        plan build) without touching its breaker. `reason` separates
        the semantics (docs/robustness.md §Verdict integrity):
        "manual" is an operator decision, "corruption" is the
        verdict-integrity plane's SDC verdict — both use the same
        mechanics, but a corruption quarantine heals ONLY through a
        clean golden self-test (IntegrityPlane.selftest), never a
        probe/timer."""
        with self._lock:
            self._manual_quarantine.add(int(device))
            self._quarantine_reasons[int(device)] = str(reason)
        self._export_quarantine()
        if self.metrics is not None:
            self.metrics.record(
                "device_quarantine_total", 1,
                plane=self.plane, reason=str(reason),
            )
        if self.recorder is not None:
            try:
                self.recorder.trigger(
                    "device_quarantine", plane=self.plane,
                    device=int(device), manual=True,
                    reason=str(reason),
                )
            except Exception:
                pass

    def heal(self, device: int) -> None:
        """Lift an operator/integrity quarantine (a breaker-driven
        quarantine heals through its own probe cycle instead)."""
        with self._lock:
            self._manual_quarantine.discard(int(device))
            self._quarantine_reasons.pop(int(device), None)
        self._export_quarantine()

    def _export_quarantine(self) -> None:
        if self.metrics is None:
            return
        for d in self.devices:
            self.metrics.gauge(
                "device_quarantine_state",
                0 if self._device_healthy(d) else 1,
                plane=self.plane, device=str(d),
            )

    # -- the plan --------------------------------------------------------------

    def plan(self) -> Optional[PartitionPlan]:
        """The current plan, rebuilt deterministically whenever the
        constraint corpus churns or device health changes (quarantine
        re-homes, heal restores homes). None when the driver has no
        partitionable constraint corpus."""
        driver = getattr(self.client, "_driver", None)
        keys_fn = getattr(driver, "constraint_keys", None)
        if keys_fn is None:
            return None
        gen_fn = getattr(driver, "constraint_generation", None)
        gen = gen_fn() if gen_fn is not None else None
        healthy = frozenset(
            d for d in self.devices if self._device_healthy(d)
        )
        excluded: frozenset = frozenset()
        if self.corpus is not None and gen is not None:
            try:
                # generation-matched ask: a stale report answers empty
                # (and kicks a debounced background recompute) — never
                # blocks the planner, never prunes on stale proofs
                excluded = frozenset(
                    self.corpus.prunable_keys(self.target, gen)
                )
            except Exception:
                excluded = frozenset()
        key = (gen, healthy, frozenset(self._manual_quarantine), excluded)
        with self._lock:
            if self._plan is not None and self._plan_key == key:
                return self._plan
        keys = keys_fn(self.target)
        if excluded:
            keys = [c for c in keys if c not in excluded]
        if not keys:
            with self._lock:
                self._plan, self._plan_key = None, key
            return None
        static, locality = self._planner_inputs(driver)
        measured = self._measured_costs()
        blended = _blend_costs(keys, static, measured)
        with self._lock:
            self._plan_gen += 1
            plan = build_plan(
                keys, self.k, self.devices, healthy,
                constraint_gen=gen, generation=self._plan_gen,
                costs=blended, locality=locality,
            )
            plan.excluded_static = tuple(sorted(excluded))
            self._plan_costs = {
                "static": dict(static or {}),
                "measured": dict(measured),
            }
            prev = self._plan
            if prev is not None:
                moved = sum(
                    1
                    for p, q in zip(plan.partitions, prev.partitions)
                    if p.device != q.device
                )
                if moved:
                    self.rehomes += moved
                    if self.metrics is not None:
                        self.metrics.record(
                            "device_partition_rehomes_total", moved,
                            plane=self.plane,
                        )
            self._plan, self._plan_key = plan, key
            # prune staged tokens the new plan obsoletes: signature
            # tokens survive re-planning while their (subset, device)
            # placement persists; legacy tokens die with their plan gen
            live = {(p.subset, p.device) for p in plan.partitions}
            self._staged = {
                t for t in self._staged
                if (
                    (isinstance(t[0], frozenset) and (t[0], t[1]) in live)
                    or (
                        not isinstance(t[0], frozenset)
                        and t[0] == self._plan_gen
                    )
                )
            }
            # churn replay: partitions that HAVE served fused and whose
            # sub-program content changed restage proactively in the
            # background, so the swap usually lands before the next
            # batch even asks (never-staged partitions stay lazy — the
            # first dispatch stages them synchronously, preserving the
            # cold-start contract)
            prestage = (
                [p for p in plan.partitions if p.index in self._staged_parts]
                if prev is not None
                else []
            )
        for p in prestage:
            if not self._subset_ready(p):
                self._spawn_restage(p)
        if self.metrics is not None:
            self.metrics.gauge(
                "device_partition_count", len(plan.partitions),
                plane=self.plane,
            )
        self._export_quarantine()
        return plan

    def _planner_inputs(self, driver):
        """Static costs + locality tokens from the driver's planner
        surface (None-safe: a driver without the surface plans round-
        robin exactly as before)."""
        static = locality = None
        fn = getattr(driver, "constraint_costs", None)
        if fn is not None:
            try:
                static = fn(self.target)
            except Exception:
                static = None
        fn = getattr(driver, "constraint_locality", None)
        if fn is not None:
            try:
                locality = fn(self.target)
            except Exception:
                locality = None
        return static, locality

    def _measured_costs(self) -> Dict[str, float]:
        """Measured per-constraint device seconds from the attributor,
        keyed `<kind>/<name>` — the plan's empirical load signal."""
        if self.attributor is None:
            return {}
        try:
            doc = self.attributor.table(None)
            return {
                f"{r.get('kind', '?')}/{r.get('name', '?')}":
                    float(r.get("seconds", 0.0))
                for r in doc.get("rows", ())
            }
        except Exception:
            return {}

    # -- restage (quarantine re-home) ------------------------------------------

    def _stage_token(self, part: Partition):
        """The staged-set membership token. Content-signature form when
        the driver exposes one (a signature change is exactly an
        obsolete staging); legacy plan-generation form otherwise."""
        driver = getattr(self.client, "_driver", None)
        sig_fn = getattr(driver, "subset_signature", None)
        if sig_fn is not None:
            try:
                return (
                    part.subset, part.device,
                    sig_fn(self.target, part.subset),
                )
            except Exception:
                pass
        return (self._plan_gen, part.index, part.device)

    def _subset_ready(self, part: Partition) -> bool:
        """Can `part` serve a fused dispatch without staging work? A
        driver without the surface (or without a device kernel) has
        nothing to stage — always ready."""
        driver = getattr(self.client, "_driver", None)
        fn = getattr(driver, "subset_ready", None)
        if fn is None:
            return True
        try:
            return bool(fn(self.target, part.subset))
        except Exception:
            return True

    def ensure_staged(self, part: Partition, wait: bool = True) -> bool:
        """Stage `part`'s sub-program on its current device before a
        fused dispatch. A restage failure (the `driver.restage[device=N]`
        fault point, or a real staging error) backs off exponentially;
        the partition serves from the host rung until a retry succeeds.

        `wait=False` (the admission hot path): a partition that has
        ALREADY served fused but whose sub-program content churned
        restages in the BACKGROUND — the batch in hand routes to the
        host rung (correct verdicts, not a degraded dispatch) while the
        shadow sub-program compiles and swaps (docs/compile.md). A
        never-staged partition still stages synchronously even with
        wait=False: cold start must produce fused dispatches, not a
        host stampede."""
        now = self._clock()
        # token computed OUTSIDE the dispatcher lock: the signature read
        # takes the driver mutex, which a concurrent dispatch may hold
        # for a while — never stack this lock under that wait
        token = self._stage_token(part)
        with self._lock:
            if token in self._staged:
                return True
            if now < self._retry_at.get(part.device, 0.0):
                return False
            staged_before = part.index in self._staged_parts
        if not wait and staged_before and not self._subset_ready(part):
            self._spawn_restage(part)
            return False
        return self._stage_sync(part, now)

    def _stage_sync(self, part: Partition, now: float) -> bool:
        prep = getattr(self.client, "prepare_subset", None)
        try:
            ok = True
            if prep is not None:
                ok = prep(part.subset, device=part.device)
        except Exception:
            self._note_restage_failure(part, now)
            return False
        if ok is False:
            # lost a race with newer churn: not a failure (no backoff),
            # but not staged either — the next pass sees the new content
            return False
        token = self._stage_token(part)
        with self._lock:
            self._staged.add(token)
            self._staged_parts.add(part.index)
            self._retry_at.pop(part.device, None)
            self._backoff.pop(part.device, None)
        return True

    def _note_restage_failure(self, part: Partition, now: float) -> None:
        with self._lock:
            back = self._backoff.get(
                part.device, self.restage_backoff_s
            )
            self._retry_at[part.device] = now + back
            self._backoff[part.device] = min(
                back * 2, self.restage_backoff_max_s
            )
            self.restage_failures += 1
            backlog = len(self._staging)
        if self.metrics is not None:
            self.metrics.record(
                "device_partition_restage_failures_total", 1,
                plane=self.plane, device=str(part.device),
            )
        # restage-failure bursts are the compile_storm trigger signal
        note = getattr(self.recorder, "note_restage_failure", None)
        if note is not None:
            try:
                note(self.plane, backlog=backlog)
            except Exception:
                pass

    def _spawn_restage(self, part: Partition) -> None:
        """Background restage of a churned, previously-fused partition.
        NON-daemon thread: a daemon killed mid-XLA-compile at teardown
        aborts the process (see TpuDriver._kick_warm); these threads
        finish on their own — staging is bounded by one compile."""
        key = (part.subset, part.device)
        with self._lock:
            if self._closed or key in self._staging:
                return
            self._staging.add(key)

        def run():
            try:
                self._stage_sync(part, self._clock())
            except Exception:
                pass
            finally:
                with self._lock:
                    self._staging.discard(key)

        threading.Thread(
            target=run, name=f"gk-restage-{self.plane}", daemon=False
        ).start()

    # -- probes ----------------------------------------------------------------

    def run_probes(self, reviews: Sequence[Any]) -> None:
        """Half-open probes against quarantined devices, on the
        breaker's own recovery schedule (its `recovery_seconds` clock —
        re-homed partitions carry no traffic to a quarantined device,
        so without this nothing would ever close its breaker). The
        probe re-dispatches the device's HOME partition subset against
        a slice of the live batch; its results are discarded — the
        batch was already answered — and only the breaker verdict
        (CLOSED on success, re-OPEN on failure) matters."""
        plan = self._plan
        if plan is None or not reviews:
            return
        for device in self.devices:
            with self._lock:
                b = self._breakers.get(device)
                manual = device in self._manual_quarantine
            if b is None or manual:
                continue
            if b.state == CLOSED or not b.allow():
                continue
            part = next(
                (p for p in plan.partitions if p.home_device == device),
                None,
            )
            if part is None:
                # no partition to probe with: count the probe slot as a
                # success so an unused device never wedges half-open
                b.record_success()
                continue
            self.probes += 1
            try:
                self.client.review_many_subset(
                    list(reviews[: self.probe_batch]), part.subset,
                    device=device,
                )
            except Exception:
                b.record_failure()
                self._note_probe(device, "failure")
                continue
            b.record_success()
            self._note_probe(device, "success")

    def _note_probe(self, device: int, result: str) -> None:
        if self.metrics is not None:
            self.metrics.record(
                "device_quarantine_probes_total", 1,
                plane=self.plane, device=str(device), result=result,
            )

    # -- dispatch accounting ---------------------------------------------------

    def note_dispatch(self, route: str, device: Optional[int] = None) -> None:
        with self._lock:
            self.dispatches[route] = self.dispatches.get(route, 0) + 1
        if self.metrics is not None:
            self.metrics.record(
                "device_partition_dispatch_total", 1,
                plane=self.plane, route=route,
                device="" if device is None else str(device),
            )

    def note_batch_touched(self, touched: int, planned: int) -> None:
        """Pruning telemetry: of `planned` partitions in the live plan,
        this batch dispatched work to `touched` (the rest were mask-
        skipped — no device call, no restage touch)."""
        with self._lock:
            self._touched.append(int(touched))
            if len(self._touched) > 4096:
                del self._touched[: len(self._touched) // 2]
        if self.metrics is not None:
            self.metrics.gauge(
                "device_partitions_touched", touched, plane=self.plane,
            )
            self.metrics.gauge(
                "device_partitions_planned", planned, plane=self.plane,
            )

    def touched_stats(self) -> Dict[str, Any]:
        """p50/max of per-batch partitions touched (bench SUMMARY's
        partitions_touched_p50/_max; window = last ~4k batches)."""
        with self._lock:
            data = sorted(self._touched)
        if not data:
            return {"batches": 0, "p50": None, "max": None}
        return {
            "batches": len(data),
            "p50": data[len(data) // 2],
            "max": data[-1],
        }

    @property
    def executor(self) -> Optional[ThreadPoolExecutor]:
        """Shared pool for concurrent partition dispatches (the driver
        serializes its own critical sections; concurrency buys overlap
        of encode/render work and, on real multi-device hardware,
        device execution)."""
        with self._lock:
            if self._closed:
                return None
            if self._executor is None:
                self._executor = ThreadPoolExecutor(
                    max_workers=min(8, self.k),
                    thread_name_prefix=f"gk-part-{self.plane}",
                )
            return self._executor

    def close(self) -> None:
        with self._lock:
            self._closed = True
            ex, self._executor = self._executor, None
        if ex is not None:
            ex.shutdown(wait=False)

    # -- introspection ---------------------------------------------------------

    def postmortem(self) -> Dict[str, Any]:
        """The flight-recorder source view: `snapshot()` PLUS each
        partition's constraint keys and, explicitly, the keys belonging
        to quarantined devices' HOME partitions — the "which constraints
        did the sick chip take with it" answer a postmortem needs
        without a live plan to interrogate."""
        snap = self.snapshot()
        with self._lock:
            plan = self._plan
        if plan is not None:
            snap["partition_keys"] = {
                str(p.index): list(p.keys) for p in plan.partitions
            }
            quarantined = set(snap.get("quarantined", ()))
            snap["quarantined_constraint_keys"] = sorted({
                k
                for p in plan.partitions
                if p.home_device in quarantined
                for k in p.keys
            })
        return snap

    def plan_table(self) -> Dict[str, Any]:
        """/debug/partitions: live plan composition — per-partition
        constraint keys, static/measured cost share, home + current
        device — replica-tagged like /debug/costs. Refreshes the plan
        first so the table reflects current churn/health."""
        try:
            plan = self.plan()
        except Exception:
            with self._lock:
                plan = self._plan
        with self._lock:
            static = dict(self._plan_costs.get("static", {}))
            measured = dict(self._plan_costs.get("measured", {}))
        s_total = sum(static.values())
        m_total = sum(v for v in measured.values() if v > 0.0)
        # corpus-analysis flags: statically-excluded keys never appear
        # in a partition row (that's the point), so they are listed at
        # the table level; shadowed keys ride their row — both answer
        # the postmortem question "why didn't this constraint fire"
        shadowed: Dict[str, str] = {}
        if self.corpus is not None:
            try:
                shadowed = dict(self.corpus.shadowed_keys())
            except Exception:
                shadowed = {}
        doc: Dict[str, Any] = {
            "plane": self.plane,
            "k": self.k,
            "generation": plan.generation if plan is not None else None,
            "all_dead": plan.all_dead if plan is not None else None,
            "excluded_static": (
                list(plan.excluded_static) if plan is not None else []
            ),
            "partitions_touched": self.touched_stats(),
            "partitions": [],
        }
        # IR liveness plane (docs/analysis.md §IR analysis): how many
        # provably-dead token slots the feature-liveness mask has
        # dropped from batch encodes on this replica
        driver = getattr(self.client, "_driver", None)
        live_fn = getattr(driver, "liveness_stats", None)
        if live_fn is not None:
            try:
                doc["liveness"] = live_fn()
            except Exception:
                pass
        if self.replica:
            doc["replica"] = self.replica
        if plan is not None:
            for p in plan.partitions:
                s = sum(static.get(k, 0.0) for k in p.keys)
                m = sum(measured.get(k, 0.0) for k in p.keys)
                row = {
                    "index": p.index,
                    "home_device": p.home_device,
                    "device": p.device,
                    "constraints": len(p.keys),
                    "keys": list(p.keys),
                    "static_cost_share":
                        (s / s_total) if s_total > 0 else None,
                    "measured_cost_share":
                        (m / m_total) if m_total > 0 else None,
                }
                row_shadowed = {
                    k: shadowed[k] for k in p.keys if k in shadowed
                }
                if row_shadowed:
                    row["shadowed"] = row_shadowed
                doc["partitions"].append(row)
        return doc

    def programs_table(self) -> Dict[str, Any]:
        """/debug/programs: the compile plane's live view — per
        partition the sub-program content signature, staged/ready
        state and in-flight restage, plus the driver's compile-plane
        counters and program-store stats (hit/miss/rejected, swap
        generation) — replica-tagged like /debug/partitions. Also the
        flight recorder's `programs` source, so a compile_storm
        postmortem carries the store state table."""
        try:
            plan = self.plan()
        except Exception:
            with self._lock:
                plan = self._plan
        driver = getattr(self.client, "_driver", None)
        doc: Dict[str, Any] = {
            "plane": self.plane,
            "partitions": [],
        }
        if self.replica:
            doc["replica"] = self.replica
        stats_fn = getattr(driver, "compile_plane_stats", None)
        if stats_fn is not None:
            try:
                doc["compile_plane"] = stats_fn()
            except Exception:
                pass
        store = getattr(driver, "program_store", None)
        if store is not None:
            try:
                doc["store_table"] = store.table()
            except Exception:
                pass
        sig_fn = getattr(driver, "subset_signature", None)
        ready_fn = getattr(driver, "subset_ready", None)
        with self._lock:
            staged = set(self._staged)
            staging = set(self._staging)
            staged_parts = set(self._staged_parts)
            doc["restage_failures"] = self.restage_failures
            doc["staging_in_flight"] = len(staging)
        if plan is not None:
            for p in plan.partitions:
                sig = ready = None
                if sig_fn is not None:
                    try:
                        sig = sig_fn(self.target, p.subset)
                    except Exception:
                        sig = None
                if ready_fn is not None:
                    try:
                        ready = bool(ready_fn(self.target, p.subset))
                    except Exception:
                        ready = None
                doc["partitions"].append({
                    "index": p.index,
                    "device": p.device,
                    "constraints": len(p.keys),
                    "signature": sig,
                    "ready": ready,
                    "staged": any(
                        (
                            isinstance(t[0], frozenset)
                            and t[0] == p.subset
                            and t[1] == p.device
                        )
                        or (
                            not isinstance(t[0], frozenset)
                            and t[1] == p.index
                            and t[2] == p.device
                        )
                        for t in staged
                    ),
                    "staging_in_flight": (p.subset, p.device) in staging,
                    "ever_staged": p.index in staged_parts,
                })
        return doc

    def snapshot(self) -> Dict[str, Any]:
        """Readyz/debug view: the plan, quarantine state, per-device
        breaker snapshots (keyed by breaker NAME), and dispatch/rehome/
        probe accounting."""
        with self._lock:
            plan = self._plan
            return {
                "plane": self.plane,
                "k": self.k,
                "devices": list(self.devices),
                "plan": plan.to_dict() if plan is not None else None,
                "quarantined": sorted(
                    d for d in self.devices if not self._device_healthy(d)
                ),
                "manual_quarantine": sorted(self._manual_quarantine),
                "quarantine_reasons": {
                    str(d): r
                    for d, r in self._quarantine_reasons.items()
                },
                "breakers": {
                    b.name: b.snapshot()
                    for b in self._breakers.values()
                },
                "dispatches": dict(self.dispatches),
                "partitions_touched": self.touched_stats(),
                "rehomes": self.rehomes,
                "probes": self.probes,
                "restage_failures": self.restage_failures,
                "staging_in_flight": len(self._staging),
            }
