"""Multi-chip sharding for the audit kernel.

The reference scales by running whole replicas per pod
(pkg/operations/operations.go:15-19) with each holding full policy state;
the TPU build shards the **resource axis** across chips and replicates
the (small) policy tensors, per SURVEY §2.4 — plus an optional
constraint-axis shard for very large constraint populations. See
`sharding.FusedAuditKernel`.
"""

from .partition import (  # noqa: F401
    PartitionDispatcher,
    PartitionPlan,
    build_plan,
    merge_partition_results,
)
from .sharding import FusedAuditKernel, audit_mesh  # noqa: F401
