"""Structured JSON logging: the zap-equivalent log plane.

The reference wires zap through controller-runtime (main.go:104-134)
and tags every record with a standard key set
(pkg/logging/logging.go:1-20); violation denials/audits log through it
(--log-denies pkg/webhook/policy.go:240-252, audit logViolation
pkg/audit/manager.go:668-682). This module is the framework's native
counterpart: one JSON object per line on stderr, bound key/value
context via `with_values`, and an injectable sink so tests (and the
webhook's denied_log compatibility surface) can observe records.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

# standard keys (pkg/logging/logging.go:1-20)
PROCESS = "process"
EVENT_TYPE = "event_type"
TEMPLATE_NAME = "template_name"
CONSTRAINT_NAMESPACE = "constraint_namespace"
CONSTRAINT_NAME = "constraint_name"
CONSTRAINT_KIND = "constraint_kind"
CONSTRAINT_API_VERSION = "constraint_api_version"
CONSTRAINT_STATUS = "constraint_status"
CONSTRAINT_ACTION = "constraint_action"
AUDIT_ID = "audit_id"
CONSTRAINT_VIOLATIONS = "constraint_violations"
RESOURCE_KIND = "resource_kind"
RESOURCE_API_VERSION = "resource_api_version"
RESOURCE_NAMESPACE = "resource_namespace"
RESOURCE_NAME = "resource_name"
# engine-specific: correlates a log record with its request trace in
# /debug/traces (docs/observability.md); bound via with_values by the
# webhook handler so every denial names the trace that explains it
TRACE_ID = "trace_id"

_LEVELS = {"debug": 10, "info": 20, "error": 40, "off": 99}


class StructuredLogger:
    """JSON-line logger with bound values (logr/zap shape).

    `sink`: callable receiving each record dict (after the stream
    write); used by tests and by callers that keep in-memory views.
    """

    def __init__(
        self,
        name: str = "gatekeeper",
        stream=None,
        level: str = "info",
        sink: Optional[Callable[[Dict[str, Any]], None]] = None,
        _bound: Optional[Dict[str, Any]] = None,
    ):
        self.name = name
        self.stream = stream if stream is not None else sys.stderr
        self.level = level
        self.sink = sink
        self._bound = dict(_bound or {})
        self._lock = threading.Lock()

    def with_values(self, **kv) -> "StructuredLogger":
        merged = dict(self._bound)
        merged.update(kv)
        out = StructuredLogger(
            name=self.name,
            stream=self.stream,
            level=self.level,
            sink=self.sink,
            _bound=merged,
        )
        out._lock = self._lock  # share the write lock across children
        return out

    def _emit(self, level: str, msg: str, kv: Dict[str, Any]) -> None:
        if _LEVELS[level] < _LEVELS.get(self.level, 20):
            return
        rec: Dict[str, Any] = {
            "level": level,
            "ts": time.time(),
            "logger": self.name,
            "msg": msg,
        }
        rec.update(self._bound)
        rec.update(kv)
        line = json.dumps(rec, default=str)
        with self._lock:
            try:
                self.stream.write(line + "\n")
            except Exception:
                pass  # a broken log stream must never fail the caller
        if self.sink is not None:
            self.sink(rec)

    def debug(self, msg: str, **kv) -> None:
        self._emit("debug", msg, kv)

    def info(self, msg: str, **kv) -> None:
        self._emit("info", msg, kv)

    def error(self, msg: str, err: Any = None, **kv) -> None:
        if err is not None:
            kv = {"error": str(err), **kv}
        self._emit("error", msg, kv)


class _NullStream:
    def write(self, s) -> None:
        pass


# level "off" short-circuits _emit BEFORE record construction: the
# audit path logs per violation, and a sweep with tens of thousands of
# violations must not pay json.dumps into a void when nothing is wired
_null = StructuredLogger(stream=_NullStream(), level="off")


def null_logger() -> StructuredLogger:
    """A logger that emits nothing (default for components whose caller
    did not wire logging); record construction is skipped entirely."""
    return _null


class CapturingLogger(StructuredLogger):
    """Test helper: keeps every record in `records`."""

    def __init__(self, level: str = "debug"):
        self.records: List[Dict[str, Any]] = []
        super().__init__(
            stream=_NullStream(),
            level=level,
            sink=self.records.append,
        )
