"""Declarative soak scenarios: a timeline of events over sustained load.

A scenario is a plain dict (checked by `Scenario.from_dict`) so runs
are reproducible from a JSON file checked in next to their evidence
artifact. The shape:

    {
      "name": "soak-default",
      "duration_s": 150,          # open-loop load window
      "rps": 60,                  # fixed Poisson arrival rate
      "deadline_s": 0.25,         # the SLO: answered within deadline
      "window_s": 5,              # reporting window size
      "seed": 1234,               # arrival/plane RNG seed
      "replicas": 2,              # real WebhookServer replicas
      "tls": true,                # HTTPS + fleet Secret cert store
      "constraints": 30,          # initial constraint count
      "external_keys": 12,        # external-data key universe
      "planes": {"validation": 0.7, "mutation": 0.15, "agent": 0.15},
      "breaker": {"failure_threshold": 3, "recovery_seconds": 5},
      "capacity": {"constraint_counts": [10, 100],
                   "rps_levels": [25, 50, 100, 200],
                   "probe_s": 3},
      "events": [
        {"at": 0,  "action": "phase", "name": "steady"},
        {"at": 62, "action": "add_constraints", "count": 50},
        {"at": 86, "action": "arm_fault",
         "point": "driver.device_dispatch", "mode": "error"},
        {"at": 100, "action": "disarm_faults"},
        {"at": 115, "action": "rotate_certs"},
        {"at": 121, "action": "kill_replica", "replica": 0},
      ]
    }

`phase` events label every subsequent reporting window until the next
`phase` event — the reporter aggregates SLO attainment, shed and 5xx
rates per phase, which is how the acceptance checks (fault window
recovers, churn stays 5xx-free, replica kill sheds bounded) find their
windows.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

PLANES = ("validation", "mutation", "agent")

# action -> required extra keys (beyond "at"/"action")
ACTIONS: Dict[str, tuple] = {
    "phase": ("name",),          # label windows from here on
    "add_constraints": (),       # count (default 25): constraint churn
    "add_template": (),          # new template kind + one constraint
    "add_provider": (),          # register another stub-backed provider
    "add_mutator": (),           # add an AssignMetadata mutator
    # locality-skewed churn (pruned dispatch): add two namespace-
    # affine constraint groups (count per group; hot_ns/cold_ns name
    # the namespaces) and skew subsequent traffic toward the hot one
    # (skew, default 0.9) — the guided planner co-locates each group,
    # so sampler windows show partitions_touched well under the plan's
    # k while the cold group's partitions sit mask-skipped
    "locality_churn": (),
    # incremental compile plane (docs/compile.md): a burst of new
    # templates + constraints lands at once; every new partition
    # shadow-stages and warm-swaps while in-flight batches keep the
    # old programs — the ingest_zero_degraded check asserts the phase
    # recorded zero degraded dispatches and zero 5xx
    "ingest_wave": (),           # count (default 500): template burst
    "arm_fault": ("point",),     # mode/count/after/delay ride along
    "disarm_faults": (),         # reset the whole fault registry
    "rotate_certs": (),          # force a cert rotation (tls only)
    "kill_replica": (),          # replica (default 0): LB-out + drain
    # device fault domains (needs partitions > 0): operator-style
    # quarantine of one logical device (its partitions re-home onto
    # healthy devices) and the matching heal
    "quarantine_device": (),     # device (default 1)
    "heal_device": (),           # device (default 1)
    # verdict-integrity plane (docs/robustness.md §Verdict integrity):
    # run the golden self-test against one device — the ONLY path that
    # heals a corruption quarantine (the sdc scenario fires it after
    # disarming the bit-flip)
    "selftest_device": (),       # device (default 1)
}


@dataclass
class ScenarioEvent:
    at_s: float
    action: str
    params: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ScenarioEvent":
        if not isinstance(d, dict):
            raise ValueError(f"event must be an object, got {d!r}")
        action = d.get("action")
        if action not in ACTIONS:
            raise ValueError(
                f"unknown scenario action {action!r} "
                f"(want one of {sorted(ACTIONS)})"
            )
        try:
            at_s = float(d.get("at", 0.0))
        except (TypeError, ValueError):
            raise ValueError(f"event 'at' must be a number: {d!r}")
        if at_s < 0:
            raise ValueError(f"event 'at' must be >= 0: {d!r}")
        params = {k: v for k, v in d.items() if k not in ("at", "action")}
        for req in ACTIONS[action]:
            if req not in params:
                raise ValueError(
                    f"scenario action {action!r} requires {req!r}: {d!r}"
                )
        return cls(at_s=at_s, action=action, params=params)

    def to_dict(self) -> Dict[str, Any]:
        return {"at": self.at_s, "action": self.action, **self.params}


@dataclass
class Scenario:
    name: str = "soak"
    duration_s: float = 60.0
    rps: float = 50.0
    deadline_s: float = 0.25
    window_s: float = 5.0
    seed: int = 1234
    replicas: int = 1
    tls: bool = False
    constraints: int = 20
    external_keys: int = 12
    violating_fraction: float = 0.1
    # micro-batch window for the replicas' batchers
    window_ms: float = 2.0
    # override the driver's adaptive small-batch floor for the run
    # (GATEKEEPER_TPU_MIN_DEVICE_BATCH equivalent): at realistic soak
    # arrival rates micro-batches are small, and without lowering the
    # floor every batch would take the interpreter route — device
    # faults would never fire and the device-time split would be empty.
    # None keeps the deployment default.
    min_device_batch: Optional[int] = None
    # device fault domains (docs/robustness.md §Fault domains): split
    # each replica's validation plane into this many constraint-subset
    # partitions with per-device breakers + quarantine; 0 keeps the
    # monolithic dispatch + single plane breaker
    partitions: int = 0
    planes: Dict[str, float] = field(
        default_factory=lambda: {
            "validation": 0.7, "mutation": 0.15, "agent": 0.15
        }
    )
    breaker: Dict[str, float] = field(
        default_factory=lambda: {
            "failure_threshold": 3, "recovery_seconds": 5.0
        }
    )
    capacity: Optional[Dict[str, Any]] = None
    # SloTarget overrides for the live SLO engine + offline reporter
    # (obs/slo.py — objective, burn windows/thresholds, the degrade/
    # recover phase thresholds). deadline_s defaults to the scenario's
    # own deadline contract; unknown keys are rejected at load time.
    slo: Optional[Dict[str, Any]] = None
    # admission scheduling policy for every replica's batcher planes
    # (docs/operations.md §Admission scheduling): "deadline" = EDF
    # batch formation + per-tenant fair-share quotas + predictive
    # shedding; "fifo" = the bit-compatible legacy queue (the
    # multi-tenant overload baseline runs use it for the contrast)
    sched_policy: str = "fifo"
    # two-tenant traffic mix (the multi_tenant_overload scenario):
    # {"noisy_fraction": 0.75, "quiet_ns": "...", "noisy_ns": "..."} —
    # `noisy_fraction` of validation/mutation requests land on the
    # noisy namespace, the rest on the quiet one; the sampler reads
    # each class's attainment/shed split from the decision log
    tenants: Optional[Dict[str, Any]] = None
    # front-door transport (docs/ingest.md): "http" drives the legacy
    # webhook endpoints over urllib; "framed" opens each replica's
    # stream listener and submits over multiplexed length-prefixed
    # frames with the deadline stamped in the frame header — the
    # wire-speed ingest plane's soak path (high_rate_scenario)
    transport: str = "http"
    events: List[ScenarioEvent] = field(default_factory=list)

    def slo_target(self):
        """The one SloTarget both the live per-replica engines and the
        offline reporter judge this run against."""
        from ..obs.slo import SloTarget

        return SloTarget.from_dict(
            self.slo, deadline_s=self.deadline_s
        )

    def validate(self) -> None:
        if self.duration_s <= 0:
            raise ValueError("duration_s must be > 0")
        if self.rps <= 0:
            raise ValueError("rps must be > 0")
        if self.deadline_s <= 0:
            raise ValueError("deadline_s must be > 0")
        if not (0 < self.window_s <= self.duration_s):
            raise ValueError("window_s must be in (0, duration_s]")
        if self.replicas < 1:
            raise ValueError("replicas must be >= 1")
        for plane in self.planes:
            if plane not in PLANES:
                raise ValueError(
                    f"unknown plane {plane!r} (want {PLANES})"
                )
        if sum(self.planes.values()) <= 0:
            raise ValueError("plane weights must sum to > 0")
        from ..sched import POLICIES

        if self.sched_policy not in POLICIES:
            raise ValueError(
                f"sched_policy must be one of {POLICIES}, "
                f"got {self.sched_policy!r}"
            )
        if self.transport not in ("http", "framed"):
            raise ValueError(
                f"transport must be 'http' or 'framed', "
                f"got {self.transport!r}"
            )
        if self.transport == "framed" and self.tls:
            raise ValueError(
                "transport='framed' is plaintext-only (the stream "
                "listener terminates no TLS); drop tls or use http"
            )
        if self.tenants is not None:
            frac = float(self.tenants.get("noisy_fraction", 0.75))
            if not (0.0 < frac < 1.0):
                raise ValueError(
                    "tenants.noisy_fraction must be in (0, 1)"
                )
        # a typoed slo override must fail the load, not the analysis
        self.slo_target()
        for ev in self.events:
            if ev.at_s > self.duration_s:
                raise ValueError(
                    f"event at t={ev.at_s}s is past duration_s="
                    f"{self.duration_s}s: {ev.to_dict()}"
                )
            if ev.action == "kill_replica":
                idx = int(ev.params.get("replica", 0))
                if not (0 <= idx < self.replicas):
                    raise ValueError(
                        f"kill_replica index {idx} out of range for "
                        f"{self.replicas} replicas"
                    )
            if ev.action in (
                "quarantine_device", "heal_device", "selftest_device"
            ):
                if self.partitions < 1:
                    raise ValueError(
                        f"{ev.action} requires partitions >= 1"
                    )
                dev = int(ev.params.get("device", 1))
                if not (0 <= dev < self.partitions):
                    raise ValueError(
                        f"{ev.action} device {dev} out of range for "
                        f"{self.partitions} partitions"
                    )

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Scenario":
        known = {
            "name", "duration_s", "rps", "deadline_s", "window_s",
            "seed", "replicas", "tls", "constraints", "external_keys",
            "violating_fraction", "window_ms", "min_device_batch",
            "partitions", "planes", "breaker", "capacity", "slo",
            "sched_policy", "tenants", "transport", "events",
        }
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown scenario keys: {sorted(unknown)}"
            )
        kwargs = {k: v for k, v in d.items() if k != "events"}
        events = [ScenarioEvent.from_dict(e) for e in d.get("events", [])]
        scn = cls(**kwargs, events=sorted(events, key=lambda e: e.at_s))
        scn.validate()
        return scn

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "duration_s": self.duration_s,
            "rps": self.rps,
            "deadline_s": self.deadline_s,
            "window_s": self.window_s,
            "seed": self.seed,
            "replicas": self.replicas,
            "tls": self.tls,
            "constraints": self.constraints,
            "external_keys": self.external_keys,
            "violating_fraction": self.violating_fraction,
            "window_ms": self.window_ms,
            "min_device_batch": self.min_device_batch,
            "partitions": self.partitions,
            "planes": dict(self.planes),
            "breaker": dict(self.breaker),
            "capacity": self.capacity,
            "slo": self.slo,
            "sched_policy": self.sched_policy,
            "tenants": dict(self.tenants) if self.tenants else None,
            "transport": self.transport,
            "events": [e.to_dict() for e in self.events],
        }


def load_scenario(path: str) -> Scenario:
    with open(path) as f:
        return Scenario.from_dict(json.load(f))


def smoke_scenario() -> Scenario:
    """The ~10 s tier-1 smoke: one replica, plain HTTP, a constraint-
    churn blip and one fault window with a fast-recovery breaker —
    enough to exercise every moving part of the harness without
    minutes of wall clock."""
    return Scenario.from_dict({
        "name": "soak-smoke",
        "duration_s": 12.5,
        "rps": 30.0,
        "deadline_s": 0.5,
        "window_s": 1.0,
        "seed": 99,
        "replicas": 1,
        "tls": False,
        "constraints": 8,
        "external_keys": 5,
        "breaker": {"failure_threshold": 3, "recovery_seconds": 1.0},
        "events": [
            {"at": 0.0, "action": "phase", "name": "steady"},
            {"at": 2.0, "action": "phase", "name": "churn"},
            {"at": 2.2, "action": "add_constraints", "count": 5},
            {"at": 3.0, "action": "add_provider"},
            {"at": 4.0, "action": "phase", "name": "fault"},
            # batch_dispatch error trips the breaker at interpreter-
            # route batch sizes too; the host-rung hang (> deadline)
            # makes the SLO dip measurable in a 2 s window
            {"at": 4.1, "action": "arm_fault",
             "point": "webhook.batch_dispatch", "mode": "error"},
            {"at": 4.1, "action": "arm_fault",
             "point": "webhook.host_review", "mode": "hang",
             "delay": 0.6},
            {"at": 6.0, "action": "disarm_faults"},
            # the backlog the hang built drains during the tail of the
            # fault phase; recovery is judged from t=7 so it measures
            # the recovered system, not the queue flush
            {"at": 7.0, "action": "phase", "name": "recovery"},
            # a small template ingest wave: the compile plane must
            # serve every request through it (ingest_zero_degraded)
            {"at": 9.0, "action": "phase", "name": "ingest"},
            {"at": 9.2, "action": "ingest_wave", "count": 6},
        ],
    })


def sdc_smoke_scenario() -> Scenario:
    """The ~9 s verdict-integrity smoke (docs/robustness.md §Verdict
    integrity): partitioned serving with a device bit-flip armed
    mid-steady-state via the `integrity.canary[device=1]` fault point.
    The canary tier must detect the corruption, trip the device into
    quarantine with reason `corruption` (its partitions re-home while
    healthy devices keep serving fused), and after the flip is
    disarmed the golden self-test — the ONLY corruption heal path —
    returns the device to the pool. The report judges it all through
    `sdc_detected_and_quarantined` over the canary_mismatches /
    quarantined_devices window columns."""
    return Scenario.from_dict({
        "name": "soak-sdc-smoke",
        "duration_s": 9.0,
        "rps": 30.0,
        "deadline_s": 0.5,
        "window_s": 1.0,
        "seed": 77,
        "replicas": 1,
        "tls": False,
        "constraints": 8,
        "external_keys": 5,
        "partitions": 2,
        # keep micro-batches on the device path so canary rows
        # actually ride the dispatches the bit-flip corrupts
        "min_device_batch": 1,
        "breaker": {"failure_threshold": 3, "recovery_seconds": 1.0},
        "events": [
            {"at": 0.0, "action": "phase", "name": "steady"},
            {"at": 3.0, "action": "phase", "name": "sdc"},
            {"at": 3.1, "action": "arm_fault",
             "point": "integrity.canary[device=1]", "mode": "error"},
            {"at": 6.0, "action": "disarm_faults"},
            {"at": 6.2, "action": "selftest_device", "device": 1},
            {"at": 6.5, "action": "phase", "name": "recovery"},
        ],
    })


def multi_tenant_overload_scenario(
    sched_policy: str = "deadline",
) -> Scenario:
    """The scheduler acceptance run (docs/operations.md §Admission
    scheduling): two tenant classes — a noisy namespace carrying 3/4 of
    arrivals and a quiet one carrying the rest — driven at roughly 2×
    the single-replica capacity so the plane saturates. With
    `sched_policy="deadline"` the fair-share quotas cap the noisy
    tenant at its share and predictive shedding drops only provably
    doomed requests, so the quiet tenant's attainment holds at the SLO
    objective (`quiet_tenant_attainment_holds`); the same scenario
    with `"fifo"` is the baseline where both classes degrade together
    (`fifo_baseline_degrades` — the contrast the report asserts)."""
    return Scenario.from_dict({
        "name": f"soak-multi-tenant-{sched_policy}",
        "duration_s": 60.0,
        "rps": 400.0,  # ~2x the capacity model's single-replica knee
        "deadline_s": 0.25,
        "window_s": 5.0,
        "seed": 4242,
        "replicas": 1,
        "tls": False,
        "constraints": 30,
        "external_keys": 12,
        "window_ms": 10.0,
        "min_device_batch": 2,
        # scheduling is a validation/mutation-plane story here; agent
        # traffic would add a second tenant-identity axis to the split
        "planes": {"validation": 0.85, "mutation": 0.15},
        "sched_policy": sched_policy,
        "tenants": {
            "noisy_fraction": 0.75,
            "quiet_ns": "ns-quiet",
            "noisy_ns": "ns-noisy",
        },
        "events": [
            {"at": 0.0, "action": "phase", "name": "overload"},
        ],
    })


def multi_tenant_smoke_scenario(
    sched_policy: str = "deadline",
) -> Scenario:
    """Tier-1 smoke of the multi-tenant overload machinery (~8 s, one
    replica): small corpus, overdriven arrivals, the same two-tenant
    mix — enough to exercise the scheduler seams, the per-class
    sampler columns, and the report checks without asserting the full
    run's attainment numbers."""
    return Scenario.from_dict({
        "name": f"soak-multi-tenant-smoke-{sched_policy}",
        "duration_s": 8.0,
        "rps": 120.0,
        "deadline_s": 0.3,
        "window_s": 1.0,
        "seed": 77,
        "replicas": 1,
        "tls": False,
        "constraints": 8,
        "external_keys": 5,
        "planes": {"validation": 0.85, "mutation": 0.15},
        "sched_policy": sched_policy,
        "tenants": {
            "noisy_fraction": 0.75,
            "quiet_ns": "ns-quiet",
            "noisy_ns": "ns-noisy",
        },
        "events": [
            {"at": 0.0, "action": "phase", "name": "overload"},
        ],
    })


def high_rate_scenario() -> Scenario:
    """The wire-speed ingest acceptance run (docs/ingest.md §Soak):
    one replica driven open-loop at 5000 rps/replica over the framed
    stream transport — an offered rate far past what conn-per-request
    HTTP/1 can even accept on one host. The arrival schedule never
    slows for the system (coordinated-omission honest), so the run
    measures what the framed front door SUSTAINS under a firehose: the
    report's `ingest_rps_sustained` check asserts within-deadline
    goodput holds a floor fraction of the offered rate, and
    `decode_span_bounded` asserts the zero-copy scanner's share of
    each request's deadline budget stays marginal (decode must never
    become the bottleneck the transport just removed)."""
    return Scenario.from_dict({
        "name": "soak-high-rate",
        "duration_s": 60.0,
        "rps": 5000.0,
        "deadline_s": 0.25,
        "window_s": 5.0,
        "seed": 1311,
        "replicas": 1,
        "tls": False,
        "constraints": 20,
        "external_keys": 5,
        "window_ms": 2.0,
        "transport": "framed",
        "events": [
            {"at": 0.0, "action": "phase", "name": "firehose"},
        ],
    })


def high_rate_smoke_scenario() -> Scenario:
    """Tier-1 smoke of the framed-transport soak path (~8 s, one
    replica, an arrival rate the CI box actually serves): exercises
    the harness's StreamClient submit pool, the per-window ingest
    sampler columns, and both ingest report checks without asserting
    the full firehose run's numbers."""
    return Scenario.from_dict({
        "name": "soak-high-rate-smoke",
        "duration_s": 8.0,
        "rps": 80.0,
        "deadline_s": 0.5,
        "window_s": 1.0,
        "seed": 1311,
        "replicas": 1,
        "tls": False,
        "constraints": 8,
        "external_keys": 5,
        "transport": "framed",
        "events": [
            {"at": 0.0, "action": "phase", "name": "firehose"},
        ],
    })


def default_scenario() -> Scenario:
    """The full evidence run behind SOAK_r01.json: two TLS replicas
    sharing a fleet cert Secret and cache/breaker gossip, >= 60 s of
    steady open-loop load for the leak curves, then churn
    (constraints + template + provider + mutator adds, capped by a
    locality-skewed window: two namespace-affine constraint groups
    with 90/10 traffic skew — the pruned-dispatch evidence), a fault
    window
    (device faults trip the breaker while the host rung stalls — the
    SLO must degrade and then recover post-disarm), a sick-chip window
    (ONE device of the 4-partition plan faulted: only its constraint
    subset degrades, then the operator quarantine/heal path re-homes
    it), a live cert rotation, a 500-template ingest wave that the
    incremental compile plane must absorb with zero degraded
    dispatches, and a graceful replica kill that replica B absorbs."""
    return Scenario.from_dict({
        "name": "soak-default",
        "duration_s": 150.0,
        "rps": 60.0,
        "deadline_s": 0.25,
        "window_s": 5.0,
        "seed": 1234,
        "replicas": 2,
        "tls": True,
        "constraints": 30,
        "external_keys": 12,
        # realistic arrival rates make small micro-batches: lower the
        # device floor so the run exercises the REAL fused path (and
        # device faults actually fire; see Scenario.min_device_batch)
        "window_ms": 10.0,
        "min_device_batch": 2,
        # device fault domains: 4 constraint-subset partitions, each
        # with its own per-device breaker (§Fault domains)
        "partitions": 4,
        "breaker": {"failure_threshold": 3, "recovery_seconds": 5.0},
        "capacity": {
            "constraint_counts": [10, 100],
            "rps_levels": [25, 50, 100, 200, 400],
            "probe_s": 3.0,
        },
        "events": [
            {"at": 0.0, "action": "phase", "name": "steady"},
            {"at": 60.0, "action": "phase", "name": "churn"},
            {"at": 62.0, "action": "add_constraints", "count": 50},
            {"at": 66.0, "action": "add_template"},
            {"at": 70.0, "action": "add_provider"},
            {"at": 74.0, "action": "add_mutator"},
            # locality-skewed churn: two namespace-affine constraint
            # groups join the corpus and 90% of subsequent traffic
            # lands on the hot namespace — the guided plan co-locates
            # each group, so this phase's sampler windows record
            # partitions_touched falling under the plan's k (the
            # pruned-dispatch evidence window)
            {"at": 76.0, "action": "phase", "name": "locality_skew"},
            {"at": 76.5, "action": "locality_churn", "count": 10,
             "skew": 0.9},
            {"at": 85.0, "action": "phase", "name": "fault"},
            {"at": 86.0, "action": "arm_fault",
             "point": "driver.device_dispatch", "mode": "error"},
            {"at": 86.0, "action": "arm_fault",
             "point": "webhook.host_review", "mode": "hang",
             "delay": 0.35},
            {"at": 100.0, "action": "disarm_faults"},
            # recovery judged after the hang-built backlog drains
            {"at": 103.0, "action": "phase", "name": "recovery"},
            # sick chip: ONE device faulted — its partition's subset
            # degrades to host (blast radius = 1/partitions), the
            # breaker trips it into quarantine, and after the disarm
            # the operator quarantine/heal path exercises re-homing
            {"at": 108.0, "action": "phase", "name": "sick_chip"},
            {"at": 108.5, "action": "arm_fault",
             "point": "driver.device_dispatch[device=1]",
             "mode": "error"},
            {"at": 114.0, "action": "disarm_faults"},
            {"at": 114.5, "action": "quarantine_device", "device": 1},
            {"at": 117.0, "action": "heal_device", "device": 1},
            {"at": 118.0, "action": "rotate_certs"},
            # 500-template ingest wave against the 4-partition plan:
            # every changed partition shadow-compiles off the serving
            # path and warm-swaps — the ingest_zero_degraded check
            # demands zero degraded dispatches and zero 5xx here
            {"at": 119.0, "action": "phase", "name": "ingest"},
            {"at": 119.5, "action": "ingest_wave", "count": 500},
            {"at": 135.0, "action": "phase", "name": "kill"},
            {"at": 136.0, "action": "kill_replica", "replica": 0},
        ],
    })
