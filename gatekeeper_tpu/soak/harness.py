"""SoakHarness: the system under test + the scenario executor.

Builds 1..N REAL `WebhookServer` replicas over HTTP(S) — validation,
mutation, and agent-review planes live on every replica — with a
self-contained policy corpus (no reference-library dependency: a soak
must run on any machine), an in-process stub external-data provider,
and, for multi-replica runs, the PR-7 fleet plane over one FakeCluster
(shared Secret-backed certs, cache gossip, breaker gossip).

The run is three concurrent machines:

  * the open-loop generator (loadgen.py) posting Poisson arrivals
    round-robin over the ACTIVE replicas — the load-balancer model:
    a replica leaves rotation the instant its readiness flips
    (`WebhookServer.on_drain`), which is exactly what a real LB
    watching /readyz does;
  * the scenario timer executing timeline events (constraint churn,
    provider/mutator adds, fault arm/disarm against the PR-4 registry,
    cert rotation through the fleet store, graceful replica kill);
  * the window sampler recording server-side counters + the leak
    series (RSS, cache entries + evictions, trace-ring size, metrics
    series count, render-cache size) once per reporting window.

The reporter (report.py) joins all three streams into the evidence
artifact; `run_soak(scenario)` is the one-call entry.
"""

from __future__ import annotations

import itertools
import json
import ssl
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from ..faults import FAULTS, CircuitBreaker
from .loadgen import CLIENT_TIMEOUT, CONN_ERROR, run_open_loop
from .report import build_report
from .scenario import Scenario

K8S_TARGET = "admission.k8s.gatekeeper.sh"
SOAK_PROVIDER = "soak-registry"

_PRIV_REGO = """package soakprivileged

violation[{"msg": msg}] {
    input.review.object.spec.containers[_].securityContext.privileged
    msg := "privileged container"
}
"""

_EXT_REGO = """package soakexternal

violation[{"msg": msg}] {
    images := [img | img := input.review.object.spec.containers[_].image]
    response := external_data({"provider": "soak-registry", "keys": images})
    count(response.errors) > 0
    msg := sprintf("image verification failed: %v", [response.errors])
}
"""

_AGENT_REGO = """package soakagentshell

allowed_cmd(c) { c == input.parameters.allowed[_] }
violation[{"msg": msg}] {
    cmd := input.review.object.spec.arguments.command
    not allowed_cmd(cmd)
    msg := sprintf("shell command <%v> is outside the allowlist", [cmd])
}
"""

# churn templates get a distinct package + kind per add
_CHURN_REGO = """package soakchurn{n}

violation[{{"msg": msg}}] {{
    input.review.object.metadata.labels["soak-churn-{n}"] == "deny"
    msg := "churn label denied"
}}
"""


def _template(kind: str, target: str, rego: str) -> Dict[str, Any]:
    return {
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": kind.lower()},
        "spec": {
            "crd": {"spec": {"names": {"kind": kind}}},
            "targets": [{"target": target, "rego": rego}],
        },
    }


def _constraint(kind: str, name: str, match=None, params=None):
    spec: Dict[str, Any] = {}
    if match is not None:
        spec["match"] = match
    if params is not None:
        spec["parameters"] = params
    return {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": kind,
        "metadata": {"name": name},
        "spec": spec,
    }


_POD_MATCH = {"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}]}


def _pod_request(i: int, violating: bool, external_keys: int = 12):
    """A synthetic UPDATE AdmissionRequest whose image cycles the
    external-data key universe (steady state = pure cache hits)."""
    image = f"reg.example/app{i % external_keys}"
    return {
        "uid": f"soak-{i}",
        "kind": {"group": "", "version": "v1", "kind": "Pod"},
        "operation": "UPDATE",
        "name": f"pod{i}",
        "namespace": f"ns{i % 7}",
        "userInfo": {"username": "soak"},
        "object": {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": f"pod{i}", "namespace": f"ns{i % 7}",
                "labels": {"app": f"svc{i % 5}"},
            },
            "spec": {
                "containers": [{
                    "name": "main",
                    "image": image,
                    "securityContext": (
                        {"privileged": True} if violating else {}
                    ),
                }],
            },
        },
    }


def _assign_metadata(name: str, label: str) -> Dict[str, Any]:
    return {
        "apiVersion": "mutations.gatekeeper.sh/v1alpha1",
        "kind": "AssignMetadata",
        "metadata": {"name": name},
        "spec": {
            "match": {"scope": "Namespaced"},
            "location": f"metadata.labels.{label}",
            "parameters": {"assign": {"value": "soak"}},
        },
    }


class _StubProvider:
    """In-process provider HTTP endpoint: answers the ProviderRequest
    protocol, counts outbound fetches (the bounded-refetch evidence),
    flags keys containing \"bad\"."""

    def __init__(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.fetches = 0
        self.keys_fetched = 0
        outer = self

        class _H(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                keys = ((body.get("request") or {}).get("keys")) or []
                outer.fetches += 1
                outer.keys_fetched += len(keys)
                payload = json.dumps({
                    "response": {
                        "items": [
                            {"key": k, "error": "unsigned"}
                            if "bad" in k
                            else {"key": k, "value": f"ok:{k}"}
                            for k in keys
                        ],
                        "systemError": "",
                    }
                }).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *args):
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), _H)
        self.url = f"http://127.0.0.1:{self._httpd.server_address[1]}/v"
        threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        ).start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


class _Replica:
    """One webhook pod: client + driver + mutation/agent/external
    systems + the serving WebhookServer, plus its fleet attachments."""

    def __init__(self, name: str):
        self.name = name
        self.active = True  # in LB rotation
        self.metrics = None
        self.tracer = None
        self.client = None
        self.driver = None
        self.external = None
        self.mutation_system = None
        self.server = None
        self.fleet_plane = None
        self.rotator = None
        self.partitioner = None  # device fault domains (partitions > 0)
        self.attributor = None  # per-constraint device-time accounting
        self.recorder = None  # trip-triggered postmortem capture
        self.decisions = None  # per-admission decision log
        self.slo = None  # live streaming SLO engine (obs/slo.py)
        self.corpus = None  # corpus static-analysis plane
        self.integrity = None  # verdict-integrity plane (canary/SDC)
        # framed-transport StreamClient pool (scenario transport
        # "framed"): lazily connected slots, round-robin by the
        # harness, a failed slot reconnects on next use
        self.streams: List[Any] = []
        self.streams_lock = threading.Lock()

    @property
    def base_url(self) -> str:
        return f"{self.server.scheme}://127.0.0.1:{self.server.port}"


class SoakHarness:
    def __init__(self, scenario: Scenario, err=None):
        import sys

        scenario.validate()
        self.scenario = scenario
        self.err = err if err is not None else sys.stderr
        self.replicas: List[_Replica] = []
        self.stub = _StubProvider()
        self.cluster = None  # FakeCluster when fleet/tls is in play
        self.transitions: List[Dict[str, Any]] = []
        self.faults_log: List[Dict[str, Any]] = []
        self.events_log: List[Dict[str, Any]] = []
        self._window_samples: List[Dict[str, Any]] = []
        self._churn_n = itertools.count(1)
        # locality-skewed traffic: (hot_ns, cold_ns, skew) once a
        # locality_churn event fires; None = the uniform ns{i%7} mix
        self._locality: Optional[tuple] = None
        self._req_n = itertools.count()
        self._rr = itertools.count()  # LB round-robin cursor
        self._stream_rr = itertools.count()  # framed pool cursor
        self._t0 = time.monotonic()  # re-stamped at load start
        self._stop = threading.Event()
        self._saved_min_batch = None
        # per-window SLO-breach detection (flight-recorder trigger):
        # _submit counts outcomes, the sampler judges each window
        self._win_lock = threading.Lock()
        self._win_total = 0
        self._win_failed = 0
        # client-side TLS: availability is what the soak measures; the
        # chain-validation contract is pinned by tests/test_fleet.py,
        # so the LB model skips verification and keeps serving across
        # CA rotations exactly like an apiserver with a caBundle lag
        self._ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        self._ssl_ctx.check_hostname = False
        self._ssl_ctx.verify_mode = ssl.CERT_NONE

    def _log(self, msg: str) -> None:
        print(f"soak: {msg}", file=self.err, flush=True)

    # -- build ----------------------------------------------------------------

    def build(self) -> None:
        scn = self.scenario
        if scn.min_device_batch is not None:
            # the run-scoped form of GATEKEEPER_TPU_MIN_DEVICE_BATCH:
            # at soak arrival rates micro-batches are small, and the
            # adaptive floor would keep every batch on the interpreter
            # — lowering it is what puts the REAL fused path under soak
            from ..constraint import tpudriver as _td

            self._saved_min_batch = _td.MIN_DEVICE_BATCH
            _td.MIN_DEVICE_BATCH = int(scn.min_device_batch)
        if scn.replicas > 1 or scn.tls:
            from ..control.events import FakeCluster

            self.cluster = FakeCluster()
        for i in range(scn.replicas):
            self.replicas.append(self._build_replica(f"soak-{i}"))
        self._log(
            f"built {len(self.replicas)} replica(s), "
            f"tls={scn.tls}, constraints={scn.constraints}"
        )

    def _build_replica(self, name: str) -> _Replica:
        from ..agentaction import AgentActionTarget
        from ..constraint import Backend, K8sValidationTarget, TpuDriver
        from ..externaldata import ExternalDataSystem
        from ..metrics import MetricsRegistry
        from ..mutation import MutationSystem
        from ..obs import CostAttributor, DecisionLog, FlightRecorder, Tracer
        from ..webhook.server import WebhookServer

        scn = self.scenario
        rep = _Replica(name)
        rep.metrics = MetricsRegistry()
        # small ring: warmup saturates it BEFORE the measured windows,
        # so the leak sampler sees a full (flat) ring, not a filling one
        rep.tracer = Tracer(max_traces=128)
        rep.driver = TpuDriver()
        rep.driver.set_metrics(rep.metrics)  # phase split + telemetry
        # replica-tagged attribution + flight recorder: multi-replica
        # runs stitch per-replica timelines from the replica field on
        # records and cost tables (docs/observability.md)
        rep.attributor = CostAttributor(metrics=rep.metrics, replica=name)
        rep.driver.set_attributor(rep.attributor)
        # replica-tagged decision log: per-window decision-loss and
        # route mix ride the sampler; a small ring keeps the leak
        # series honest (warmup saturates it before measurement)
        rep.decisions = DecisionLog(metrics=rep.metrics, replica=name)
        rep.recorder = FlightRecorder(
            tracer=rep.tracer,
            attributor=rep.attributor,
            metrics=rep.metrics,
            decisions=rep.decisions,
            replica=name,
        )
        # live SLO engine, judging every admission against the SAME
        # target the offline reporter scores (the scenario's deadline
        # contract + any `slo` overrides) — the live_vs_offline soak
        # check compares the two planes after the run
        from ..obs import SloEngine

        rep.slo = SloEngine(
            target=scn.slo_target(),
            metrics=rep.metrics,
            recorder=rep.recorder,
            replica=name,
        )
        rep.decisions.slo = rep.slo
        rep.client = Backend(rep.driver).new_client(
            K8sValidationTarget(), AgentActionTarget()
        )
        # verdict-integrity plane (docs/robustness.md §Verdict
        # integrity): canary rows ride every padded dispatch and a
        # CRC-sampled shadow oracle re-checks live verdicts — the sdc
        # scenario's bit-flip detection + corruption-quarantine story
        from ..integrity import IntegrityPlane

        rep.integrity = IntegrityPlane(
            metrics=rep.metrics,
            decisions=rep.decisions,
            recorder=rep.recorder,
            quarantine_threshold=2,
        )
        rep.driver.set_integrity(rep.integrity)
        rep.integrity.attach_client(rep.client)
        rep.recorder.add_source("integrity", rep.integrity.snapshot)
        rep.external = ExternalDataSystem(metrics=rep.metrics)
        if self.cluster is not None:
            from ..fleet import FleetPlane

            rep.fleet_plane = FleetPlane(
                self.cluster, name,
                metrics=rep.metrics, publish_interval_s=0.1,
            )
            rep.fleet_plane.attach_cache(rep.external)
        rep.external.upsert({
            "apiVersion": "externaldata.gatekeeper.sh/v1alpha1",
            "kind": "Provider",
            "metadata": {"name": SOAK_PROVIDER},
            "spec": {
                "url": self.stub.url,
                "timeout": 5,
                "failurePolicy": "Ignore",
                "cacheTTLSeconds": 3600,
                "negativeCacheTTLSeconds": 3600,
            },
        })
        rep.client.set_external_data(rep.external)
        rep.client.add_template(
            _template("SoakPrivileged", K8S_TARGET, _PRIV_REGO)
        )
        rep.client.add_template(
            _template("SoakExternal", K8S_TARGET, _EXT_REGO)
        )
        for i in range(scn.constraints):
            rep.client.add_constraint(
                _constraint("SoakPrivileged", f"w{i}", match=_POD_MATCH)
            )
        rep.client.add_constraint(
            _constraint("SoakExternal", "ext", match=_POD_MATCH)
        )
        from ..agentaction import TARGET_NAME as AGENT_TARGET

        rep.client.add_template(
            _template("SoakAgentShell", AGENT_TARGET, _AGENT_REGO)
        )
        rep.client.add_constraint(
            _constraint(
                "SoakAgentShell", "shell",
                match={"tools": ["shell.*"]},
                params={"allowed": ["ls", "cat"]},
            )
        )
        rep.mutation_system = MutationSystem(metrics=rep.metrics)
        rep.mutation_system.upsert(_assign_metadata("soak-base", "soak"))
        from ..analysis.corpus import CorpusPlane

        # corpus static-analysis plane (docs/analysis.md §Corpus
        # analysis): recomputed in the background when churn moves the
        # policy generation — the sampler's maybe_recompute() poll
        # mirrors production's /readyz-driven kick, never the request
        # path — and the partition planner consumes prunable_keys for
        # verdict-safe static pruning
        rep.corpus = CorpusPlane(
            rep.client,
            mutation_system=rep.mutation_system,
            external_data=rep.external,
            metrics=rep.metrics,
        )
        rep.corpus.refresh()

        rotator = None
        if scn.tls:
            import tempfile

            from ..fleet import FleetCertRotator, SecretCertStore

            store = SecretCertStore(
                self.cluster, name="soak-webhook-cert",
                namespace="gatekeeper-system", replica_id=name,
                metrics=rep.metrics,
            )
            rotator = FleetCertRotator(
                tempfile.mkdtemp(prefix=f"gk-soak-{name}-"), store,
                metrics=rep.metrics,
            )
            rotator.ensure()
            rotator.start()
        rep.rotator = rotator

        rep.server = WebhookServer(
            rep.client,
            K8S_TARGET,
            agent_review=True,
            mutation_system=rep.mutation_system,
            metrics=rep.metrics,
            tracer=rep.tracer,
            tls=scn.tls,
            rotator=rotator,
            window_ms=scn.window_ms,
            request_timeout=max(5.0, scn.deadline_s * 8),
            # denial records carry trace ids (the traceparent
            # propagation acceptance reads them)
            log_denies=True,
            recorder=rep.recorder,
            decision_log=rep.decisions,
            # admission scheduling (docs/operations.md §Admission
            # scheduling): the scenario's policy on every batcher
            # plane, fed by the replica's own streaming SLO engine
            # (saturation feedback) and cost attributor (batch cost
            # prediction seeds)
            sched_policy=scn.sched_policy,
            slo=rep.slo,
            attributor=rep.attributor,
            integrity=rep.integrity,
            # wire-speed ingest plane (docs/ingest.md): framed
            # scenarios mount the stream listener next to the HTTP
            # front door; the harness then submits over multiplexed
            # StreamClients with the deadline in each frame header
            ingest=(scn.transport == "framed"),
        )
        rep.recorder.add_source(
            "webhook", lambda rep=rep: {
                "shed": rep.server.batcher.shed_count,
                "batch_failures": rep.server.batcher.batch_failures,
            },
        )
        # scenario-tuned breakers (the stock 30 s recovery would spend
        # a whole fault window waiting): share metrics/tracer so the
        # transition series and spans land in the same registries
        br = scn.breaker

        def _ledger_subscribe(breaker, plane, replica):
            # transition ledger keyed by breaker NAME: multi-breaker
            # planes (one per device) stay exactly accounted instead of
            # collapsing into one per-plane stream
            breaker.subscribe(
                lambda f, t, breaker=breaker, plane=plane, replica=replica: (
                    self.transitions.append({
                        "t_s": round(time.monotonic() - self._t0, 3),
                        "replica": replica,
                        "plane": plane,
                        "breaker": breaker.name,
                        "from": f,
                        "to": t,
                    })
                )
            )

        for batcher, plane in (
            (rep.server.batcher, "validation"),
            (rep.server.mutate_batcher, "mutation"),
            (rep.server.agent_batcher, "agent"),
        ):
            if batcher is None:
                continue
            if plane == "validation" and scn.partitions:
                # device fault domains replace the single validation
                # breaker: per-(device, plane) breakers live in the
                # PartitionDispatcher (docs/robustness.md §Fault
                # domains)
                continue
            breaker = CircuitBreaker(
                failure_threshold=int(br.get("failure_threshold", 3)),
                recovery_seconds=float(br.get("recovery_seconds", 5.0)),
                plane=plane,
                metrics=rep.metrics,
                tracer=rep.tracer,
                recorder=rep.recorder,
            )
            batcher.breaker = breaker
            _ledger_subscribe(breaker, plane, name)
            if rep.fleet_plane is not None:
                rep.fleet_plane.register_breaker(
                    f"device:{plane}", breaker
                )
        if scn.partitions:
            from ..parallel.partition import PartitionDispatcher

            disp = PartitionDispatcher(
                rep.client,
                K8S_TARGET,
                k=scn.partitions,
                plane="validation",
                metrics=rep.metrics,
                tracer=rep.tracer,
                failure_threshold=int(br.get("failure_threshold", 3)),
                recovery_seconds=float(br.get("recovery_seconds", 5.0)),
                breaker_listener=lambda b, replica=name: (
                    _ledger_subscribe(b, "validation", replica)
                ),
                recorder=rep.recorder,
                corpus=rep.corpus,
            )
            rep.partitioner = disp
            rep.recorder.add_source("partitions", disp.postmortem)
            # compile_storm postmortems capture the program-store state
            # table + per-partition signatures (docs/compile.md)
            rep.recorder.add_source("programs", disp.programs_table)
            rep.server.partitioner = disp  # server.stop() closes it
            rep.server.batcher.partitioner = disp
            rep.server.batcher.breaker = None
            # corruption quarantine needs the dispatcher to re-home a
            # bit-flipping device's partitions (built after the server,
            # so the server's own attach above never saw it)
            rep.integrity.attach_dispatcher(disp)
            if rep.fleet_plane is not None:
                # per-device breakers gossip under their
                # device:validation:<id> keys as they are created
                disp.set_fleet(rep.fleet_plane)
        if rep.fleet_plane is not None:
            rep.fleet_plane.start()
        # the LB model: readiness flip takes the replica out of
        # rotation BEFORE the listener closes (graceful drain)
        rep.server.on_drain(
            lambda rep=rep: setattr(rep, "active", False)
        )
        rep.server.start()
        return rep

    # -- request bodies -------------------------------------------------------

    def _pod_request(self, i: int, violating: bool) -> Dict[str, Any]:
        req = _pod_request(i, violating, self.scenario.external_keys)
        tn = self.scenario.tenants
        if tn is not None:
            # two-tenant mix (multi_tenant_overload): a deterministic
            # noisy/quiet namespace split — the scheduler's fair-share
            # quotas key on the namespace, and the sampler reads each
            # class's attainment/shed from the decision log
            frac = float(tn.get("noisy_fraction", 0.75))
            ns = (
                str(tn.get("noisy_ns", "ns-noisy"))
                if (i % 100) < int(round(frac * 100))
                else str(tn.get("quiet_ns", "ns-quiet"))
            )
            req["namespace"] = ns
            req["object"]["metadata"]["namespace"] = ns
        loc = self._locality
        if loc is not None:
            # deterministic 90/10 (skew) namespace split: the hot
            # group's partitions stay hot, the cold group's sit mask-
            # skipped for most batches
            hot, cold, skew = loc
            ns = hot if (i % 100) < int(round(skew * 100)) else cold
            req["namespace"] = ns
            req["object"]["metadata"]["namespace"] = ns
        return req

    def _body(self, plane: str) -> bytes:
        i = next(self._req_n)
        scn = self.scenario
        violating = (i % 997) / 997.0 < scn.violating_fraction
        if plane == "agent":
            doc = {
                "apiVersion": "agentaction.gatekeeper.sh/v1",
                "kind": "AgentActionReview",
                "request": {
                    "uid": f"call-{i}",
                    "id": f"call-{i}",
                    "agent": f"planner-{i % 3}",
                    "session": f"s-{i % 11}",
                    "tool": "shell.exec",
                    "arguments": {
                        "command": "rm" if violating else "ls"
                    },
                    "capabilities": ["exec"],
                    "skill": {"name": "fs-tools", "publisher": "acme",
                              "signed": True, "digest": "sha256:abc"},
                },
            }
        else:
            doc = {
                "apiVersion": "admission.k8s.io/v1",
                "kind": "AdmissionReview",
                "request": self._pod_request(i, violating),
            }
        return json.dumps(doc).encode()

    _PATHS = {
        "validation": "/v1/admit",
        "mutation": "/v1/mutate",
        "agent": "/v1/agent/review",
    }

    def _submit(self, plane: str):
        """One open-loop request: round-robin over ACTIVE replicas,
        POST, classify. Returns (status, outcome) for the generator.
        Outcomes also feed the per-window SLO-breach detector (a bad
        window trips a flight-recorder postmortem)."""
        status, outcome = self._submit_once(plane)
        with self._win_lock:
            self._win_total += 1
            if status != 200:
                self._win_failed += 1
        return status, outcome

    # framed-transport pool width: StreamClients per replica. Each
    # client is multiplexed (many in-flight frames share one socket),
    # so a handful of sockets carries the whole arrival schedule —
    # the connection-efficiency contrast with conn-per-request HTTP
    _STREAM_POOL = 8

    def _stream_client(self, rep: _Replica, slot: int):
        """The replica's StreamClient for `slot`, connecting lazily.
        None when the listener refuses (replica draining)."""
        from ..ingest.transport import StreamClient

        with rep.streams_lock:
            if not rep.streams:
                rep.streams = [None] * self._STREAM_POOL
            client = rep.streams[slot]
            if client is None:
                try:
                    client = StreamClient(
                        "127.0.0.1", rep.server.ingest.port,
                        connect_timeout=2.0,
                    )
                except OSError:
                    return None
                rep.streams[slot] = client
        return client

    def _drop_stream(self, rep: _Replica, slot: int, client) -> None:
        """Retire a failed StreamClient slot; next use reconnects."""
        with rep.streams_lock:
            if rep.streams and rep.streams[slot] is client:
                rep.streams[slot] = None
        try:
            client.close()
        except Exception:
            pass

    def _submit_framed(self, rep: _Replica, plane: str, body: bytes,
                       timeout: float):
        """One admission over the framed stream transport: the
        scenario deadline rides the frame header (the server's
        batchers read it via deadline_scope), the verdict comes back
        as (status, AdmissionReview bytes) — classified exactly like
        the urllib path so windows/checks compare across transports."""
        from concurrent.futures import TimeoutError as _FutTimeout

        from ..ingest.transport import (
            PLANE_AGENT, PLANE_MUTATE, PLANE_VALIDATE, ProtocolError,
        )

        plane_tag = {
            "validation": PLANE_VALIDATE,
            "mutation": PLANE_MUTATE,
            "agent": PLANE_AGENT,
        }[plane]
        slot = next(self._stream_rr) % self._STREAM_POOL
        client = self._stream_client(rep, slot)
        if client is None:
            return 0, CONN_ERROR
        try:
            status, payload = client.request(
                body, plane_tag,
                budget_ms=int(self.scenario.deadline_s * 1000),
                timeout=timeout,
            )
        except (_FutTimeout, TimeoutError):
            return 0, CLIENT_TIMEOUT
        except (ProtocolError, ConnectionError, OSError):
            self._drop_stream(rep, slot, client)
            return 0, CONN_ERROR
        if int(status) != 200:
            return int(status), f"http_{int(status)}"
        try:
            doc = json.loads(payload)
        except ValueError:
            return 0, CONN_ERROR
        allowed = bool(
            ((doc.get("response") or {}).get("allowed", False))
        )
        return 200, ("ok" if allowed else "denied")

    def _submit_once(self, plane: str):
        live = [r for r in self.replicas if r.active]
        if not live:
            return 0, CONN_ERROR
        rep = live[next(self._rr) % len(live)]
        body = self._body(plane)
        timeout = max(5.0, self.scenario.deadline_s * 8)
        if self.scenario.transport == "framed":
            return self._submit_framed(rep, plane, body, timeout)
        url = rep.base_url + self._PATHS[plane]
        req = urllib.request.Request(
            url, data=body,
            headers={"Content-Type": "application/json"}, method="POST",
        )
        try:
            with urllib.request.urlopen(
                req, timeout=timeout,
                context=self._ssl_ctx if self.scenario.tls else None,
            ) as resp:
                doc = json.loads(resp.read())
            allowed = bool(
                ((doc.get("response") or {}).get("allowed", False))
            )
            return 200, ("ok" if allowed else "denied")
        except urllib.error.HTTPError as e:
            return int(e.code), f"http_{e.code}"
        except TimeoutError:
            return 0, CLIENT_TIMEOUT
        except urllib.error.URLError as e:
            if isinstance(getattr(e, "reason", None), TimeoutError):
                return 0, CLIENT_TIMEOUT
            return 0, CONN_ERROR
        except (ConnectionError, OSError):
            return 0, CONN_ERROR

    # -- scenario actions -----------------------------------------------------

    def _run_event(self, action: str, params: Dict[str, Any]) -> None:
        if action == "phase":
            return  # phases only label reporting windows
        if action == "add_constraints":
            count = int(params.get("count", 25))
            stamp = next(self._churn_n)  # unique names across adds
            for rep in self.replicas:
                for j in range(count):
                    rep.client.add_constraint(_constraint(
                        "SoakPrivileged", f"churn{stamp}-{j}",
                        match=_POD_MATCH,
                    ))
        elif action == "locality_churn":
            # two namespace-affine constraint groups: identical match
            # blocks within a group give one locality token each, so
            # the guided planner co-locates them — and the traffic
            # skew applied in _pod_request makes one group hot while
            # the other's partitions sit mask-skipped
            count = int(params.get("count", 10))
            hot = str(params.get("hot_ns", "ns-aff-hot"))
            cold = str(params.get("cold_ns", "ns-aff-cold"))
            skew = float(params.get("skew", 0.9))
            stamp = next(self._churn_n)
            for rep in self.replicas:
                for ns in (hot, cold):
                    for j in range(count):
                        rep.client.add_constraint(_constraint(
                            "SoakPrivileged",
                            f"aff{stamp}-{ns}-{j}",
                            match={**_POD_MATCH, "namespaces": [ns]},
                        ))
            self._locality = (hot, cold, skew)
        elif action == "add_template":
            n = next(self._churn_n)
            kind = f"SoakChurn{n}"
            rego = _CHURN_REGO.format(n=n)
            for rep in self.replicas:
                rep.client.add_template(_template(kind, K8S_TARGET, rego))
                rep.client.add_constraint(
                    _constraint(kind, f"churn-t{n}", match=_POD_MATCH)
                )
        elif action == "ingest_wave":
            # template ingest burst (docs/compile.md): `count` new
            # template kinds + constraints land while traffic flows.
            # Each new kind compiles exactly once; signature-unchanged
            # partitions carry forward and churned ones restage in the
            # background — the `ingest_zero_degraded` report check pins
            # zero degraded dispatches and zero 5xx through the wave.
            count = int(params.get("count", 500))
            for _ in range(count):
                n = next(self._churn_n)
                kind = f"SoakChurn{n}"
                rego = _CHURN_REGO.format(n=n)
                for rep in self.replicas:
                    rep.client.add_template(
                        _template(kind, K8S_TARGET, rego)
                    )
                    rep.client.add_constraint(
                        _constraint(kind, f"wave-t{n}", match=_POD_MATCH)
                    )
        elif action == "add_provider":
            n = next(self._churn_n)
            for rep in self.replicas:
                rep.external.upsert({
                    "apiVersion": "externaldata.gatekeeper.sh/v1alpha1",
                    "kind": "Provider",
                    "metadata": {"name": f"soak-extra-{n}"},
                    "spec": {
                        "url": self.stub.url,
                        "timeout": 5,
                        "failurePolicy": "Ignore",
                        "cacheTTLSeconds": 600,
                    },
                })
        elif action == "add_mutator":
            n = next(self._churn_n)
            for rep in self.replicas:
                rep.mutation_system.upsert(
                    _assign_metadata(f"soak-churn-{n}", f"soak-{n}")
                )
        elif action == "arm_fault":
            FAULTS.arm(
                params["point"],
                mode=params.get("mode", "error"),
                count=int(params.get("count", -1)),
                after=int(params.get("after", 0)),
                delay_s=float(params.get("delay", 0.05)),
            )
        elif action == "disarm_faults":
            snap = FAULTS.snapshot()
            self.faults_log.append({
                "t_s": round(time.monotonic() - self._t0, 3),
                "disarmed": snap,
            })
            FAULTS.reset()
        elif action == "rotate_certs":
            rep = next(
                (r for r in self.replicas if r.active and r.rotator),
                None,
            )
            if rep is None:
                self._log("rotate_certs: no TLS rotator (no-op)")
                return
            rot = rep.rotator
            rec, _won = rot.store.offer(
                rot.generate_pair(),
                expected_generation=rot.cert_generation,
            )
            rot._install_record(rec)
            self._log(
                f"rotated certs via {rep.name} -> generation "
                f"{rot.cert_generation}"
            )
        elif action == "quarantine_device":
            dev = int(params.get("device", 1))
            for rep in self.replicas:
                if rep.partitioner is not None:
                    rep.partitioner.quarantine(dev)
        elif action == "heal_device":
            dev = int(params.get("device", 1))
            for rep in self.replicas:
                if rep.partitioner is not None:
                    rep.partitioner.heal(dev)
        elif action == "selftest_device":
            # golden self-test: the only heal path for a corruption
            # quarantine (docs/robustness.md §Verdict integrity)
            dev = int(params.get("device", 1))
            for rep in self.replicas:
                if rep.integrity is not None:
                    ok = rep.integrity.selftest(dev)
                    self._log(
                        f"selftest device={dev} on {rep.name}: "
                        f"{'pass' if ok else 'fail'}"
                    )
        elif action == "kill_replica":
            idx = int(params.get("replica", 0))
            rep = self.replicas[idx]
            rep.active = False  # LB-out first (readiness model)

            def _graceful():
                # graceful drain: readiness already flipped; the server
                # closes its listener and completes in-flight requests
                rep.server.stop()
                if rep.fleet_plane is not None:
                    rep.fleet_plane.stop()
                if rep.rotator is not None:
                    rep.rotator.stop()

            threading.Thread(
                target=_graceful, name=f"gk-soak-kill-{rep.name}",
                daemon=True,
            ).start()
        else:  # pragma: no cover - Scenario.validate rejects these
            raise ValueError(f"unknown action {action!r}")

    def _event_loop(self) -> None:
        for ev in self.scenario.events:
            while not self._stop.is_set():
                delay = (self._t0 + ev.at_s) - time.monotonic()
                if delay <= 0:
                    break
                self._stop.wait(min(delay, 0.2))
            if self._stop.is_set():
                return
            t_rel = round(time.monotonic() - self._t0, 3)
            try:
                self._run_event(ev.action, ev.params)
                self._log(f"event t={t_rel}s: {ev.action} {ev.params}")
                self.events_log.append({
                    "t_s": t_rel, "action": ev.action, **ev.params,
                })
            except Exception as e:
                self._log(f"event t={t_rel}s {ev.action} FAILED: {e}")
                self.events_log.append({
                    "t_s": t_rel, "action": ev.action,
                    "error": str(e), **ev.params,
                })

    # -- per-window sampling --------------------------------------------------

    def _rss_kb(self) -> Optional[int]:
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return int(line.split()[1])
        except OSError:
            return None
        return None

    def _cumulative(self) -> Dict[str, Any]:
        """Cumulative server-side counters + instantaneous gauges,
        summed over replicas (dead replicas keep their last counts —
        diffs stay correct)."""
        shed = failures = cache_entries = cache_evictions = 0
        trace_ring = metrics_series = render_cache = 0
        cert_gen = metrics_dropped = 0
        dec_recorded = dec_dropped = dec_sampled = dec_ring = 0
        dec_routes: Dict[str, int] = {}
        pt_p50 = pt_max = None  # pruned-dispatch width across replicas
        # live SLO plane (obs/slo.py): saturation is the WORST replica
        # (the autoscaler scales on the hottest pod), attainment is
        # request-weighted across replicas, breaches/burning aggregate
        slo_sat = None
        slo_burning = False
        slo_breaches = 0
        slo_fast_n = slo_fast_ok = 0.0
        slo_slow_n = slo_slow_ok = 0.0
        slo_burn_fast = 0.0
        degraded = 0  # webhook_degraded_dispatch_total across planes
        program_swaps = program_carryforwards = program_compiles = 0
        corpus_recomputes = 0  # corpus-analysis background refreshes
        # admission scheduler (gatekeeper_tpu/sched): shed split by
        # typed reason + per-tenant-class attainment read straight
        # from the decision log's full-stream tenant counters
        sched_pred = sched_capped = sched_qfull = sched_throttled = 0
        # verdict-integrity plane: canary mismatch batches + shadow
        # divergences (cumulative), corruption-quarantined devices
        # (instantaneous) — the sdc check's evidence columns
        canary_mism = shadow_div = quarantined_now = 0
        # wire-speed ingest plane (docs/ingest.md): frames served,
        # protocol sheds, live framed connections, the decode route
        # split, and the zero-copy scanner's cumulative seconds/count
        # (the ingest_decode_seconds distribution) — what the
        # ingest_rps_sustained / decode_span_bounded checks consume
        ing_frames = ing_proto_err = ing_conns = 0
        ing_routes: Dict[str, int] = {}
        ing_dec_s = 0.0
        ing_dec_n = 0
        tn = self.scenario.tenants or {}
        quiet_ns = str(tn.get("quiet_ns", "ns-quiet"))
        noisy_ns = str(tn.get("noisy_ns", "ns-noisy"))
        tclass = {
            "quiet": {"count": 0, "ok": 0, "shed": 0},
            "noisy": {"count": 0, "ok": 0, "shed": 0},
        }
        for rep in self.replicas:
            for b in (
                rep.server.batcher,
                rep.server.mutate_batcher,
                rep.server.agent_batcher,
                rep.server.agent_mutate_batcher,
            ):
                if b is not None:
                    shed += b.shed_count
                    failures += b.batch_failures
                    sched = getattr(b, "sched", None)
                    if sched is not None:
                        ss = sched.snapshot()
                        sched_pred += ss["sheds"]["predicted_miss"]
                        sched_capped += ss["sheds"]["tenant_capped"]
                        sched_qfull += ss["sheds"]["queue_full"]
                        sched_throttled += sum(
                            t["throttled"]
                            for t in ss["tenants"].values()
                        )
            if self.scenario.tenants and rep.decisions is not None:
                for key, row in rep.decisions.tenant_stats().items():
                    name = key.split("/", 1)[-1]
                    cls = (
                        "quiet" if name == quiet_ns
                        else "noisy" if name == noisy_ns
                        else None
                    )
                    if cls is None:
                        continue
                    tclass[cls]["count"] += row["count"]
                    tclass[cls]["ok"] += row["ok"]
                    tclass[cls]["shed"] += row["shed"]
            cache_entries += len(rep.external.cache)
            cache_evictions += rep.external.cache.evictions
            trace_ring += rep.tracer.size()["ring"]
            metrics_series += rep.metrics.series_count()
            # the cardinality cap's drop count: series_count staying
            # flat WITH drops accruing means the cap is holding (the
            # bounded-registry evidence), not that churn stopped
            metrics_dropped += sum(
                rep.metrics.dropped_series().values()
            )
            size_fn = getattr(rep.driver, "render_cache_size", None)
            if size_fn is not None:
                render_cache += size_fn()
            if rep.rotator is not None:
                cert_gen = max(cert_gen, rep.rotator.cert_generation)
            if rep.decisions is not None:
                # decision-plane health: recorded vs lost (rate-gated
                # drops + denial-log drops = "decision loss") and the
                # cumulative route mix, diffed per window below
                dsnap = rep.decisions.snapshot()
                dec_recorded += dsnap["recorded"]
                dec_dropped += (
                    dsnap["dropped"] + dsnap["denial_log_dropped"]
                )
                dec_sampled += dsnap["sampled_out"]
                dec_ring += dsnap["retained"]
                for route, n in dsnap["routes"].items():
                    dec_routes[route] = dec_routes.get(route, 0) + n
            if rep.slo is not None:
                auto = rep.slo.autoscaler()
                s = auto.get("saturation")
                if s is not None:
                    slo_sat = s if slo_sat is None else max(slo_sat, s)
                slo_burning = slo_burning or bool(auto.get("burning"))
                slo_breaches += int(auto.get("breaches") or 0)
                ssnap = rep.slo.snapshot()
                for p in ssnap["planes"].values():
                    slo_burn_fast = max(
                        slo_burn_fast, p["burn_rate_fast"]
                    )
                    if p["attainment_fast"] is not None:
                        slo_fast_n += p["requests_fast"]
                        slo_fast_ok += (
                            p["attainment_fast"] * p["requests_fast"]
                        )
                    if p["attainment_slow"] is not None:
                        slo_slow_n += p["requests_slow"]
                        slo_slow_ok += (
                            p["attainment_slow"] * p["requests_slow"]
                        )
            # degraded dispatches (breaker-open / all-dead host
            # routing): the ingest_zero_degraded check's evidence —
            # host-rung routing during a background restage does NOT
            # count here, only genuine degradation does
            try:
                msnap = rep.metrics.snapshot()
            except Exception:
                msnap = {}
            counters = msnap.get("counters", {})
            degraded += sum(
                v for k, v in counters.items()
                if k.startswith("webhook_degraded_dispatch_total")
            )
            ing = getattr(rep.server, "ingest", None)
            if ing is not None:
                try:
                    istats = ing.stats()
                except Exception:
                    istats = {}
                ing_frames += int(istats.get("frames_total", 0))
                ing_proto_err += int(
                    istats.get("protocol_errors_total", 0)
                )
                ing_conns += int(istats.get("connections_active", 0))
                for route, n in (istats.get("decode") or {}).items():
                    ing_routes[route] = ing_routes.get(route, 0) + n
                for k, d in msnap.get("distributions", {}).items():
                    if k.startswith("ingest_decode_seconds"):
                        ing_dec_s += float(d.get("sum") or 0.0)
                        ing_dec_n += int(d.get("count") or 0)
            drv = rep.driver
            program_swaps += int(getattr(drv, "subset_swaps", 0) or 0)
            program_carryforwards += int(
                getattr(drv, "subset_carryforwards", 0) or 0
            )
            program_compiles += int(
                getattr(drv, "program_compiles", 0) or 0
            )
            if rep.corpus is not None:
                # the sampler IS the recompute kick (production's
                # /readyz poll): a generation-compare + time-compare,
                # with the analysis itself on a background thread —
                # churn waves trigger one debounced recompute, never
                # one per add and never request-path work
                try:
                    rep.corpus.maybe_recompute()
                    corpus_recomputes += int(rep.corpus.recomputes)
                except Exception:
                    pass
            if rep.integrity is not None:
                try:
                    isnap = rep.integrity.snapshot()
                    canary_mism += isnap["canary"]["mismatch_batches"]
                    shadow_div += isnap["shadow"]["divergences"]
                    quarantined_now += len(isnap["quarantined"])
                except Exception:
                    pass
            if rep.partitioner is not None:
                # pruning width (mask-gated partition skipping): p50/
                # max partitions touched per batch over the recent
                # window — the locality_skew phase's evidence series
                st = rep.partitioner.touched_stats()
                if st["p50"] is not None:
                    pt_p50 = (
                        st["p50"] if pt_p50 is None
                        else max(pt_p50, st["p50"])
                    )
                    pt_max = (
                        st["max"] if pt_max is None
                        else max(pt_max, st["max"])
                    )
        return {
            "shed_cum": shed,
            "batch_failures_cum": failures,
            "transitions_cum": len(self.transitions),
            "fetches_cum": self.stub.fetches,
            "cache_entries": cache_entries,
            "cache_evictions": cache_evictions,
            "trace_ring": trace_ring,
            "metrics_series": metrics_series,
            "metrics_dropped": metrics_dropped,
            "render_cache": render_cache,
            "rss_kb": self._rss_kb(),
            "cert_generation": cert_gen,
            "decisions_cum": dec_recorded,
            "decisions_dropped_cum": dec_dropped,
            "decisions_sampled_out_cum": dec_sampled,
            "decision_ring": dec_ring,
            "decision_routes_cum": dec_routes,
            "partitions_touched_p50": pt_p50,
            "partitions_touched_max": pt_max,
            "degraded_cum": degraded,
            "sched_predicted_miss_cum": sched_pred,
            "sched_tenant_capped_cum": sched_capped,
            "sched_queue_full_cum": sched_qfull,
            "sched_throttled_cum": sched_throttled,
            "tenant_class_cum": tclass,
            "program_swaps_cum": program_swaps,
            "program_carryforwards_cum": program_carryforwards,
            "program_compiles_cum": program_compiles,
            "corpus_recomputes_cum": corpus_recomputes,
            "canary_mismatch_cum": canary_mism,
            "shadow_divergence_cum": shadow_div,
            "quarantined_devices": quarantined_now,
            "ingest_frames_cum": ing_frames,
            "ingest_protocol_errors_cum": ing_proto_err,
            "ingest_connections_active": ing_conns,
            "ingest_decode_routes_cum": ing_routes,
            "ingest_decode_seconds_cum": ing_dec_s,
            "ingest_decode_count_cum": ing_dec_n,
            # live SLO plane (obs/slo.py)
            "slo_saturation": slo_sat,
            "slo_burning": slo_burning,
            "slo_breaches_cum": slo_breaches,
            "slo_burn_fast": round(slo_burn_fast, 3),
            "slo_live_attainment_fast": (
                slo_fast_ok / slo_fast_n if slo_fast_n else None
            ),
            "slo_live_attainment_slow": (
                slo_slow_ok / slo_slow_n if slo_slow_n else None
            ),
            "slo_live_requests_slow": int(slo_slow_n),
        }

    def _sampler_loop(self) -> None:
        scn = self.scenario
        n_windows = max(1, int(round(scn.duration_s / scn.window_s)))
        prev = self._cumulative()
        for i in range(n_windows):
            target = self._t0 + (i + 1) * scn.window_s
            while not self._stop.is_set():
                delay = target - time.monotonic()
                if delay <= 0:
                    break
                self._stop.wait(min(delay, 0.2))
            cur = self._cumulative()
            dec_n = (
                cur["ingest_decode_count_cum"]
                - prev["ingest_decode_count_cum"]
            )
            dec_s = (
                cur["ingest_decode_seconds_cum"]
                - prev["ingest_decode_seconds_cum"]
            )
            self._window_samples.append({
                "shed": cur["shed_cum"] - prev["shed_cum"],
                "batch_failures": (
                    cur["batch_failures_cum"]
                    - prev["batch_failures_cum"]
                ),
                "breaker_transitions": (
                    cur["transitions_cum"] - prev["transitions_cum"]
                ),
                "fetches": cur["fetches_cum"] - prev["fetches_cum"],
                "cache_entries": cur["cache_entries"],
                "cache_evictions": cur["cache_evictions"],
                "trace_ring": cur["trace_ring"],
                "metrics_series": cur["metrics_series"],
                "metrics_dropped": cur["metrics_dropped"],
                "render_cache": cur["render_cache"],
                "rss_kb": cur["rss_kb"],
                "cert_generation": cur["cert_generation"],
                # decision-plane per-window view: records kept vs lost
                # (rate-gate + denial-log drops), the bounded-ring leak
                # series, and the route mix this window served
                "decisions": (
                    cur["decisions_cum"] - prev["decisions_cum"]
                ),
                "decisions_dropped": (
                    cur["decisions_dropped_cum"]
                    - prev["decisions_dropped_cum"]
                ),
                "decisions_sampled_out": (
                    cur["decisions_sampled_out_cum"]
                    - prev["decisions_sampled_out_cum"]
                ),
                "decision_ring": cur["decision_ring"],
                "decision_routes": {
                    route: n - prev["decision_routes_cum"].get(route, 0)
                    for route, n in cur["decision_routes_cum"].items()
                },
                # pruning width at this window's close (running p50/
                # max over the dispatcher's recent-batch window)
                "partitions_touched_p50": (
                    cur["partitions_touched_p50"]
                ),
                "partitions_touched_max": (
                    cur["partitions_touched_max"]
                ),
                # compile plane (docs/compile.md): degraded dispatches
                # this window (the ingest check's evidence), plus the
                # swap/carry-forward/compile activity behind the wave
                "degraded_dispatches": (
                    cur["degraded_cum"] - prev["degraded_cum"]
                ),
                # admission scheduler: typed shed split this window +
                # the per-tenant-class attainment/shed deltas read
                # from the decision log (multi_tenant_overload's
                # evidence columns)
                "sched_predicted_miss": (
                    cur["sched_predicted_miss_cum"]
                    - prev["sched_predicted_miss_cum"]
                ),
                "sched_tenant_capped": (
                    cur["sched_tenant_capped_cum"]
                    - prev["sched_tenant_capped_cum"]
                ),
                "sched_queue_full": (
                    cur["sched_queue_full_cum"]
                    - prev["sched_queue_full_cum"]
                ),
                "sched_throttled": (
                    cur["sched_throttled_cum"]
                    - prev["sched_throttled_cum"]
                ),
                "tenant_classes": {
                    cls: {
                        "requests": (
                            cur["tenant_class_cum"][cls]["count"]
                            - prev["tenant_class_cum"][cls]["count"]
                        ),
                        "ok": (
                            cur["tenant_class_cum"][cls]["ok"]
                            - prev["tenant_class_cum"][cls]["ok"]
                        ),
                        "shed": (
                            cur["tenant_class_cum"][cls]["shed"]
                            - prev["tenant_class_cum"][cls]["shed"]
                        ),
                    }
                    for cls in ("quiet", "noisy")
                } if self.scenario.tenants else None,
                "program_swaps": (
                    cur["program_swaps_cum"] - prev["program_swaps_cum"]
                ),
                "program_carryforwards": (
                    cur["program_carryforwards_cum"]
                    - prev["program_carryforwards_cum"]
                ),
                "program_compiles": (
                    cur["program_compiles_cum"]
                    - prev["program_compiles_cum"]
                ),
                # corpus analysis (docs/analysis.md): debounced
                # background recomputes completed this window — the
                # ingest_corpus_recompute check's evidence
                "corpus_recomputes": (
                    cur["corpus_recomputes_cum"]
                    - prev["corpus_recomputes_cum"]
                ),
                # verdict-integrity plane (docs/robustness.md §Verdict
                # integrity): canary mismatch batches + shadow-oracle
                # divergences this window, and how many devices sit in
                # corruption quarantine at the window's close — the
                # sdc_detected_and_quarantined check's evidence
                "canary_mismatches": (
                    cur["canary_mismatch_cum"]
                    - prev["canary_mismatch_cum"]
                ),
                "shadow_divergences": (
                    cur["shadow_divergence_cum"]
                    - prev["shadow_divergence_cum"]
                ),
                "quarantined_devices": cur["quarantined_devices"],
                # wire-speed ingest plane (docs/ingest.md): frames +
                # protocol sheds this window, live framed connections
                # at the close, the decode route split, and the
                # scanner's mean per-frame decode cost in ms — the
                # decode_span_bounded check's evidence column
                "ingest_frames": (
                    cur["ingest_frames_cum"]
                    - prev["ingest_frames_cum"]
                ),
                "ingest_protocol_errors": (
                    cur["ingest_protocol_errors_cum"]
                    - prev["ingest_protocol_errors_cum"]
                ),
                "ingest_connections": cur["ingest_connections_active"],
                "ingest_decode_routes": {
                    route: (
                        n
                        - prev["ingest_decode_routes_cum"].get(route, 0)
                    )
                    for route, n in
                    cur["ingest_decode_routes_cum"].items()
                },
                "ingest_decode_ms_mean": (
                    round(dec_s / dec_n * 1000.0, 4) if dec_n else None
                ),
                # live SLO plane at this window's close: worst-replica
                # saturation, live fast-window attainment/burn, any
                # plane in the burning state, breaches fired this
                # window (each breach = one slo_breach flight record)
                "slo_saturation": cur["slo_saturation"],
                "slo_burning": cur["slo_burning"],
                "slo_burn_fast": cur["slo_burn_fast"],
                "slo_live_attainment": (
                    cur["slo_live_attainment_fast"]
                ),
                "slo_breaches": (
                    cur["slo_breaches_cum"] - prev["slo_breaches_cum"]
                ),
            })
            prev = cur
            # per-window SLO-breach detector: a window whose failure
            # rate crosses the threshold trips one postmortem on every
            # active replica (the recorders rate-limit the storm)
            with self._win_lock:
                total, failed = self._win_total, self._win_failed
                self._win_total = self._win_failed = 0
            if total >= 20 and failed / total > 0.2:
                for rep in self.replicas:
                    if rep.recorder is not None and rep.active:
                        rep.recorder.trigger(
                            "slo_window_breach",
                            window=i,
                            requests=total,
                            failed=failed,
                            failure_rate=round(failed / total, 4),
                        )
            if self._stop.is_set():
                return

    # -- device-time split ----------------------------------------------------

    def _device_time_split(self) -> Dict[str, Any]:
        """Aggregate the driver's phase_seconds metric across replicas:
        where a second of admission work actually went — host
        flatten/encode vs device execution vs violation render. This is
        the utilization denominator ROADMAP item 1/3 speed work is
        judged against."""
        import re

        totals: Dict[str, float] = {}
        rx = re.compile(r'phase="([a-z_]+)"')
        for rep in self.replicas:
            dists = rep.metrics.snapshot()["distributions"]
            for key, d in dists.items():
                if not key.startswith("driver_phase_seconds"):
                    continue
                m = rx.search(key)
                if not m:
                    continue
                totals[m.group(1)] = (
                    totals.get(m.group(1), 0.0) + float(d["sum"])
                )
        total = sum(totals.values())
        out: Dict[str, Any] = {
            "seconds": {k: round(v, 4) for k, v in sorted(totals.items())}
        }
        if total > 0:
            out["fractions"] = {
                k: round(v / total, 4) for k, v in sorted(totals.items())
            }
            # the utilization headline: device share of total work
            out["device_fraction"] = round(
                totals.get("device_dispatch", 0.0) / total, 4
            )
        return out

    # -- warmup / run / teardown ----------------------------------------------

    def warmup(self) -> float:
        """Closed-loop pre-load: compile the fused routes and fill the
        external-data cache so the measured windows start from steady
        state (cold compile belongs to readiness, not to the SLO)."""
        from concurrent.futures import ThreadPoolExecutor

        t0 = time.monotonic()
        for rep in self.replicas:
            try:
                rep.server.warmup()
            except Exception:
                pass
        with ThreadPoolExecutor(max_workers=16) as ex:
            for plane, n in (
                ("validation", 96), ("mutation", 32), ("agent", 32)
            ):
                list(ex.map(lambda _i: self._submit(plane), range(n)))
        # serial pass: open-loop arrivals make batch sizes 1-2, whose
        # pad buckets differ from the concurrent burst's — compile them
        # here, not inside the first measured window
        for plane in ("validation", "agent", "mutation"):
            for _ in range(4 * max(1, len(self.replicas))):
                self._submit(plane)
        return time.monotonic() - t0

    def run(self) -> Dict[str, Any]:
        scn = self.scenario
        self.build()
        warm_s = self.warmup()
        # live SLO windows restart here: warmup traffic (all-good,
        # closed-loop) would otherwise inflate live attainment over
        # what the offline reporter bins from the measured run — the
        # cost EWMA warmup primed is kept
        for rep in self.replicas:
            if rep.slo is not None:
                rep.slo.reset_windows()
        self._log(f"warmup {warm_s:.1f}s; starting open loop "
                  f"@{scn.rps}rps for {scn.duration_s}s")
        self._t0 = time.monotonic()
        threads = [
            threading.Thread(
                target=self._event_loop, name="gk-soak-events",
                daemon=True,
            ),
            threading.Thread(
                target=self._sampler_loop, name="gk-soak-sampler",
                daemon=True,
            ),
        ]
        for th in threads:
            th.start()
        try:
            load = run_open_loop(
                self._submit,
                rps=scn.rps,
                duration_s=scn.duration_s,
                deadline_s=scn.deadline_s,
                planes=scn.planes,
                seed=scn.seed,
            )
        finally:
            self._stop.set()
            for th in threads:
                th.join(timeout=5)
            FAULTS.reset()
        split = self._device_time_split()
        capacity = None
        if scn.capacity:
            capacity = run_capacity_model(
                scn.capacity, scn.deadline_s, err=self.err
            )
        # per-replica flight-recorder summaries: the postmortems the
        # run tripped (breaker opens, quarantines, SLO breaches, shed
        # bursts), replica-tagged so multi-replica timelines stitch
        flight = []
        for rep in self.replicas:
            if rep.recorder is None:
                continue
            rep.recorder.flush(timeout=1.0)
            flight.append({
                "replica": rep.name,
                **rep.recorder.snapshot(),
                "triggers": [
                    r["trigger"] for r in rep.recorder.records()
                ],
            })
        report = build_report(
            scn.to_dict(),
            load,
            self._window_samples,
            self.transitions,
            split,
            capacity=capacity,
            faults_log=self.faults_log,
            live_slo=self._live_slo_summary(),
            extra={
                "events_log": self.events_log,
                "warmup_seconds": round(warm_s, 1),
                "provider_fetches_total": self.stub.fetches,
                "flight_records": flight,
                "sched": self._sched_summary(),
            },
        )
        return report

    def _sched_summary(self) -> Dict[str, Any]:
        """End-of-run admission-scheduler rollup: per-replica plane
        snapshots (the same document /debug/sched serves) plus the
        decision-log per-tenant attainment split the acceptance checks
        read."""
        out: Dict[str, Any] = {
            "policy": self.scenario.sched_policy,
            "replicas": [],
            "tenant_stats": {},
        }
        for rep in self.replicas:
            if rep.server is not None and hasattr(
                rep.server, "sched_snapshot"
            ):
                out["replicas"].append({
                    "replica": rep.name,
                    "planes": rep.server.sched_snapshot(),
                })
            if rep.decisions is not None:
                for key, row in rep.decisions.tenant_stats().items():
                    agg = out["tenant_stats"].setdefault(
                        key, {"count": 0, "ok": 0, "miss": 0, "shed": 0}
                    )
                    for f in ("count", "ok", "miss", "shed"):
                        agg[f] += row[f]
        for row in out["tenant_stats"].values():
            row["attainment"] = (
                round(row["ok"] / row["count"], 4)
                if row["count"] else None
            )
        return out

    def _live_slo_summary(self) -> Optional[Dict[str, Any]]:
        """End-of-run rollup of the per-replica streaming SLO engines:
        slow-window attainment (request-weighted across replicas) is
        what the live_vs_offline check compares against the offline
        reporter; saturation/headroom are the autoscaler signals the
        capacity model cross-checks."""
        cum = self._cumulative()
        if not any(rep.slo is not None for rep in self.replicas):
            return None
        headroom = None
        arrival = 0.0
        cost = None
        for rep in self.replicas:
            if rep.slo is None:
                continue
            util = rep.slo.snapshot()["utilization"]
            arrival += util["arrival_rps"] or 0.0
            h = util["estimated_headroom_rps"]
            if h is not None:
                headroom = h if headroom is None else headroom + h
            c = util["device_seconds_per_row_ewma"]
            if c is not None:
                cost = c if cost is None else max(cost, c)
        return {
            "attainment_fast": cum["slo_live_attainment_fast"],
            "attainment_slow": cum["slo_live_attainment_slow"],
            "requests_slow": cum["slo_live_requests_slow"],
            "saturation": cum["slo_saturation"],
            "burning": cum["slo_burning"],
            "breaches": cum["slo_breaches_cum"],
            "arrival_rps": round(arrival, 2),
            "estimated_headroom_rps": headroom,
            "device_seconds_per_row_ewma": cost,
        }

    def stop(self) -> None:
        self._stop.set()
        FAULTS.reset()
        if self._saved_min_batch is not None:
            from ..constraint import tpudriver as _td

            _td.MIN_DEVICE_BATCH = self._saved_min_batch
            self._saved_min_batch = None
        for rep in self.replicas:
            # retire the framed client pool BEFORE the server stops:
            # closing a StreamClient shuts the socket down (FIN), so
            # the listener's drain isn't left waiting on harness conns
            with rep.streams_lock:
                streams, rep.streams = rep.streams, []
            for c in streams:
                if c is not None:
                    try:
                        c.close()
                    except Exception:
                        pass
            try:
                if rep.server is not None:
                    rep.server.stop()
            except Exception:
                pass
            if rep.fleet_plane is not None:
                rep.fleet_plane.stop()
            if rep.rotator is not None:
                rep.rotator.stop()
            if rep.integrity is not None:
                rep.integrity.close()
            if rep.recorder is not None:
                rep.recorder.stop()
        self.stub.stop()


def run_capacity_model(
    cfg: Dict[str, Any], deadline_s: float, err=None
) -> List[Dict[str, Any]]:
    """Max sustainable rps at the p99 SLO vs constraint count: for each
    count, step the open-loop rate up the configured levels until a
    probe window's attainment drops below 99% — the last passing level
    is the capacity. Handler-level (no HTTP client noise): this models
    ENGINE capacity; the sustained-run numbers include transport."""
    import sys

    from ..constraint import Backend, K8sValidationTarget, TpuDriver
    from ..webhook.server import BatchedValidationHandler, MicroBatcher

    err = err if err is not None else sys.stderr
    counts = list(cfg.get("constraint_counts", [10, 100]))
    levels = list(cfg.get("rps_levels", [25, 50, 100, 200]))
    probe_s = float(cfg.get("probe_s", 3.0))
    out: List[Dict[str, Any]] = []
    for n_con in counts:
        client = Backend(TpuDriver()).new_client(K8sValidationTarget())
        client.add_template(
            _template("SoakPrivileged", K8S_TARGET, _PRIV_REGO)
        )
        for i in range(n_con):
            client.add_constraint(
                _constraint("SoakPrivileged", f"c{i}", match=_POD_MATCH)
            )
        batcher = MicroBatcher(client, K8S_TARGET, window_ms=2.0)
        handler = BatchedValidationHandler(batcher, request_timeout=30)
        batcher.start()
        counter = itertools.count()

        def submit(_plane: str):
            i = next(counter)
            resp = handler.handle(_pod_request(i, violating=(i % 10 == 0)))
            return 200, ("ok" if resp.allowed else "denied")

        row: Dict[str, Any] = {"constraints": n_con, "levels": []}
        max_ok = None
        try:
            # warm the route + jit buckets outside the measurement
            from ..constraint import AugmentedReview

            client.warm_review_path([
                AugmentedReview(_pod_request(i, False))
                for i in range(16)
            ])
            run_open_loop(
                submit, rps=min(levels), duration_s=1.0,
                deadline_s=deadline_s,
            )
            for rps in levels:
                load = run_open_loop(
                    submit, rps=rps, duration_s=probe_s,
                    deadline_s=deadline_s,
                )
                lats = sorted(s.latency_s for s in load.samples)
                p99 = lats[int(0.99 * (len(lats) - 1))] if lats else 0.0
                att = load.slo_attainment()
                row["levels"].append({
                    "rps": rps,
                    "achieved_rps": load.achieved_rps,
                    "p99_ms": round(p99 * 1e3, 2),
                    "attainment": round(att, 4),
                })
                print(
                    f"soak capacity: c={n_con} rps={rps} "
                    f"p99={p99 * 1e3:.1f}ms att={att:.3f}",
                    file=err, flush=True,
                )
                if att >= 0.99 and p99 <= deadline_s:
                    max_ok = rps
                else:
                    break
        finally:
            batcher.stop()
        row["max_rps_at_slo"] = max_ok
        out.append(row)
    return out


def run_soak(scenario: Scenario, err=None) -> Dict[str, Any]:
    """Build, run, and tear down one soak scenario; returns the report
    (report.py's schema; `summarize_soak` renders the SUMMARY line)."""
    harness = SoakHarness(scenario, err=err)
    try:
        return harness.run()
    finally:
        harness.stop()
