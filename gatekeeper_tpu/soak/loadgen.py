"""Open-loop Poisson load generation.

The defining property — and the reason this exists next to the
closed-loop `replay()` in bench_webhook.py — is that the arrival
process NEVER waits for the system under test. A scheduler thread draws
exponential inter-arrival gaps at the target rate and hands each
arrival to a worker pool; if every worker is busy the arrival queues,
and its measured latency INCLUDES that wait, because latency is counted
from the scheduled arrival instant, not from when a worker got around
to it. A request that misses its deadline (or errors, or is still
queued when the drain window closes) is counted against the SLO —
overload shows up as failed attainment, never as a conveniently
slowed-down arrival rate (coordinated omission).

Determinism: arrivals and plane choices come from one seeded
`random.Random`, so a scenario replays the same schedule every run.
"""

from __future__ import annotations

import queue
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

# outcome statuses beyond an HTTP code: the generator's own verdicts
UNSERVED = "unserved"      # still queued when the drain window closed
CONN_ERROR = "conn_error"  # transport-level failure (refused/reset)
CLIENT_TIMEOUT = "client_timeout"


@dataclass
class Sample:
    t_rel: float        # scheduled arrival, seconds from load start
    plane: str
    latency_s: float    # scheduled arrival -> response (open-loop)
    status: int         # HTTP status; 0 for generator verdicts
    outcome: str        # "ok"/"denied"/CONN_ERROR/CLIENT_TIMEOUT/UNSERVED

    def ok_within(self, deadline_s: float) -> bool:
        return (
            self.outcome in ("ok", "denied")
            and self.status == 200
            and self.latency_s <= deadline_s
        )


@dataclass
class OpenLoopLoad:
    """The result of one open-loop run."""

    target_rps: float
    duration_s: float
    deadline_s: float
    generated: int = 0
    samples: List[Sample] = field(default_factory=list)

    @property
    def achieved_rps(self) -> float:
        if self.duration_s <= 0:
            return 0.0
        return round(self.generated / self.duration_s, 2)

    def slo_attainment(self) -> float:
        if not self.samples:
            return 0.0
        ok = sum(1 for s in self.samples if s.ok_within(self.deadline_s))
        return ok / len(self.samples)


def _weighted_choice(rng: random.Random, weights: Dict[str, float]) -> str:
    total = sum(weights.values())
    x = rng.random() * total
    for name, w in weights.items():
        x -= w
        if x <= 0:
            return name
    return next(iter(weights))


def run_open_loop(
    submit: Callable[[str], Tuple[int, str]],
    rps: float,
    duration_s: float,
    deadline_s: float,
    planes: Optional[Dict[str, float]] = None,
    seed: int = 0,
    max_workers: Optional[int] = None,
    drain_s: Optional[float] = None,
    stop_event: Optional[threading.Event] = None,
    clock: Callable[[], float] = time.monotonic,
) -> OpenLoopLoad:
    """Drive `submit(plane) -> (status, outcome)` with Poisson arrivals
    at `rps` for `duration_s`. Returns every sample; the caller bins
    them into windows. `submit` must be thread-safe and should enforce
    its own transport timeout (a hung submit occupies a worker, which
    is exactly the backlog an open loop is supposed to surface).

    Worker sizing: enough concurrency that a healthy system never
    queues at the generator (2 x rps x deadline, clamped) — anything
    beyond that IS system slowness and belongs in the latency numbers.
    """
    planes = planes or {"validation": 1.0}
    if max_workers is None:
        max_workers = max(8, min(256, int(rps * deadline_s * 2) + 4))
    if drain_s is None:
        drain_s = max(2.0, deadline_s * 2)
    rng = random.Random(seed)
    load = OpenLoopLoad(
        target_rps=rps, duration_s=duration_s, deadline_s=deadline_s
    )
    samples = load.samples
    samples_lock = threading.Lock()
    work: "queue.Queue" = queue.Queue()
    t0 = clock()
    t_end = t0 + duration_s
    stop_workers = threading.Event()

    def worker() -> None:
        while not stop_workers.is_set():
            try:
                item = work.get(timeout=0.1)
            except queue.Empty:
                continue
            if item is None:
                return
            t_sched, plane = item
            try:
                status, outcome = submit(plane)
            except Exception:
                status, outcome = 0, CONN_ERROR
            latency = clock() - t_sched
            with samples_lock:
                samples.append(
                    Sample(
                        t_rel=t_sched - t0,
                        plane=plane,
                        latency_s=latency,
                        status=status,
                        outcome=outcome,
                    )
                )

    threads = [
        threading.Thread(target=worker, name=f"gk-soak-w{i}", daemon=True)
        for i in range(max_workers)
    ]
    for th in threads:
        th.start()

    # the scheduler: cumulative arrival times so timing error never
    # drifts the rate; when we're behind schedule the backlog fires as
    # a burst (open loop: the system's slowness must not slow arrivals)
    next_t = t0
    while True:
        if stop_event is not None and stop_event.is_set():
            break
        next_t += rng.expovariate(rps)
        if next_t >= t_end:
            break
        delay = next_t - clock()
        if delay > 0:
            time.sleep(delay)
        work.put((next_t, _weighted_choice(rng, planes)))
        load.generated += 1

    # drain: give in-flight/queued work a bounded window to finish;
    # whatever is still queued afterwards is an UNSERVED SLO miss, not
    # a silently-dropped data point
    drain_deadline = clock() + drain_s
    while clock() < drain_deadline:
        with samples_lock:
            done = len(samples)
        if done >= load.generated:
            break
        time.sleep(0.02)
    stop_workers.set()
    for th in threads:
        th.join(timeout=1.0)
    leftovers: List[Tuple[float, str]] = []
    while True:
        try:
            item = work.get_nowait()
        except queue.Empty:
            break
        if item is not None:
            leftovers.append(item)
    now = clock()
    with samples_lock:
        for t_sched, plane in leftovers:
            samples.append(
                Sample(
                    t_rel=t_sched - t0,
                    plane=plane,
                    latency_s=now - t_sched,
                    status=0,
                    outcome=UNSERVED,
                )
            )
        # rebind to a sorted COPY: a worker stuck in a hung submit past
        # the join timeout appends (harmlessly) to the orphaned list,
        # never to the result the reporter is reading
        load.samples = sorted(samples, key=lambda s: s.t_rel)
    return load
