"""Soak plane: open-loop sustained-load harness (docs/operations.md).

Every other bench phase in this repo is a short CLOSED-loop burst: N
threads each wait for their previous response before sending the next
request, so an overloaded system quietly slows its own arrival rate and
the measured p99 flatters it (coordinated omission). The soak plane is
the opposite instrument — an OPEN-loop generator schedules Poisson
arrivals at a fixed target rps whether or not the system keeps up, and
a request that misses its deadline is COUNTED AGAINST THE SLO instead
of back-pressured away. Around that generator:

  * `scenario`   — the declarative timeline (at t=20s add constraints,
    at t=45s arm a fault, at t=90s rotate certs / kill a replica);
  * `loadgen`    — the Poisson arrival scheduler + worker pool;
  * `harness`    — builds the system under test (1..N real
    `WebhookServer` replicas over HTTP(S), mutation + agent planes,
    stub external-data provider, fleet gossip) and executes the
    timeline against it;
  * `report`     — per-window SLO attainment, shed rate, breaker
    transition log, device-time split, capacity model, and leak
    evidence (RSS / cache / trace-ring / metrics-series curves).

Entry points: `bench_webhook.py --soak` for the CLI, `run_soak()` from
code, and the `soak` pytest lane for the ~10 s smoke scenario.
"""

from .loadgen import OpenLoopLoad, run_open_loop  # noqa: F401
from .report import (  # noqa: F401
    SOAK_SCHEMA_FIELDS,
    build_report,
    check_soak_schema,
    monotonic_growth,
    parse_summary_line,
    summarize_soak,
)
from .scenario import (  # noqa: F401
    ACTIONS,
    Scenario,
    ScenarioEvent,
    default_scenario,
    high_rate_scenario,
    high_rate_smoke_scenario,
    load_scenario,
    multi_tenant_overload_scenario,
    multi_tenant_smoke_scenario,
    sdc_smoke_scenario,
    smoke_scenario,
)
from .harness import SoakHarness, run_soak  # noqa: F401
