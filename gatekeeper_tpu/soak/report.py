"""Soak reporting: windows, phases, leak evidence, acceptance checks.

The reporter turns two streams into one JSON artifact:

  * the generator's per-request samples (scheduled time, latency,
    status) binned into fixed windows with per-window p50/p99 and SLO
    attainment (fraction answered 200 within the deadline — misses,
    client timeouts, connection errors and unserved arrivals all count
    against it);
  * the harness sampler's per-window server-side observations (shed
    counts, breaker transitions, outbound fetches, cert generation,
    and the leak series: RSS, cache entries, trace-ring size, metrics
    series count, render-cache size).

Phases (scenario `phase` events) aggregate windows; the acceptance
checks read the conventional phase names — `fault` must degrade and
`recovery` must restore the SLO with breaker transitions logged,
`churn` must stay 5xx-free, `kill` must keep shed bounded — and the
leak checker flags any sampled series that grows monotonically across
the steady windows.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

from .loadgen import OpenLoopLoad, Sample

# top-level fields every soak JSON must carry (the CI schema gate)
SOAK_SCHEMA_FIELDS = (
    "scenario", "windows", "phases", "slo", "shed",
    "breaker_transitions", "leak", "device_time_split", "checks",
)

# fraction of kill-phase requests allowed to fail before "bounded shed"
# flips false (a graceful drain should shed ~zero; 2% leaves room for
# the LB-flip race on a loaded box)
KILL_SHED_BOUND = 0.02

# live-vs-offline attainment agreement tolerance: the streaming engine
# and the offline binner watch the same traffic through different
# clocks (server-side windows vs client-side schedule), so exact
# equality is not expected — divergence past this is a measurement bug
LIVE_OFFLINE_TOL = 0.05

# minimum live slow-window sample count before live-vs-offline
# agreement is judged (a near-empty window proves nothing)
LIVE_MIN_SAMPLES = 50

# wire-speed ingest plane (docs/ingest.md §Soak): a framed run's
# within-deadline goodput must hold at least this fraction of the
# OFFERED open-loop rate. The firehose scenario deliberately offers
# more than one host serves (that's what "wire-speed front door" has
# to survive), so the floor judges graceful saturation — sustained
# goodput, not collapse — rather than full attainment; the smoke runs
# clear it with room
INGEST_SUSTAIN_FRAC = 0.05

# ...and the zero-copy scanner's mean per-frame decode cost must stay
# a marginal slice of the deadline budget (decode must never become
# the bottleneck the transport removed)
DECODE_SPAN_FRAC = 0.05


def _slo_target(scenario_dict: Dict[str, Any]):
    """The run's SloTarget (obs/slo.py): the scenario's deadline
    contract + any `slo` overrides — the SAME object the live
    per-replica engines judged against, so the offline checks cannot
    drift from the live plane."""
    from ..obs.slo import SloTarget

    try:
        return SloTarget.from_dict(
            (scenario_dict or {}).get("slo"),
            deadline_s=(scenario_dict or {}).get("deadline_s"),
        )
    except (ValueError, TypeError):
        return SloTarget()


def _pct(sorted_vals: Sequence[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


def bin_windows(
    samples: List[Sample],
    duration_s: float,
    window_s: float,
    deadline_s: float,
    phase_at: Optional[Dict[float, str]] = None,
) -> List[Dict[str, Any]]:
    """Samples -> per-window rows. `phase_at` maps event times to phase
    labels; a window takes the label active at its start."""
    n_windows = max(1, int(round(duration_s / window_s)))
    rows: List[Dict[str, Any]] = []
    marks = sorted((phase_at or {}).items())

    def phase_for(t: float) -> str:
        label = ""
        for at, name in marks:
            if at <= t + 1e-9:
                label = name
        return label

    buckets: List[List[Sample]] = [[] for _ in range(n_windows)]
    for s in samples:
        idx = int(s.t_rel / window_s)
        if 0 <= idx < n_windows:
            buckets[idx].append(s)
        elif idx >= n_windows:
            buckets[-1].append(s)
    for i, bucket in enumerate(buckets):
        lats = sorted(s.latency_s for s in bucket)
        ok = sum(1 for s in bucket if s.ok_within(deadline_s))
        err5xx = sum(1 for s in bucket if s.status >= 500)
        conn = sum(
            1 for s in bucket
            if s.outcome in ("conn_error", "client_timeout", "unserved")
        )
        # server-side transport failures only: a refused/reset
        # connection is the server's fault; a client_timeout/unserved
        # arrival is the load generator (or a starved CI box) giving
        # up and must not be judged as a serving error
        conn_hard = sum(1 for s in bucket if s.outcome == "conn_error")
        rows.append({
            "t0_s": round(i * window_s, 3),
            "t1_s": round((i + 1) * window_s, 3),
            "phase": phase_for(i * window_s),
            "requests": len(bucket),
            "rps": round(len(bucket) / window_s, 2),
            "p50_ms": round(_pct(lats, 0.50) * 1e3, 2),
            "p99_ms": round(_pct(lats, 0.99) * 1e3, 2),
            "slo_attainment": round(ok / len(bucket), 4) if bucket else None,
            "slo_misses": len(bucket) - ok,
            "http_5xx": err5xx,
            "transport_errors": conn,
            "conn_errors": conn_hard,
            "client_unserved": conn - conn_hard,
        })
    return rows


def aggregate_phases(windows: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    order: List[str] = []
    by_phase: Dict[str, List[Dict[str, Any]]] = {}
    for w in windows:
        p = w.get("phase") or ""
        if p not in by_phase:
            by_phase[p] = []
            order.append(p)
        by_phase[p].append(w)
    out = []
    for p in order:
        ws = by_phase[p]
        total = sum(w["requests"] for w in ws)
        ok = sum(
            w["requests"] - w["slo_misses"] for w in ws
        )
        out.append({
            "phase": p,
            "windows": len(ws),
            "requests": total,
            "slo_attainment": round(ok / total, 4) if total else None,
            "worst_p99_ms": max((w["p99_ms"] for w in ws), default=0.0),
            "best_p99_ms": min(
                (w["p99_ms"] for w in ws if w["requests"]), default=0.0
            ),
            "http_5xx": sum(w["http_5xx"] for w in ws),
            "transport_errors": sum(w["transport_errors"] for w in ws),
            # old window docs lack the split: fall back to the lumped
            # count so the strict judgement is preserved for them
            "conn_errors": sum(
                w.get("conn_errors", w.get("transport_errors", 0))
                for w in ws
            ),
            "client_unserved": sum(
                w.get("client_unserved", 0) or 0 for w in ws
            ),
            # verdict-integrity plane (docs/robustness.md §Verdict
            # integrity): canary/shadow evidence for the sdc check
            "canary_mismatches": sum(
                w.get("canary_mismatches", 0) or 0 for w in ws
            ),
            "shadow_divergences": sum(
                w.get("shadow_divergences", 0) or 0 for w in ws
            ),
            "shed": sum(w.get("shed", 0) for w in ws),
            "breaker_transitions": sum(
                w.get("breaker_transitions", 0) for w in ws
            ),
            "fetches": sum(w.get("fetches", 0) for w in ws),
            # compile plane (docs/compile.md): degraded dispatches in
            # the phase (ingest_zero_degraded's evidence) + the swap/
            # carry-forward/compile activity the churn drove
            "degraded_dispatches": sum(
                w.get("degraded_dispatches", 0) or 0 for w in ws
            ),
            "program_swaps": sum(
                w.get("program_swaps", 0) or 0 for w in ws
            ),
            "program_carryforwards": sum(
                w.get("program_carryforwards", 0) or 0 for w in ws
            ),
            "program_compiles": sum(
                w.get("program_compiles", 0) or 0 for w in ws
            ),
            # corpus static analysis: debounced background recomputes
            # completed in the phase (ingest_corpus_recompute evidence)
            "corpus_recomputes": sum(
                w.get("corpus_recomputes", 0) or 0 for w in ws
            ),
            # admission scheduler (gatekeeper_tpu/sched): the typed
            # shed split for the phase — predictive sheds are the ones
            # that provably could not make their deadline
            "sched_predicted_miss": sum(
                w.get("sched_predicted_miss", 0) or 0 for w in ws
            ),
            "sched_tenant_capped": sum(
                w.get("sched_tenant_capped", 0) or 0 for w in ws
            ),
            "sched_queue_full": sum(
                w.get("sched_queue_full", 0) or 0 for w in ws
            ),
            "tenant_classes": _phase_tenant_classes(ws),
        })
    return out


def _phase_tenant_classes(
    ws: List[Dict[str, Any]],
) -> Optional[Dict[str, Any]]:
    """Aggregate the sampler's per-window tenant-class deltas over a
    phase; attainment is server-side (decision-log judged), which is
    what the multi-tenant checks read."""
    rows = [w.get("tenant_classes") for w in ws]
    rows = [r for r in rows if r]
    if not rows:
        return None
    out: Dict[str, Any] = {}
    for cls in ("quiet", "noisy"):
        req = sum(r[cls]["requests"] for r in rows if cls in r)
        ok = sum(r[cls]["ok"] for r in rows if cls in r)
        shed = sum(r[cls]["shed"] for r in rows if cls in r)
        out[cls] = {
            "requests": req,
            "ok": ok,
            "shed": shed,
            "attainment": round(ok / req, 4) if req else None,
        }
    return out


def monotonic_growth(
    values: Sequence[float],
    tol_frac: float = 0.10,
    min_windows: int = 6,
) -> bool:
    """True when a sampled series looks like a leak: enough windows,
    (almost) never decreasing, and net growth beyond tolerance. A
    series that plateaus — cache fills to its bound, RSS settles after
    warmup — must NOT flag, which is why the nondecreasing-step ratio
    matters and not just first-vs-last."""
    vals = [float(v) for v in values if v is not None]
    if len(vals) < min_windows:
        return False
    first = vals[0]
    last = vals[-1]
    if last <= first * (1 + tol_frac) + 1e-9:
        return False
    steps = list(zip(vals, vals[1:]))
    increases = sum(1 for a, b in steps if b > a + 1e-9)
    decreases = sum(1 for a, b in steps if b < a - 1e-9)
    # a leak grows in most windows and essentially never shrinks; the
    # "essentially" absorbs one GC/eviction blip
    return increases >= len(steps) * 0.5 and decreases <= 1


def leak_report(
    window_stats: List[Dict[str, Any]],
    steady_phases: Sequence[str] = ("steady",),
    tolerances: Optional[Dict[str, float]] = None,
) -> Dict[str, Any]:
    """Per-series leak verdicts over the STEADY windows (churn/fault
    windows legitimately grow caches; judging them would cry wolf).
    Falls back to all windows when no steady phase was labeled."""
    tol = {
        "rss_kb": 0.25,  # allocator slack + lazy JAX buffers
        "cache_entries": 0.10,
        "trace_ring": 0.10,
        "metrics_series": 0.10,
        "render_cache": 0.10,
        "decision_ring": 0.10,  # bounded by construction; proven here
    }
    tol.update(tolerances or {})
    steady = [
        w for w in window_stats if (w.get("phase") or "") in steady_phases
    ]
    # a leak verdict needs enough STEADY evidence: churn/fault windows
    # legitimately grow every cache, so judging them would cry wolf.
    # With too few steady windows the curves are still reported, but
    # nothing flags — insufficient evidence is not evidence of a leak.
    sufficient = len(steady) >= 4
    judged = steady if sufficient else window_stats
    series: Dict[str, Any] = {}
    flagged = []
    for name, t in tol.items():
        vals = [w.get(name) for w in judged if w.get(name) is not None]
        growing = sufficient and monotonic_growth(vals, tol_frac=t)
        series[name] = {
            "samples": vals,
            "tolerance_frac": t,
            "monotonic_growth": growing,
        }
        if growing:
            flagged.append(name)
    return {
        "steady_windows": len(steady),
        "sufficient_steady_windows": sufficient,
        "series": series,
        "flagged": flagged,
        "flat": not flagged,
    }


def build_checks(
    phases: List[Dict[str, Any]],
    leak: Dict[str, Any],
    transitions: List[Dict[str, Any]],
    windows: List[Dict[str, Any]],
    target=None,
    scenario: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    # degrade/recover thresholds come from the shared SloTarget
    # (scenario-overridable), not hardcoded here — the live engine and
    # this reporter judge the same objective
    if target is None:
        target = _slo_target({})
    by_name = {p["phase"]: p for p in phases}
    checks: Dict[str, Any] = {}
    fault = by_name.get("fault")
    recovery = by_name.get("recovery")
    if fault and recovery:
        degraded = (
            (fault["slo_attainment"] or 0.0) < target.degraded_below
        )
        recovered = (
            (recovery["slo_attainment"] or 0.0) >= target.recovered_at
        )
        trans_in_fault = fault.get("breaker_transitions", 0) > 0 or any(
            t for t in transitions
        )
        checks["fault_window_degrades_and_recovers"] = bool(
            degraded and recovered and trans_in_fault
        )
    churn = by_name.get("churn")
    if churn:
        # judged on SERVER-side failure only: 5xx and refused/reset
        # connections. Lumping in client_timeout/unserved (the load
        # generator or a starved CI box giving up) made this check
        # flake on loaded runners for errors the server never made —
        # those now ride the separate client_unserved column.
        checks["churn_zero_5xx"] = (
            churn["http_5xx"] == 0
            and churn.get("conn_errors", churn["transport_errors"]) == 0
        )
    ingest = by_name.get("ingest")
    if ingest:
        # the zero-downtime warm-swap contract (docs/compile.md): a
        # template ingest wave serves every request — fused or host
        # rung — with zero degraded dispatches and zero 5xx while the
        # new sub-programs compile on the shadow slot and swap live
        checks["ingest_zero_degraded"] = (
            ingest.get("degraded_dispatches", 0) == 0
            and ingest["http_5xx"] == 0
        )
        # corpus static analysis (docs/analysis.md §Corpus analysis):
        # the wave's churn must trigger a corpus recompute — in the
        # background and DEBOUNCED (a handful of recomputes for a
        # hundreds-of-templates wave, never one per add) — while the
        # request path stays untouched (the latency/5xx side of that
        # claim is pinned by ingest_zero_degraded above)
        n_rec = ingest.get("corpus_recomputes", 0) or 0
        checks["ingest_corpus_recompute"] = (
            0 < n_rec <= 2 * ingest["windows"] + 2
        )
    # verdict-integrity plane (docs/robustness.md §Verdict integrity):
    # the sdc phase's injected bit-flip must be DETECTED (canary
    # mismatches recorded), the device must land in corruption
    # quarantine (a window closed with quarantined_devices > 0), and
    # by the end of the run the golden self-test must have healed it
    # (the final window shows an empty quarantine set)
    sdc = by_name.get("sdc")
    if sdc is not None:
        detected = (sdc.get("canary_mismatches", 0) or 0) > 0
        tripped = any(
            (w.get("quarantined_devices", 0) or 0) > 0 for w in windows
        )
        healed = bool(windows) and (
            (windows[-1].get("quarantined_devices", 0) or 0) == 0
        )
        checks["sdc_detected_and_quarantined"] = {
            "canary_mismatches": sdc.get("canary_mismatches", 0) or 0,
            "shadow_divergences": sdc.get("shadow_divergences", 0) or 0,
            "quarantined": tripped,
            "healed": healed,
            "holds": bool(detected and tripped and healed),
        }
    kill = by_name.get("kill")
    if kill and kill["requests"]:
        failed = (
            kill["http_5xx"] + kill["transport_errors"] + kill["shed"]
        )
        checks["replica_kill_shed_bounded"] = (
            failed / kill["requests"] <= KILL_SHED_BOUND
        )
    # multi-tenant overload (docs/operations.md §Admission scheduling):
    # judged over the `overload` phase's decision-log tenant split.
    # With the deadline policy the quiet tenant must hold the SLO
    # objective while the noisy one absorbs the shed (fair-share caps
    # + predictive shedding); the SAME scenario under fifo is the
    # baseline where both classes degrade together — the contrast the
    # acceptance criteria demand.
    overload = by_name.get("overload")
    tcls = (overload or {}).get("tenant_classes")
    if tcls and (tcls["quiet"]["requests"] or 0) >= 20:
        policy = str((scenario or {}).get("sched_policy") or "fifo")
        quiet_att = tcls["quiet"]["attainment"] or 0.0
        noisy_att = tcls["noisy"]["attainment"] or 0.0
        if policy == "deadline":
            checks["quiet_tenant_attainment_holds"] = {
                "quiet_attainment": quiet_att,
                "noisy_attainment": noisy_att,
                "noisy_shed": tcls["noisy"]["shed"],
                "objective": target.objective,
                "holds": bool(
                    quiet_att >= target.objective
                    and tcls["noisy"]["shed"] > 0
                ),
            }
        else:
            checks["fifo_baseline_degrades"] = {
                "quiet_attainment": quiet_att,
                "noisy_attainment": noisy_att,
                "objective": target.objective,
                "degrades": bool(quiet_att < target.objective),
            }
    # wire-speed ingest plane (docs/ingest.md §Soak): framed runs are
    # judged on sustained goodput and decode cost over the WHOLE run
    # (every window rides the stream transport, so no phase gate)
    if (scenario or {}).get("transport") == "framed":
        duration = float((scenario or {}).get("duration_s") or 0.0)
        offered = float((scenario or {}).get("rps") or 0.0)
        deadline_ms = (
            float((scenario or {}).get("deadline_s") or 0.0) * 1000.0
        )
        ok_total = sum(
            (w["requests"] - w["slo_misses"]) for w in windows
        )
        frames = sum(w.get("ingest_frames", 0) or 0 for w in windows)
        goodput = round(ok_total / duration, 2) if duration else 0.0
        floor = round(INGEST_SUSTAIN_FRAC * offered, 2)
        checks["ingest_rps_sustained"] = {
            "offered_rps": offered,
            "rps_sustained": goodput,
            "floor_rps": floor,
            "frames": frames,
            "holds": bool(frames > 0 and goodput >= floor),
        }
        # frame-weighted mean of the sampler's per-window decode cost
        dec_pairs = [
            (w["ingest_decode_ms_mean"], w.get("ingest_frames", 0) or 0)
            for w in windows
            if w.get("ingest_decode_ms_mean") is not None
        ]
        wsum = sum(n for _, n in dec_pairs)
        mean_ms = (
            round(sum(m * n for m, n in dec_pairs) / wsum, 4)
            if wsum else None
        )
        bound_ms = round(DECODE_SPAN_FRAC * deadline_ms, 2)
        checks["decode_span_bounded"] = {
            "decode_ms_mean": mean_ms,
            "bound_ms": bound_ms,
            "deadline_ms": deadline_ms,
            "holds": bool(mean_ms is not None and mean_ms <= bound_ms),
        }
    checks["leak_flat"] = bool(leak.get("flat"))
    steady_windows = [
        w for w in windows if (w.get("phase") or "") == "steady"
    ]
    checks["steady_seconds"] = round(
        sum(w["t1_s"] - w["t0_s"] for w in steady_windows), 1
    )
    return checks


def build_report(
    scenario_dict: Dict[str, Any],
    load: OpenLoopLoad,
    window_stats: List[Dict[str, Any]],
    transitions: List[Dict[str, Any]],
    device_time_split: Dict[str, float],
    capacity: Optional[List[Dict[str, Any]]] = None,
    faults_log: Optional[List[Dict[str, Any]]] = None,
    live_slo: Optional[Dict[str, Any]] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Merge generator samples + sampler rows into the soak artifact.
    `window_stats` rows carry server-side per-window observations and
    are matched to sample windows by index. `live_slo` is the harness's
    end-of-run rollup of the streaming engines (obs/slo.py) — when
    present it rides in `slo.live` and is cross-checked against the
    offline numbers (`live_vs_offline_attainment`,
    `capacity_live_within_model`)."""
    phase_at = {
        float(e["at"]): e.get("name", "")
        for e in scenario_dict.get("events", [])
        if e.get("action") == "phase"
    }
    windows = bin_windows(
        load.samples,
        scenario_dict["duration_s"],
        scenario_dict["window_s"],
        scenario_dict["deadline_s"],
        phase_at=phase_at,
    )
    for i, w in enumerate(windows):
        if i < len(window_stats):
            w.update(window_stats[i])
    phases = aggregate_phases(windows)
    leak = leak_report(windows)
    target = _slo_target(scenario_dict)
    checks = build_checks(
        phases, leak, transitions, windows, target=target,
        scenario=scenario_dict,
    )
    total = len(load.samples)
    ok = sum(
        1 for s in load.samples
        if s.ok_within(scenario_dict["deadline_s"])
    )
    shed_total = sum(w.get("shed", 0) for w in windows)
    report = {
        "scenario": scenario_dict,
        "open_loop": {
            "target_rps": load.target_rps,
            "achieved_rps": load.achieved_rps,
            "generated": load.generated,
            "observed": total,
        },
        "slo": {
            "deadline_s": scenario_dict["deadline_s"],
            "target": target.to_dict(),
            "attainment": round(ok / total, 4) if total else None,
            "misses": total - ok,
            "worst_window_p99_ms": max(
                (w["p99_ms"] for w in windows if w["requests"]),
                default=0.0,
            ),
        },
        "shed": {
            "total": shed_total,
            "rate": round(shed_total / total, 4) if total else 0.0,
        },
        "windows": windows,
        "phases": phases,
        "breaker_transitions": transitions,
        "faults": faults_log or [],
        "device_time_split": device_time_split,
        "leak": leak,
        "checks": checks,
    }
    if capacity is not None:
        report["capacity_model"] = capacity
    if live_slo is not None:
        report["slo"]["live"] = live_slo
        # live-vs-offline agreement: the streaming engine's
        # slow-window attainment must match what the offline binner
        # computed from the generator's samples, within tolerance
        live_att = live_slo.get("attainment_slow")
        off_att = report["slo"]["attainment"]
        if (
            live_att is not None
            and off_att is not None
            and (live_slo.get("requests_slow") or 0) >= LIVE_MIN_SAMPLES
        ):
            checks["live_vs_offline_attainment"] = {
                "live": round(live_att, 4),
                "offline": off_att,
                "tolerance": LIVE_OFFLINE_TOL,
                "agree": abs(live_att - off_att) <= LIVE_OFFLINE_TOL,
            }
        # headroom sanity vs the offline capacity model: the live
        # estimate (1 / cost EWMA) is engine-side and the model probes
        # through the full handler stack, so this is an order-of-
        # magnitude cross-check, not an equality
        cost = live_slo.get("device_seconds_per_row_ewma")
        if capacity and cost:
            model_max = max(
                (
                    row.get("max_rps_at_slo") or 0
                    for row in capacity
                ),
                default=0,
            )
            if model_max > 0:
                live_cap = 1.0 / cost
                ratio = live_cap / model_max
                checks["capacity_live_within_model"] = {
                    "live_capacity_rps": round(live_cap, 1),
                    "model_max_rps": model_max,
                    "within": 0.1 <= ratio <= 100.0,
                }
    if extra:
        report.update(extra)
    return report


def check_soak_schema(doc: Dict[str, Any]) -> List[str]:
    """Missing-field list (empty = valid). The CI gate runs this over
    both a live smoke run and the checked-in SOAK_r01.json so the
    artifact format cannot silently drift from the reader."""
    problems = []
    for f in SOAK_SCHEMA_FIELDS:
        if f not in doc:
            problems.append(f"missing field: {f}")
    slo = doc.get("slo") or {}
    for f in ("deadline_s", "attainment", "misses", "worst_window_p99_ms"):
        if f not in slo:
            problems.append(f"missing slo.{f}")
    shed = doc.get("shed") or {}
    for f in ("total", "rate"):
        if f not in shed:
            problems.append(f"missing shed.{f}")
    leak = doc.get("leak") or {}
    for f in ("series", "flagged", "flat"):
        if f not in leak:
            problems.append(f"missing leak.{f}")
    for w in doc.get("windows") or []:
        for f in ("t0_s", "phase", "requests", "p99_ms", "slo_attainment"):
            if f not in w:
                problems.append(f"window missing {f}")
                break
        break  # shape-check the first row; rows are built by one loop
    return problems


def summarize_soak(res: Dict[str, Any]) -> str:
    """The compact driver-parseable line (the bench SUMMARY contract,
    gatekeeper_tpu/summary.py): headline SLO/shed/leak numbers that
    survive a truncated capture."""
    from ..summary import format_summary

    head: Dict[str, Any] = {}
    try:
        scn = res.get("scenario") or {}
        head["scenario"] = scn.get("name")
        head["duration_s"] = scn.get("duration_s")
        ol = res.get("open_loop") or {}
        head["target_rps"] = ol.get("target_rps")
        head["achieved_rps"] = ol.get("achieved_rps")
        slo = res.get("slo") or {}
        head["slo_attainment"] = slo.get("attainment")
        head["worst_window_p99_ms"] = slo.get("worst_window_p99_ms")
        head["shed_rate"] = (res.get("shed") or {}).get("rate")
        head["breaker_transitions"] = len(
            res.get("breaker_transitions") or []
        )
        # trip-triggered postmortems captured across replicas (full
        # per-replica detail in the artifact's flight_records section)
        head["flight_records"] = sum(
            int(fr.get("captured") or 0)
            for fr in (res.get("flight_records") or [])
        )
        head["leak_flagged"] = (res.get("leak") or {}).get("flagged")
        # admission scheduler headline (optional: only runs with the
        # sched plane wired carry it — older artifacts stay valid)
        sched = res.get("sched") or {}
        if sched:
            head["sched_policy"] = sched.get("policy")
            head["predicted_miss_shed"] = sum(
                p.get("sched_predicted_miss", 0) or 0
                for p in (res.get("phases") or [])
            )
        # live SLO headline (optional: only runs with streaming
        # engines attached carry it — older artifacts stay valid)
        live = (res.get("slo") or {}).get("live") or {}
        if live:
            head["saturation"] = live.get("saturation")
            head["live_attainment"] = live.get("attainment_slow")
            head["slo_breaches"] = live.get("breaches")
        head["checks"] = res.get("checks")
    except Exception as e:  # the summary must never kill the artifact
        head["error"] = str(e)
    return format_summary("soak", head)


def parse_summary_line(line: str) -> Dict[str, Any]:
    """Round-trip reader for the soak SUMMARY line — now the soak
    instance of the shared per-mode schema contract
    (gatekeeper_tpu/summary.py enforces EVERY bench lane the same
    way). Raises on anything that is not a valid soak summary."""
    from ..summary import parse_summary_line as _parse

    return _parse(line, mode="soak")
