"""Compile-time AST rewriting: dynamic-operand hoisting for negation.

OPA's compiler (rewriteDynamics + RewriteExprTerms stages, see
/root/reference/vendor/github.com/open-policy-agent/opa/ast/compile.go:2817)
binds refs/calls/comprehensions appearing as call operands to fresh local
variables *before* the calling expression. This is semantically observable
under negation: in

    not accept_value(rule, provided_value, params.ranges)

the operand `params.ranges` is hoisted to `__l = params.ranges` outside the
`not`, so if it is undefined the whole rule body fails instead of the `not`
succeeding. Plain negated refs (`not input.x.y`) and eq-unification sides
keep their refs inline (rewriteDynamicsEqExpr only rewrites nested bracket
operands), so `not x.y` keeps its succeed-on-undefined behavior.

The reference's policy library relies on both behaviors (e.g.
/root/reference/library/pod-security-policy/users/src.rego vs
allow-privilege-escalation), so this pass rewrites every rule and
comprehension body at module-load time to hoist dynamics out of negated
expressions only — hoisting non-negated operands would be semantics-neutral.
"""

from __future__ import annotations

from typing import List, Tuple

from . import ast as A


class _Gen:
    def __init__(self):
        self.n = 0

    def fresh(self) -> str:
        self.n += 1
        return f"$hoist{self.n}"


def rewrite_module(mod: A.Module) -> None:
    gen = _Gen()
    for rule in mod.rules:
        rule.body = _rewrite_body(rule.body, gen)
        _rewrite_terms_in_head(rule.head, gen)


def _rewrite_terms_in_head(head: A.RuleHead, gen: _Gen) -> None:
    for t in [head.key, head.value] + list(head.args or []):
        if t is not None:
            _rewrite_nested_bodies(t, gen)


def _rewrite_body(body: A.Body, gen: _Gen) -> A.Body:
    out: List[A.Expr] = []
    for expr in body:
        out.extend(_rewrite_expr(expr, gen))
    return out


def _rewrite_expr(expr: A.Expr, gen: _Gen) -> List[A.Expr]:
    if isinstance(expr, A.NotExpr):
        hoists, inner = _hoist_expr(expr.expr, gen)
        # recursively rewrite any comprehension bodies inside
        for h in hoists:
            _rewrite_nested_bodies_expr(h, gen)
        _rewrite_nested_bodies_expr(inner, gen)
        return hoists + [A.NotExpr(expr=inner, line=expr.line)]
    if isinstance(expr, A.WithExpr):
        rewritten = _rewrite_expr(expr.expr, gen)
        return [
            A.WithExpr(expr=e, mods=expr.mods, line=expr.line) for e in rewritten
        ]
    _rewrite_nested_bodies_expr(expr, gen)
    return [expr]


def _rewrite_nested_bodies_expr(expr: A.Expr, gen: _Gen) -> None:
    if isinstance(expr, A.TermExpr):
        _rewrite_nested_bodies(expr.term, gen)
    elif isinstance(expr, A.Assign):
        _rewrite_nested_bodies(expr.target, gen)
        _rewrite_nested_bodies(expr.value, gen)
    elif isinstance(expr, A.Unify):
        _rewrite_nested_bodies(expr.lhs, gen)
        _rewrite_nested_bodies(expr.rhs, gen)
    elif isinstance(expr, A.NotExpr):
        _rewrite_nested_bodies_expr(expr.expr, gen)
    elif isinstance(expr, A.WithExpr):
        _rewrite_nested_bodies_expr(expr.expr, gen)


def _rewrite_nested_bodies(term: A.Term, gen: _Gen) -> None:
    """Apply negation-hoisting inside comprehension bodies nested in terms."""
    if isinstance(term, A.Comprehension):
        term.body = _rewrite_body(term.body, gen)
        _rewrite_nested_bodies(term.head, gen)
        if term.key is not None:
            _rewrite_nested_bodies(term.key, gen)
    elif isinstance(term, A.Ref):
        _rewrite_nested_bodies(term.head, gen)
        for op in term.ops:
            _rewrite_nested_bodies(op, gen)
    elif isinstance(term, A.Call):
        for a in term.args:
            _rewrite_nested_bodies(a, gen)
    elif isinstance(term, A.BinOp):
        _rewrite_nested_bodies(term.lhs, gen)
        _rewrite_nested_bodies(term.rhs, gen)
    elif isinstance(term, A.UnaryMinus):
        _rewrite_nested_bodies(term.operand, gen)
    elif isinstance(term, (A.ArrayTerm, A.SetTerm)):
        for x in term.items:
            _rewrite_nested_bodies(x, gen)
    elif isinstance(term, A.ObjectTerm):
        for k, v in term.items:
            _rewrite_nested_bodies(k, gen)
            _rewrite_nested_bodies(v, gen)


# -- hoisting inside a negated expression -----------------------------------


def _hoist_expr(expr: A.Expr, gen: _Gen) -> Tuple[List[A.Expr], A.Expr]:
    hoists: List[A.Expr] = []
    if isinstance(expr, A.TermExpr):
        t = expr.term
        if isinstance(t, A.Ref):
            # keep the ref itself inline; hoist dynamic bracket operands
            new_ops = [_hoist_operand(op, gen, hoists) for op in t.ops]
            new_t = A.Ref(head=t.head, ops=new_ops, line=t.line)
            return hoists, A.TermExpr(term=new_t, line=expr.line)
        if isinstance(t, (A.Call, A.BinOp)):
            return hoists, A.TermExpr(
                term=_hoist_call_like(t, gen, hoists), line=expr.line
            )
        return hoists, expr
    if isinstance(expr, A.Unify):
        # eq semantics: refs on either side stay inline; only their bracket
        # operands are hoisted
        lhs = _hoist_eq_side(expr.lhs, gen, hoists)
        rhs = _hoist_eq_side(expr.rhs, gen, hoists)
        return hoists, A.Unify(lhs=lhs, rhs=rhs, line=expr.line)
    if isinstance(expr, A.Assign):
        value = _hoist_eq_side(expr.value, gen, hoists)
        return hoists, A.Assign(target=expr.target, value=value, line=expr.line)
    if isinstance(expr, A.NotExpr):
        # double negation: rewrite inner independently
        inner_h, inner = _hoist_expr(expr.expr, gen)
        return hoists, A.NotExpr(
            expr=inner if not inner_h else expr.expr, line=expr.line
        )
    return hoists, expr


def _hoist_eq_side(t: A.Term, gen: _Gen, hoists: List[A.Expr]) -> A.Term:
    if isinstance(t, A.Ref):
        new_ops = [_hoist_operand(op, gen, hoists) for op in t.ops]
        return A.Ref(head=t.head, ops=new_ops, line=t.line)
    if isinstance(t, (A.Call, A.BinOp)):
        return _hoist_call_like(t, gen, hoists)
    return t


def _hoist_call_like(t: A.Term, gen: _Gen, hoists: List[A.Expr]) -> A.Term:
    if isinstance(t, A.Call):
        new_args = [_hoist_operand(a, gen, hoists) for a in t.args]
        return A.Call(name=t.name, args=new_args, line=t.line)
    assert isinstance(t, A.BinOp)
    if t.op == "==":
        # OPA rewrites `==` to `=` (RewriteEquals) before dynamics hoisting,
        # so equality keeps refs inline: `not x.missing == false` succeeds on
        # undefined (relied on by e.g. the reference's
        # allow-privilege-escalation template)
        lhs = _hoist_eq_side(t.lhs, gen, hoists)
        rhs = _hoist_eq_side(t.rhs, gen, hoists)
        return A.BinOp(op=t.op, lhs=lhs, rhs=rhs, line=t.line)
    lhs = _hoist_operand(t.lhs, gen, hoists)
    rhs = _hoist_operand(t.rhs, gen, hoists)
    return A.BinOp(op=t.op, lhs=lhs, rhs=rhs, line=t.line)


def _hoist_operand(t: A.Term, gen: _Gen, hoists: List[A.Expr]) -> A.Term:
    """Replace a dynamic operand with a fresh local bound before the expr."""
    if isinstance(t, A.Ref):
        # hoist nested dynamics first, then the ref itself
        new_ops = [_hoist_operand(op, gen, hoists) for op in t.ops]
        inner = A.Ref(head=t.head, ops=new_ops, line=t.line)
        v = gen.fresh()
        hoists.append(A.Unify(lhs=A.Var(name=v, line=t.line), rhs=inner, line=t.line))
        return A.Var(name=v, line=t.line)
    if isinstance(t, (A.Call, A.BinOp)):
        inner = _hoist_call_like(t, gen, hoists)
        v = gen.fresh()
        hoists.append(
            A.Unify(lhs=A.Var(name=v, line=t.line), rhs=inner, line=t.line)
        )
        return A.Var(name=v, line=t.line)
    if isinstance(t, (A.ArrayTerm, A.SetTerm)):
        items = [_hoist_operand(x, gen, hoists) for x in t.items]
        if isinstance(t, A.ArrayTerm):
            return A.ArrayTerm(items=items, line=t.line)
        return A.SetTerm(items=items, line=t.line)
    if isinstance(t, A.ObjectTerm):
        items = [
            (_hoist_operand(k, gen, hoists), _hoist_operand(v, gen, hoists))
            for k, v in t.items
        ]
        return A.ObjectTerm(items=items, line=t.line)
    # scalars, vars, wildcards, comprehensions (always defined) stay inline
    return t
