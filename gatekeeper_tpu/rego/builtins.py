"""Builtin functions for the Rego interpreter.

Covers the builtin surface exercised by the reference's policy library
(/root/reference/library) and target/hook Rego. Semantics follow the vendored
OPA topdown builtins (/root/reference/vendor/github.com/open-policy-agent/
opa/topdown/). A builtin error (e.g. to_number on garbage) makes the calling
expression undefined, matching OPA's default (non-strict) behavior.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, Tuple

from .values import freeze, opa_repr, rego_cmp, sort_key, type_name


class BuiltinError(Exception):
    """Raised by builtins on type/domain errors -> expression undefined."""


def _want(v: Any, *types: str) -> Any:
    if type_name(v) not in types:
        raise BuiltinError(f"expected {'/'.join(types)}, got {type_name(v)}")
    return v


def _count(x):
    _want(x, "array", "set", "object", "string")
    return len(x)


def _sprintf(fmt, args):
    _want(fmt, "string")
    _want(args, "array")
    out = []
    i = 0
    ai = 0
    while i < len(fmt):
        c = fmt[i]
        if c == "%" and i + 1 < len(fmt):
            spec = fmt[i + 1]
            if spec == "%":
                out.append("%")
                i += 2
                continue
            if spec in "vdsf":
                if ai >= len(args):
                    raise BuiltinError("sprintf: not enough args")
                arg = args[ai]
                ai += 1
                if spec == "v":
                    out.append(opa_repr(arg, top=True))
                elif spec == "d":
                    _want(arg, "number")
                    out.append(str(int(arg)))
                elif spec == "s":
                    out.append(arg if isinstance(arg, str) else opa_repr(arg, top=True))
                elif spec == "f":
                    _want(arg, "number")
                    out.append(f"{float(arg):f}")
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


def _startswith(s, prefix):
    _want(s, "string")
    _want(prefix, "string")
    return s.startswith(prefix)


def _endswith(s, suffix):
    _want(s, "string")
    _want(suffix, "string")
    return s.endswith(suffix)


def _contains(s, sub):
    _want(s, "string")
    _want(sub, "string")
    return sub in s


def _split(s, sep):
    _want(s, "string")
    _want(sep, "string")
    return tuple(s.split(sep))


def _concat(sep, coll):
    _want(sep, "string")
    _want(coll, "array", "set")
    items = list(coll) if isinstance(coll, tuple) else sorted(coll, key=sort_key)
    for x in items:
        _want(x, "string")
    return sep.join(items)


def _trim(s, cutset):
    _want(s, "string")
    _want(cutset, "string")
    return s.strip(cutset)


def _trim_left(s, cutset):
    _want(s, "string")
    _want(cutset, "string")
    return s.lstrip(cutset)


def _trim_right(s, cutset):
    _want(s, "string")
    _want(cutset, "string")
    return s.rstrip(cutset)


def _trim_space(s):
    _want(s, "string")
    return s.strip()


def _trim_prefix(s, prefix):
    _want(s, "string")
    _want(prefix, "string")
    return s[len(prefix) :] if s.startswith(prefix) else s


def _trim_suffix(s, suffix):
    _want(s, "string")
    _want(suffix, "string")
    return s[: len(s) - len(suffix)] if suffix and s.endswith(suffix) else s


def _replace(s, old, new):
    _want(s, "string")
    _want(old, "string")
    _want(new, "string")
    return s.replace(old, new)


def _lower(s):
    _want(s, "string")
    return s.lower()


def _upper(s):
    _want(s, "string")
    return s.upper()


def _format_int(n, base):
    _want(n, "number")
    _want(base, "number")
    base = int(base)
    n = int(n)
    if base == 10:
        return str(n)
    if base == 16:
        return format(n, "x")
    if base == 8:
        return format(n, "o")
    if base == 2:
        return format(n, "b")
    raise BuiltinError("format_int: unsupported base")


_RE_CACHE: Dict[str, "re.Pattern[str]"] = {}


def compile_go_regex(pattern: str) -> "re.Pattern[str]":
    """Compile a (RE2-flavored) pattern with Python's re.

    The reference's library uses a conservative regex subset that is common to
    RE2 and Python re. Patterns that fail to compile raise BuiltinError, which
    makes the calling expression undefined (OPA errors there too).
    """
    pat = _RE_CACHE.get(pattern)
    if pat is None:
        try:
            pat = re.compile(pattern)
        except re.error as e:
            raise BuiltinError(f"re_match: bad pattern {pattern!r}: {e}")
        _RE_CACHE[pattern] = pat
    return pat


def _re_match(pattern, value):
    _want(pattern, "string")
    _want(value, "string")
    return compile_go_regex(pattern).search(value) is not None


def _to_number(x):
    t = type_name(x)
    if t == "null":
        return 0
    if t == "boolean":
        return 1 if x else 0
    if t == "number":
        return x
    if t == "string":
        s = x.strip()
        try:
            if re.fullmatch(r"[-+]?\d+", s):
                return int(s)
            return float(s)
        except ValueError:
            raise BuiltinError(f"to_number: cannot parse {x!r}")
    raise BuiltinError(f"to_number: bad type {t}")


def _any(coll):
    _want(coll, "array", "set")
    return any(x is True for x in coll)


def _all(coll):
    _want(coll, "array", "set")
    return all(x is True for x in coll)


def _sort(coll):
    _want(coll, "array", "set")
    return tuple(sorted(coll, key=sort_key))


def _sum(coll):
    _want(coll, "array", "set")
    total = 0
    for x in coll:
        _want(x, "number")
        total += x
    return total


def _max(coll):
    _want(coll, "array", "set")
    if not coll:
        raise BuiltinError("max: empty collection")
    items = sorted(coll, key=sort_key)
    return items[-1]


def _min(coll):
    _want(coll, "array", "set")
    if not coll:
        raise BuiltinError("min: empty collection")
    items = sorted(coll, key=sort_key)
    return items[0]


def _abs(n):
    _want(n, "number")
    return abs(n)


def _round(n):
    _want(n, "number")
    import math

    return math.floor(n + 0.5)


def _object_get(obj, key, default):
    _want(obj, "object")
    return obj[key] if key in obj else default


def _substring(s, start, length):
    _want(s, "string")
    _want(start, "number")
    _want(length, "number")
    start = int(start)
    length = int(length)
    if start < 0:
        raise BuiltinError("substring: negative offset")
    if length < 0:
        return s[start:]
    return s[start : start + length]


def _object_union(a, b):
    # mergeWithOverwrite semantics: recursive merge, right side wins on
    # conflicts unless both values are objects (then merged recursively);
    # mirrors /root/reference/vendor/.../opa/topdown/object.go
    _want(a, "object")
    _want(b, "object")
    from .values import Obj

    out = dict(a)
    for k, v in b.items():
        if k in out and type_name(out[k]) == "object" and type_name(v) == "object":
            out[k] = _object_union(out[k], v)
        else:
            out[k] = v
    return Obj(out)


def _object_remove(obj, keys):
    _want(obj, "object")
    _want(keys, "array", "set", "object")
    drop = set(keys) if not isinstance(keys, dict) else set(keys.keys())
    from .values import Obj

    return Obj({k: v for k, v in obj.items() if k not in drop})


def _object_filter(obj, keys):
    _want(obj, "object")
    _want(keys, "array", "set", "object")
    keep = set(keys) if not isinstance(keys, dict) else set(keys.keys())
    from .values import Obj

    return Obj({k: v for k, v in obj.items() if k in keep})


def _trace(note):
    _want(note, "string")
    return True


def _array_concat(a, b):
    _want(a, "array")
    _want(b, "array")
    return a + b


def _to_set(coll):
    _want(coll, "array", "set")
    return frozenset(coll)


def _intersection(sets):
    _want(sets, "set")
    result = None
    for s in sets:
        _want(s, "set")
        result = s if result is None else result & s
    return result if result is not None else frozenset()


def _union(sets):
    _want(sets, "set")
    result = frozenset()
    for s in sets:
        _want(s, "set")
        result = result | s
    return result


def _json_marshal(v):
    import json

    from .values import thaw

    return json.dumps(thaw(v), separators=(",", ":"), sort_keys=True)


def _json_unmarshal(s):
    import json

    _want(s, "string")
    try:
        return freeze(json.loads(s))
    except ValueError as e:
        raise BuiltinError(f"json.unmarshal: {e}")


def _is_type(t: str) -> Callable[[Any], bool]:
    def check(v):
        return type_name(v) == t

    return check


def _external_data(req):
    """Gatekeeper v3's external_data builtin: {"provider": name,
    "keys": [...]} -> {"responses": [[k, v]...], "errors": [[k,
    reason]...], "status_code", "system_error"}. Resolution goes
    through the process's ExternalDataSystem (externaldata/binding.py):
    cache-first, with the batch plane having prefetched the
    micro-batch's deduped keys in ONE outbound fetch per provider. No
    system bound or unknown provider -> undefined (counted), matching
    OPA's behavior for an unconfigured builtin."""
    _want(req, "object")
    if "provider" not in req or "keys" not in req:
        raise BuiltinError("external_data: want {provider, keys}")
    provider = _want(req["provider"], "string")
    keys_val = _want(req["keys"], "array", "set")
    keys = []
    items = (
        sorted(keys_val, key=sort_key)
        if isinstance(keys_val, frozenset)
        else keys_val
    )
    for k in items:
        _want(k, "string")
        keys.append(k)
    from ..externaldata import UnknownProviderError, get_system

    system = get_system()
    if system is None:
        raise BuiltinError(
            "external_data: no provider system configured"
        )
    try:
        resp = system.resolve(provider, keys)
    except UnknownProviderError as e:
        raise BuiltinError(f"external_data: {e.args[0]}")
    return freeze(resp)


def _glob_match(pattern, delimiters, match):
    # glob.match with "*" wildcards per delimiter segment; the reference
    # snapshot's library does not use it, provided for API completeness.
    _want(pattern, "string")
    _want(match, "string")
    delims = [x for x in (delimiters or ())] if delimiters is not None else ["."]
    delim = delims[0] if delims else "."
    regex = "^" + "$DSTAR$".join(re.escape(p) for p in pattern.split("**"))
    regex = regex.replace(re.escape("*"), f"[^{re.escape(delim)}]*")
    regex = regex.replace("$DSTAR$", ".*") + "$"
    return re.match(regex, match) is not None


BUILTINS: Dict[str, Tuple[int, Callable]] = {
    "count": (1, _count),
    "sprintf": (2, _sprintf),
    "startswith": (2, _startswith),
    "endswith": (2, _endswith),
    "contains": (2, _contains),
    "split": (2, _split),
    "concat": (2, _concat),
    "trim": (2, _trim),
    "trim_left": (2, _trim_left),
    "trim_right": (2, _trim_right),
    "trim_prefix": (2, _trim_prefix),
    "trim_suffix": (2, _trim_suffix),
    "trim_space": (1, _trim_space),
    "replace": (3, _replace),
    "lower": (1, _lower),
    "upper": (1, _upper),
    "format_int": (2, _format_int),
    "re_match": (2, _re_match),
    "regex.match": (2, _re_match),
    "to_number": (1, _to_number),
    "any": (1, _any),
    "all": (1, _all),
    "sort": (1, _sort),
    "sum": (1, _sum),
    "max": (1, _max),
    "min": (1, _min),
    "abs": (1, _abs),
    "round": (1, _round),
    "object.get": (3, _object_get),
    "object.union": (2, _object_union),
    "object.remove": (2, _object_remove),
    "object.filter": (2, _object_filter),
    "substring": (3, _substring),
    "trace": (1, _trace),
    "array.concat": (2, _array_concat),
    "cast_set": (1, _to_set),
    "intersection": (1, _intersection),
    "union": (1, _union),
    "json.marshal": (1, _json_marshal),
    "json.unmarshal": (1, _json_unmarshal),
    "is_number": (1, _is_type("number")),
    "is_string": (1, _is_type("string")),
    "is_array": (1, _is_type("array")),
    "is_object": (1, _is_type("object")),
    "is_boolean": (1, _is_type("boolean")),
    "is_null": (1, _is_type("null")),
    "is_set": (1, _is_type("set")),
    "glob.match": (3, _glob_match),
    "external_data": (1, _external_data),
    # equality / comparison exposed as functions (used via operators mostly)
    "eq": (2, lambda a, b: rego_cmp(a, b) == 0),
    "neq": (2, lambda a, b: rego_cmp(a, b) != 0),
}
