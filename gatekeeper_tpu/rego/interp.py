"""Topdown-style Rego interpreter.

This is the framework's semantics oracle and CPU fallback evaluator. It
mirrors the behavior of the vendored OPA topdown evaluator
(/root/reference/vendor/github.com/open-policy-agent/opa/topdown/eval.go)
for the dialect used by Gatekeeper's policy library:

- generator-based body evaluation with backtracking,
- virtual documents (complete / partial-set / partial-object rules) mounted
  into the `data` tree alongside base documents,
- multi-clause functions with literal-pattern formals,
- negation as failure, comprehensions, `with` modifiers,
- memoized rule and function evaluation per query context.

Undefined propagates silently (an expression referencing a missing field
simply fails); builtin errors also make expressions undefined, matching
OPA's non-strict default.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Tuple

from . import ast as A
from .builtins import BUILTINS, BuiltinError
from .parser import parse_module
from .rewrite import rewrite_module
from .safety import all_vars, module_known, reorder_body
from .values import (
    Obj,
    freeze,
    is_truthy,
    rego_cmp,
    sort_key,
    thaw,
    type_name,
)

Env = Dict[str, Any]


class RegoError(Exception):
    """Evaluation error (conflict, recursion, unsafe var)."""


class _UndefinedType:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<undefined>"

    def __bool__(self):
        return False


Undefined = _UndefinedType()


class PkgNode:
    """A node in the package tree: child packages + rules mounted here."""

    __slots__ = ("children", "rules")

    def __init__(self):
        self.children: Dict[str, "PkgNode"] = {}
        self.rules: Dict[str, List[A.Rule]] = {}


class DataCursor:
    """Navigation handle over the merged base-data / virtual-document tree."""

    __slots__ = ("base", "pkg", "path")

    def __init__(self, base: Any, pkg: Optional[PkgNode], path: Tuple[str, ...]):
        self.base = base  # frozen value or Undefined
        self.pkg = pkg  # PkgNode or None
        self.path = path


class Context:
    """Per-query evaluation context: documents + caches.

    `with` modifiers create derived contexts with fresh caches.
    """

    __slots__ = ("interp", "input", "data_root", "cache", "fn_cache", "stack")

    def __init__(self, interp: "Interpreter", input_doc: Any, data_root: Any):
        self.interp = interp
        self.input = input_doc
        self.data_root = data_root
        self.cache: Dict[Any, Any] = {}
        self.fn_cache: Dict[Any, Any] = {}
        self.stack: set = set()


class Interpreter:
    def __init__(self):
        self.pkg_root = PkgNode()
        self.modules: Dict[str, A.Module] = {}
        # safety-reorder caches (keyed by body identity + initially-bound vars)
        self._reorder_cache: Dict[Any, List[A.Expr]] = {}
        self._body_vars_cache: Dict[int, frozenset] = {}
        self._known_cache: Dict[int, frozenset] = {}

    # -- module management --------------------------------------------------

    def add_module(self, name: str, src_or_module) -> A.Module:
        mod = (
            src_or_module
            if isinstance(src_or_module, A.Module)
            else parse_module(src_or_module)
        )
        if name in self.modules:
            self.remove_module(name)
        rewrite_module(mod)
        self.modules[name] = mod
        node = self._pkg_node(mod.package, create=True)
        for rule in mod.rules:
            rule._module = mod  # type: ignore[attr-defined]
            node.rules.setdefault(rule.head.name, []).append(rule)
        self._reorder_cache.clear()
        self._known_cache.clear()
        self._body_vars_cache.clear()
        return mod

    def remove_module(self, name: str) -> None:
        mod = self.modules.pop(name, None)
        if mod is None:
            return
        node = self._pkg_node(mod.package, create=False)
        if node is None:
            return
        for rule in mod.rules:
            lst = node.rules.get(rule.head.name)
            if lst and rule in lst:
                lst.remove(rule)
                if not lst:
                    del node.rules[rule.head.name]
        self._reorder_cache.clear()
        self._known_cache.clear()
        self._body_vars_cache.clear()

    def _pkg_node(self, path: List[str], create: bool) -> Optional[PkgNode]:
        node = self.pkg_root
        for seg in path:
            nxt = node.children.get(seg)
            if nxt is None:
                if not create:
                    return None
                nxt = PkgNode()
                node.children[seg] = nxt
            node = nxt
        return node

    # -- public query API ---------------------------------------------------

    def make_context(self, input_doc: Any = None, data_doc: Any = None) -> Context:
        return Context(self, freeze(input_doc), freeze(data_doc or {}))

    def eval_rule_extent(
        self, pkg_path: List[str], rule_name: str, ctx: Context
    ) -> Any:
        """Evaluate a rule's full extent; Undefined if no solutions."""
        node = self._pkg_node(list(pkg_path), create=False)
        if node is None or rule_name not in node.rules:
            return Undefined
        mod = node.rules[rule_name][0]._module  # type: ignore[attr-defined]
        return _eval_rule(ctx, mod, node, rule_name)

    def query_violations(
        self, pkg_path: List[str], input_doc: Any, data_doc: Any = None
    ) -> List[Any]:
        """Evaluate the `violation` partial set of a template package.

        Returns thawed violation objects ({"msg": ..., "details": ...}).
        """
        ctx = self.make_context(input_doc, data_doc)
        extent = self.eval_rule_extent(pkg_path, "violation", ctx)
        if extent is Undefined:
            return []
        return [thaw(v) for v in sorted(extent, key=sort_key)]

    def run_tests(self, data_doc: Any = None) -> Dict[str, Any]:
        """Run OPA-style unit tests: every rule named test_* must be true.

        Mirrors `opa test` as used by the reference's library test harness
        (/root/reference/library/pod-security-policy/test.sh). Returns a map
        of test name -> True (pass) / False (fail/undefined) / Exception.
        """
        results: Dict[str, Any] = {}
        for mod in self.modules.values():
            seen = set()
            for rule in mod.rules:
                name = rule.head.name
                if not name.startswith("test_") or name in seen:
                    continue
                seen.add(name)
                ctx = self.make_context(None, data_doc)
                node = self._pkg_node(mod.package, create=False)
                try:
                    v = _eval_rule(ctx, mod, node, name)
                    results[f"{mod.package_path}.{name}"] = v is not Undefined and v is not False
                except Exception as e:  # pragma: no cover - diagnostics
                    results[f"{mod.package_path}.{name}"] = e
        return results


# ===========================================================================
# Evaluation machinery (module-level functions; ctx carries all state)


def _bind(env: Env, name: str, value: Any) -> Env:
    e2 = dict(env)
    e2[name] = value
    return e2


def _module_node(ctx: Context, mod: A.Module) -> PkgNode:
    node = ctx.interp._pkg_node(mod.package, create=False)
    assert node is not None
    return node


def _rule_key(mod: A.Module, name: str) -> Tuple:
    return (mod.package_path, name)


def _strict_eq(a: Any, b: Any) -> bool:
    return rego_cmp(a, b) == 0 and isinstance(a, bool) == isinstance(b, bool)


def _eval_rule(ctx: Context, mod: A.Module, node: PkgNode, name: str) -> Any:
    """Evaluate the extent of rule `name` in package node; memoized."""
    key = _rule_key(mod, name)
    if key in ctx.cache:
        return ctx.cache[key]
    if key in ctx.stack:
        raise RegoError(f"recursive rule reference: {'.'.join(key[0])}.{name}")
    rules = node.rules.get(name, [])
    ctx.stack.add(key)
    try:
        kinds = {r.head.kind for r in rules if not r.is_default}
        defaults = [r for r in rules if r.is_default]
        normal = [r for r in rules if not r.is_default]
        if "func" in kinds:
            raise RegoError(f"rule {name} is a function; cannot use as document")
        if kinds <= {"complete"}:
            # all body solutions are enumerated: conflicting outputs raise,
            # matching OPA's "complete rules must not produce multiple
            # outputs" error rather than silently taking the first
            results: List[Any] = []
            for rule in normal:
                rmod = rule._module  # type: ignore[attr-defined]
                for env in _eval_body(ctx, rmod, rule.body, {}):
                    for v, _ in _eval_term(ctx, rmod, rule.head.value, env):
                        if not any(_strict_eq(v, r) for r in results):
                            results.append(v)
            if len(results) > 1:
                raise RegoError(f"complete rule {name}: conflicting values")
            if results:
                value = results[0]
            elif defaults:
                value = _eval_default(ctx, defaults[0])
            else:
                value = Undefined
        elif kinds == {"set"}:
            items = []
            for rule in normal:
                rmod = rule._module  # type: ignore[attr-defined]
                for env in _eval_body(ctx, rmod, rule.body, {}):
                    for v, _ in _eval_term(ctx, rmod, rule.head.key, env):
                        items.append(v)
            value = frozenset(items)
        elif kinds == {"object"}:
            out: Dict[Any, Any] = {}
            for rule in normal:
                rmod = rule._module  # type: ignore[attr-defined]
                for env in _eval_body(ctx, rmod, rule.body, {}):
                    for k, env2 in _eval_term(ctx, rmod, rule.head.key, env):
                        for v, _ in _eval_term(ctx, rmod, rule.head.value, env2):
                            if k in out and not _strict_eq(out[k], v):
                                raise RegoError(
                                    f"partial object {name}: conflicting values"
                                )
                            out[k] = v
            value = Obj(out)
        else:
            raise RegoError(f"rule {name}: mixed rule kinds {kinds}")
        ctx.cache[key] = value
        return value
    finally:
        ctx.stack.discard(key)


def _eval_default(ctx: Context, rule: A.Rule) -> Any:
    rmod = rule._module  # type: ignore[attr-defined]
    for v, _ in _eval_term(ctx, rmod, rule.head.value, {}):
        return v
    return Undefined


def _call_function(
    ctx: Context, mod: A.Module, node: PkgNode, name: str, args: List[Any]
) -> Any:
    """Call a user function; returns value or Undefined."""
    fkey = (id(node), name, tuple(args))
    if fkey in ctx.fn_cache:
        return ctx.fn_cache[fkey]
    rules = node.rules.get(name, [])
    outputs: List[Any] = []
    for rule in rules:
        if rule.head.kind != "func":
            raise RegoError(f"{name} is not a function")
        formals = rule.head.args or []
        if len(formals) != len(args):
            continue
        rmod = rule._module  # type: ignore[attr-defined]
        env: Optional[Env] = {}
        for formal, actual in zip(formals, args):
            env = _match_formal(ctx, rmod, formal, actual, env)
            if env is None:
                break
        if env is None:
            continue
        for benv in _eval_body(ctx, rmod, rule.body, env):
            for v, _ in _eval_term(ctx, rmod, rule.head.value, benv):
                if not any(_strict_eq(v, o) for o in outputs):
                    outputs.append(v)
    if len(outputs) > 1:
        raise RegoError(f"function {name}: conflicting outputs")
    result = outputs[0] if outputs else Undefined
    ctx.fn_cache[fkey] = result
    return result


def _match_formal(
    ctx: Context, mod: A.Module, formal: A.Term, actual: Any, env: Env
) -> Optional[Env]:
    """Unify a function formal parameter against an actual value."""
    if isinstance(formal, A.Wildcard):
        return env
    if isinstance(formal, A.Var):
        if formal.name in env:
            return env if _strict_eq(env[formal.name], actual) else None
        return _bind(env, formal.name, actual)
    if isinstance(formal, A.Scalar):
        return env if _strict_eq(freeze(formal.value), actual) else None
    if isinstance(formal, A.ArrayTerm):
        if type_name(actual) != "array" or len(actual) != len(formal.items):
            return None
        for f, a in zip(formal.items, actual):
            env = _match_formal(ctx, mod, f, a, env)
            if env is None:
                return None
        return env
    # fall back: evaluate the formal as a term and compare
    for v, env2 in _eval_term(ctx, mod, formal, env):
        if _strict_eq(v, actual):
            return env2
    return None


# -- body / expr evaluation -------------------------------------------------


def _known_names(ctx: Context, mod: A.Module) -> frozenset:
    interp = ctx.interp
    key = id(mod)
    known = interp._known_cache.get(key)
    if known is None:
        node = _module_node(ctx, mod)
        known = frozenset(module_known(mod, set(node.rules)))
        interp._known_cache[key] = known
    return known


def _eval_body(
    ctx: Context, mod: A.Module, body: A.Body, env: Env
) -> Iterator[Env]:
    """Evaluate a body with OPA-style safety reordering (memoized)."""
    if not body:
        yield env
        return
    interp = ctx.interp
    known = _known_names(ctx, mod)
    bvars = interp._body_vars_cache.get(id(body))
    if bvars is None:
        referenced: set = set()
        for e in body:
            referenced |= all_vars(e, known)
        bvars = frozenset(referenced)
        interp._body_vars_cache[id(body)] = bvars
    bound0 = frozenset(k for k in env if k in bvars)
    ckey = (id(body), bound0)
    ordered = interp._reorder_cache.get(ckey)
    if ordered is None:
        ordered = reorder_body(body, set(bound0), set(known))
        interp._reorder_cache[ckey] = ordered
    yield from _eval_body_seq(ctx, mod, ordered, 0, env)


def _eval_body_seq(
    ctx: Context, mod: A.Module, body: List[A.Expr], i: int, env: Env
) -> Iterator[Env]:
    if i == len(body):
        yield env
        return
    for env2 in _eval_expr(ctx, mod, body[i], env):
        yield from _eval_body_seq(ctx, mod, body, i + 1, env2)


def _eval_expr(ctx: Context, mod: A.Module, expr: A.Expr, env: Env) -> Iterator[Env]:
    if isinstance(expr, A.TermExpr):
        for v, env2 in _eval_term(ctx, mod, expr.term, env):
            if is_truthy(v):
                yield env2
        return
    if isinstance(expr, A.Assign):
        # `:=` declares locals and may shadow rule names and even input/data
        # (the reference's src_test.rego files do `input := {...}`)
        for v, env2 in _eval_term(ctx, mod, expr.value, env):
            env3 = _bind_pattern(ctx, mod, expr.target, v, env2, declare=True)
            if env3 is not None:
                yield env3
        return
    if isinstance(expr, A.Unify):
        yield from _unify(ctx, mod, expr.lhs, expr.rhs, env)
        return
    if isinstance(expr, A.NotExpr):
        for _ in _eval_expr(ctx, mod, expr.expr, env):
            return  # at least one solution -> `not` fails
        yield env
        return
    if isinstance(expr, A.SomeDecl):
        env2 = dict(env)
        for n in expr.names:
            env2.pop(n, None)
        yield env2
        return
    if isinstance(expr, A.WithExpr):
        yield from _eval_with(ctx, mod, expr, env)
        return
    raise RegoError(f"unsupported expression {type(expr).__name__}")


def _eval_with(
    ctx: Context, mod: A.Module, expr: A.WithExpr, env: Env
) -> Iterator[Env]:
    new_input = ctx.input
    new_data = ctx.data_root
    for m in expr.mods:
        # resolve the modifier value in the *current* context
        vals = list(_eval_term(ctx, mod, m.value, env))
        if not vals:
            return  # undefined modifier value -> expression undefined
        value = vals[0][0]
        path = _term_ref_path(m.target)
        if path is None:
            raise RegoError("with: unsupported target")
        if path[0] == "input":
            new_input = value if len(path) == 1 else _set_path(new_input, path[1:], value)
        elif path[0] == "data":
            new_data = value if len(path) == 1 else _set_path(new_data, path[1:], value)
        else:
            raise RegoError("with: target must be input or data")
    sub = Context(ctx.interp, new_input, new_data)
    # share the recursion stack so cycles through `with` are still detected
    sub.stack = ctx.stack
    # bindings made under `with` propagate out (OPA behavior)
    yield from _eval_expr(sub, mod, expr.expr, env)


def _term_ref_path(t: A.Term) -> Optional[List[str]]:
    if isinstance(t, A.Var):
        return [t.name]
    if isinstance(t, A.Ref) and isinstance(t.head, A.Var):
        path = [t.head.name]
        for op in t.ops:
            if isinstance(op, A.Scalar) and isinstance(op.value, str):
                path.append(op.value)
            else:
                return None
        return path
    return None


def _set_path(root: Any, path: List[str], value: Any) -> Any:
    if not path:
        return value
    base = root if isinstance(root, Obj) else Obj({})
    k = path[0]
    child = base[k] if k in base else Obj({})
    return base.set(k, _set_path(child, path[1:], value))


def _bind_pattern(
    ctx: Context,
    mod: A.Module,
    pattern: A.Term,
    value: Any,
    env: Env,
    declare: bool = False,
) -> Optional[Env]:
    if isinstance(pattern, A.Wildcard):
        return env
    if isinstance(pattern, A.Var):
        node = _module_node(ctx, mod)
        if pattern.name in env:
            return env if _strict_eq(env[pattern.name], value) else None
        if not declare and (
            pattern.name in node.rules or pattern.name in ("input", "data")
        ):
            # name refers to a rule/document: compare, don't bind
            for v, env2 in _eval_term(ctx, mod, pattern, env):
                if _strict_eq(v, value):
                    return env2
            return None
        return _bind(env, pattern.name, value)
    if isinstance(pattern, A.ArrayTerm):
        if type_name(value) != "array" or len(value) != len(pattern.items):
            return None
        for p, v in zip(pattern.items, value):
            env2 = _bind_pattern(ctx, mod, p, v, env, declare=declare)
            if env2 is None:
                return None
            env = env2
        return env
    if isinstance(pattern, A.ObjectTerm):
        if type_name(value) != "object":
            return None
        for kt, vt in pattern.items:
            kvals = list(_eval_term(ctx, mod, kt, env))
            if len(kvals) != 1:
                return None
            k = kvals[0][0]
            if k not in value:
                return None
            env2 = _bind_pattern(ctx, mod, vt, value[k], env, declare=declare)
            if env2 is None:
                return None
            env = env2
        return env
    if isinstance(pattern, A.Scalar):
        return env if _strict_eq(freeze(pattern.value), value) else None
    # general term: evaluate and compare
    for v, env2 in _eval_term(ctx, mod, pattern, env):
        if _strict_eq(v, value):
            return env2
    return None


def _is_pattern(node: PkgNode, term: A.Term, env: Env) -> bool:
    """True if term contains unbound variables (bindable positions)."""
    if isinstance(term, A.Wildcard):
        return True
    if isinstance(term, A.Var):
        return (
            term.name not in env
            and term.name not in ("input", "data")
            and term.name not in node.rules
        )
    if isinstance(term, A.ArrayTerm):
        return any(_is_pattern(node, t, env) for t in term.items)
    if isinstance(term, A.ObjectTerm):
        return any(_is_pattern(node, v, env) for _, v in term.items)
    return False


def _unify(
    ctx: Context, mod: A.Module, lhs: A.Term, rhs: A.Term, env: Env
) -> Iterator[Env]:
    node = _module_node(ctx, mod)
    lhs_pat = _is_pattern(node, lhs, env)
    rhs_pat = _is_pattern(node, rhs, env)
    if lhs_pat and rhs_pat:
        if isinstance(lhs, A.Wildcard) and isinstance(rhs, A.Wildcard):
            yield env
            return
        raise RegoError("unification with unbound variables on both sides")
    if lhs_pat:
        for v, env2 in _eval_term(ctx, mod, rhs, env):
            env3 = _bind_pattern(ctx, mod, lhs, v, env2)
            if env3 is not None:
                yield env3
        return
    if rhs_pat:
        for v, env2 in _eval_term(ctx, mod, lhs, env):
            env3 = _bind_pattern(ctx, mod, rhs, v, env2)
            if env3 is not None:
                yield env3
        return
    for lv, env2 in _eval_term(ctx, mod, lhs, env):
        for rv, env3 in _eval_term(ctx, mod, rhs, env2):
            if _strict_eq(lv, rv):
                yield env3


# -- term evaluation --------------------------------------------------------


def _eval_terms(
    ctx: Context, mod: A.Module, terms: List[A.Term], env: Env
) -> Iterator[Tuple[List[Any], Env]]:
    if not terms:
        yield [], env
        return
    for v, env2 in _eval_term(ctx, mod, terms[0], env):
        for vs, env3 in _eval_terms(ctx, mod, terms[1:], env2):
            yield [v] + vs, env3


def _eval_term(
    ctx: Context, mod: A.Module, term: A.Term, env: Env
) -> Iterator[Tuple[Any, Env]]:
    if isinstance(term, A.Scalar):
        yield freeze(term.value), env
        return
    if isinstance(term, A.Var):
        yield from _resolve_var(ctx, mod, term.name, env)
        return
    if isinstance(term, A.Wildcard):
        raise RegoError("wildcard in value position")
    if isinstance(term, A.Ref):
        yield from _eval_ref(ctx, mod, term, env)
        return
    if isinstance(term, A.Call):
        yield from _eval_call(ctx, mod, term, env)
        return
    if isinstance(term, A.BinOp):
        yield from _eval_binop(ctx, mod, term, env)
        return
    if isinstance(term, A.UnaryMinus):
        for v, env2 in _eval_term(ctx, mod, term.operand, env):
            if type_name(v) == "number" and not isinstance(v, bool):
                yield -v, env2
        return
    if isinstance(term, A.ArrayTerm):
        for vs, env2 in _eval_terms(ctx, mod, term.items, env):
            yield tuple(vs), env2
        return
    if isinstance(term, A.SetTerm):
        for vs, env2 in _eval_terms(ctx, mod, term.items, env):
            yield frozenset(vs), env2
        return
    if isinstance(term, A.ObjectTerm):
        keys = [k for k, _ in term.items]
        vals = [v for _, v in term.items]
        for kvs, env2 in _eval_terms(ctx, mod, keys, env):
            for vvs, env3 in _eval_terms(ctx, mod, vals, env2):
                yield Obj(dict(zip(kvs, vvs))), env3
        return
    if isinstance(term, A.Comprehension):
        yield _eval_comprehension(ctx, mod, term, env), env
        return
    raise RegoError(f"unsupported term {type(term).__name__}")


def _resolve_var(
    ctx: Context, mod: A.Module, name: str, env: Env
) -> Iterator[Tuple[Any, Env]]:
    if name in env:
        yield env[name], env
        return
    if name == "input":
        if ctx.input is not None:
            yield ctx.input, env
        return
    if name == "data":
        yield DataCursor(ctx.data_root, ctx.interp.pkg_root, ()), env
        return
    node = _module_node(ctx, mod)
    if name in node.rules:
        rules = node.rules[name]
        if rules and rules[0].head.kind == "func":
            raise RegoError(f"function {name} used as value")
        v = _eval_rule(ctx, mod, node, name)
        if v is not Undefined:
            yield v, env
        return
    # imports: `import data.x.y` binds y (or its alias)
    for imp in mod.imports:
        bound = imp.alias or imp.path[-1]
        if bound == name and imp.path and imp.path[0] == "data":
            cur: Any = DataCursor(ctx.data_root, ctx.interp.pkg_root, ())
            ok = True
            for seg in imp.path[1:]:
                cur = _index_value(ctx, cur, seg)
                if cur is Undefined:
                    ok = False
                    break
            if ok:
                yield cur, env
            return
    raise RegoError(f"unsafe variable: {name} (module {mod.package_path})")


def _eval_ref(
    ctx: Context, mod: A.Module, ref: A.Ref, env: Env
) -> Iterator[Tuple[Any, Env]]:
    if isinstance(ref.head, A.Var):
        bases = _resolve_var(ctx, mod, ref.head.name, env)
    else:
        bases = _eval_term(ctx, mod, ref.head, env)
    for base, env1 in bases:
        yield from _walk_ops(ctx, mod, base, ref.ops, 0, env1)


def _walk_ops(
    ctx: Context, mod: A.Module, val: Any, ops: List[A.Term], i: int, env: Env
) -> Iterator[Tuple[Any, Env]]:
    if i == len(ops):
        if isinstance(val, DataCursor):
            val = _materialize_cursor(ctx, val)
            if val is Undefined:
                return
        yield val, env
        return
    op = ops[i]
    node = _module_node(ctx, mod)
    if _is_pattern(node, op, env):
        # unbound operand: enumerate the collection, unifying the pattern
        # against each key (for sets, against each member — this covers
        # `general_violation[{"msg": msg, "field": "containers"}]`-style
        # partial-set lookups in the reference library)
        for k, item in _enumerate_value(ctx, val):
            env2 = _bind_pattern(ctx, mod, op, k, env)
            if env2 is not None:
                yield from _walk_ops(ctx, mod, item, ops, i + 1, env2)
        return
    for k, env1 in _eval_term(ctx, mod, op, env):
        item = _index_value(ctx, val, k)
        if item is not Undefined:
            yield from _walk_ops(ctx, mod, item, ops, i + 1, env1)


def _index_value(ctx: Context, val: Any, key: Any) -> Any:
    if isinstance(val, DataCursor):
        if not isinstance(key, str):
            return (
                _index_raw(val.base, key) if val.base is not Undefined else Undefined
            )
        if val.pkg is not None:
            rules = val.pkg.rules.get(key)
            if rules:
                mod = rules[0]._module  # type: ignore[attr-defined]
                node = ctx.interp._pkg_node(mod.package, create=False)
                return _eval_rule(ctx, mod, node, key)
            child = val.pkg.children.get(key)
            base_child = (
                _index_raw(val.base, key) if val.base is not Undefined else Undefined
            )
            if child is not None:
                return DataCursor(base_child, child, val.path + (key,))
            return base_child
        return _index_raw(val.base, key) if val.base is not Undefined else Undefined
    return _index_raw(val, key)


def _index_raw(val: Any, key: Any) -> Any:
    if val is Undefined:
        return Undefined
    t = type_name(val)
    if t == "object":
        return val[key] if key in val else Undefined
    if t == "array":
        if isinstance(key, bool) or not isinstance(key, (int, float)):
            return Undefined
        idx = int(key)
        if idx != key or idx < 0 or idx >= len(val):
            return Undefined
        return val[idx]
    if t == "set":
        return key if key in val else Undefined
    return Undefined


def _enumerate_value(ctx: Context, val: Any) -> Iterator[Tuple[Any, Any]]:
    if isinstance(val, DataCursor):
        seen = set()
        if val.pkg is not None:
            for name, rules in list(val.pkg.rules.items()):
                mod = rules[0]._module  # type: ignore[attr-defined]
                node = ctx.interp._pkg_node(mod.package, create=False)
                v = _eval_rule(ctx, mod, node, name)
                if v is not Undefined:
                    seen.add(name)
                    yield name, v
            for name, child in val.pkg.children.items():
                base_child = (
                    _index_raw(val.base, name)
                    if val.base is not Undefined
                    else Undefined
                )
                seen.add(name)
                yield name, DataCursor(base_child, child, val.path + (name,))
        if val.base is not Undefined and type_name(val.base) == "object":
            for k in sorted(val.base.keys(), key=sort_key):
                if k not in seen:
                    yield k, val.base[k]
        return
    if val is Undefined:
        return
    t = type_name(val)
    if t == "object":
        for k in sorted(val.keys(), key=sort_key):
            yield k, val[k]
    elif t == "array":
        for idx, item in enumerate(val):
            yield idx, item
    elif t == "set":
        for item in sorted(val, key=sort_key):
            yield item, item
    # scalars: nothing to enumerate -> undefined


def _materialize_cursor(ctx: Context, cur: DataCursor) -> Any:
    out: Dict[Any, Any] = {}
    for k, v in _enumerate_value(ctx, cur):
        if isinstance(v, DataCursor):
            v = _materialize_cursor(ctx, v)
            if v is Undefined:
                continue
        out[k] = v
    if out:
        return Obj(out)
    if cur.base is not Undefined:
        return cur.base
    return Obj({})


def _resolve_fn_node(
    ctx: Context, mod: A.Module, name: str
) -> Tuple[Optional[PkgNode], str]:
    """Resolve a call name to its package node + local rule name.

    Bare names resolve in the calling module; dotted `data.…` names resolve
    through the package tree (cross-package function calls, used by
    ConstraintTemplate libs after rewriting)."""
    node = _module_node(ctx, mod)
    if (
        name in node.rules
        and node.rules[name]
        and node.rules[name][0].head.kind == "func"
    ):
        return node, name
    if name.startswith("data."):
        parts = name.split(".")[1:]
        fn_node = ctx.interp._pkg_node(parts[:-1], create=False)
        local = parts[-1]
        if (
            fn_node is not None
            and local in fn_node.rules
            and fn_node.rules[local]
            and fn_node.rules[local][0].head.kind == "func"
        ):
            return fn_node, local
    return None, name


def _eval_call(
    ctx: Context, mod: A.Module, call: A.Call, env: Env
) -> Iterator[Tuple[Any, Env]]:
    name = call.name
    fn_node, local_name = _resolve_fn_node(ctx, mod, name)
    for args, env2 in _eval_terms(ctx, mod, call.args, env):
        if fn_node is not None:
            v = _call_function(ctx, mod, fn_node, local_name, args)
            if v is not Undefined:
                yield v, env2
            continue
        if name in BUILTINS:
            arity, fn = BUILTINS[name]
            if arity != len(args):
                raise RegoError(
                    f"builtin {name}: want {arity} args, got {len(args)}"
                )
            try:
                v = fn(*args)
            except BuiltinError:
                continue  # undefined
            yield v, env2
            continue
        raise RegoError(f"unknown function {name}")


def _eval_binop(
    ctx: Context, mod: A.Module, term: A.BinOp, env: Env
) -> Iterator[Tuple[Any, Env]]:
    op = term.op
    for lv, env2 in _eval_term(ctx, mod, term.lhs, env):
        for rv, env3 in _eval_term(ctx, mod, term.rhs, env2):
            if op == "==":
                yield _strict_eq(lv, rv), env3
            elif op == "!=":
                yield not _strict_eq(lv, rv), env3
            elif op in ("<", "<=", ">", ">="):
                c = rego_cmp(lv, rv)
                yield {"<": c < 0, "<=": c <= 0, ">": c > 0, ">=": c >= 0}[op], env3
            elif op in ("+", "-", "*", "/", "%", "&", "|"):
                tl, tr = type_name(lv), type_name(rv)
                if tl == "set" and tr == "set":
                    if op == "-":
                        yield lv - rv, env3
                    elif op == "&":
                        yield lv & rv, env3
                    elif op == "|":
                        yield lv | rv, env3
                    # other ops on sets: undefined
                    continue
                if (
                    tl == "number"
                    and tr == "number"
                    and not isinstance(lv, bool)
                    and not isinstance(rv, bool)
                ):
                    if op == "+":
                        yield lv + rv, env3
                    elif op == "-":
                        yield lv - rv, env3
                    elif op == "*":
                        yield lv * rv, env3
                    elif op == "/":
                        if rv == 0:
                            continue  # undefined (division by zero)
                        if isinstance(lv, int) and isinstance(rv, int) and lv % rv == 0:
                            yield lv // rv, env3
                        else:
                            yield lv / rv, env3
                    elif op == "%":
                        if rv == 0 or not (
                            isinstance(lv, int) and isinstance(rv, int)
                        ):
                            continue  # modulo on floats / by zero: undefined
                        yield lv % rv, env3
                # mismatched operand types: undefined
                continue
            else:
                raise RegoError(f"unknown operator {op}")


def _eval_comprehension(
    ctx: Context, mod: A.Module, term: A.Comprehension, env: Env
) -> Any:
    if term.kind == "array":
        items = []
        for env2 in _eval_body(ctx, mod, term.body, env):
            for v, _ in _eval_term(ctx, mod, term.head, env2):
                items.append(v)
        return tuple(items)
    if term.kind == "set":
        items = []
        for env2 in _eval_body(ctx, mod, term.body, env):
            for v, _ in _eval_term(ctx, mod, term.head, env2):
                items.append(v)
        return frozenset(items)
    if term.kind == "object":
        out: Dict[Any, Any] = {}
        for env2 in _eval_body(ctx, mod, term.body, env):
            for k, env3 in _eval_term(ctx, mod, term.key, env2):
                for v, _ in _eval_term(ctx, mod, term.head, env3):
                    if k in out and not _strict_eq(out[k], v):
                        raise RegoError("object comprehension: conflicting keys")
                    out[k] = v
        return Obj(out)
    raise RegoError(f"unknown comprehension kind {term.kind}")
