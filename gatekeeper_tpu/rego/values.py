"""Canonical immutable value model for the Rego interpreter.

Values are kept in frozen form throughout evaluation so sets/object-keys and
unification are well-defined:

  null     -> None
  boolean  -> bool
  number   -> int | float       (ints and floats compare equal, as in Rego)
  string   -> str
  array    -> tuple
  object   -> Obj (immutable, hashable mapping)
  set      -> frozenset

Known limitation (documented): Python treats True == 1, so a set containing
both `true` and `1` would collapse; this combination does not occur in the
reference's policy corpus (/root/reference/library).

Ordering follows OPA's total term order (null < bool < number < string <
array < object < set; see the vendored OPA's ast term Compare semantics at
/root/reference/vendor/github.com/open-policy-agent/opa/ast/term.go) so that
sort()/set-iteration/printing are deterministic and reference-shaped.
"""

from __future__ import annotations

from typing import Any, Iterator, Mapping, Tuple


class Obj(Mapping):
    """Immutable hashable object (Rego object value)."""

    __slots__ = ("_d", "_hash")

    def __init__(self, d: Mapping):
        self._d = dict(d)
        self._hash = None

    def __getitem__(self, k):
        return self._d[k]

    def __iter__(self) -> Iterator:
        return iter(self._d)

    def __len__(self) -> int:
        return len(self._d)

    def __hash__(self):
        if self._hash is None:
            self._hash = hash(frozenset(self._d.items()))
        return self._hash

    def __eq__(self, other):
        if isinstance(other, Obj):
            return self._d == other._d
        if isinstance(other, Mapping):
            return self._d == dict(other)
        return NotImplemented

    def __repr__(self):  # pragma: no cover - debug aid
        return f"Obj({self._d!r})"

    def set(self, k, v) -> "Obj":
        d = dict(self._d)
        d[k] = v
        return Obj(d)


EMPTY_OBJ = Obj({})


def freeze(v: Any) -> Any:
    """JSON-ish Python value -> frozen canonical value."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, Obj):
        # Obj is only ever built over frozen contents — re-freezing a
        # cached subtree (e.g. the audit inventory) must be O(1)
        return v
    if isinstance(v, (list, tuple)):
        return tuple(freeze(x) for x in v)
    if isinstance(v, (set, frozenset)):
        return frozenset(freeze(x) for x in v)
    if isinstance(v, Mapping):
        return Obj({freeze(k): freeze(val) for k, val in v.items()})
    raise TypeError(f"cannot freeze value of type {type(v)}")


def thaw(v: Any) -> Any:
    """Frozen value -> plain JSON-ish Python value (sets become sorted lists)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, tuple):
        return [thaw(x) for x in v]
    if isinstance(v, frozenset):
        return [thaw(x) for x in sorted(v, key=sort_key)]
    if isinstance(v, Obj):
        return {thaw(k): thaw(val) for k, val in v.items()}
    raise TypeError(f"cannot thaw value of type {type(v)}")


def type_name(v: Any) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, (int, float)):
        return "number"
    if isinstance(v, str):
        return "string"
    if isinstance(v, tuple):
        return "array"
    if isinstance(v, Obj):
        return "object"
    if isinstance(v, frozenset):
        return "set"
    raise TypeError(f"unknown value type {type(v)}")


_TYPE_RANK = {
    "null": 0,
    "boolean": 1,
    "number": 2,
    "string": 3,
    "array": 4,
    "object": 5,
    "set": 6,
}


def rego_cmp(a: Any, b: Any) -> int:
    """Total order over values, mirroring OPA term comparison."""
    ta, tb = type_name(a), type_name(b)
    if ta != tb:
        return -1 if _TYPE_RANK[ta] < _TYPE_RANK[tb] else 1
    if ta == "null":
        return 0
    if ta == "boolean":
        return (a > b) - (a < b)
    if ta == "number":
        return (a > b) - (a < b)
    if ta == "string":
        return (a > b) - (a < b)
    if ta == "array":
        for x, y in zip(a, b):
            c = rego_cmp(x, y)
            if c:
                return c
        return (len(a) > len(b)) - (len(a) < len(b))
    if ta == "object":
        ka = sorted(a.keys(), key=sort_key)
        kb = sorted(b.keys(), key=sort_key)
        for x, y in zip(ka, kb):
            c = rego_cmp(x, y)
            if c:
                return c
            c = rego_cmp(a[x], b[y])
            if c:
                return c
        return (len(ka) > len(kb)) - (len(ka) < len(kb))
    if ta == "set":
        sa = sorted(a, key=sort_key)
        sb = sorted(b, key=sort_key)
        for x, y in zip(sa, sb):
            c = rego_cmp(x, y)
            if c:
                return c
        return (len(sa) > len(sb)) - (len(sa) < len(sb))
    raise TypeError(ta)


class _SortKey:
    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return rego_cmp(self.v, other.v) < 0

    def __eq__(self, other):
        return rego_cmp(self.v, other.v) == 0


def sort_key(v: Any) -> _SortKey:
    return _SortKey(v)


def rego_eq(a: Any, b: Any) -> bool:
    """Type-strict equality (booleans are never equal to numbers)."""
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    return rego_cmp(a, b) == 0 if type_name(a) == type_name(b) else False


def _num_str(n) -> str:
    if isinstance(n, bool):  # pragma: no cover - callers dispatch on type
        return "true" if n else "false"
    if isinstance(n, int):
        return str(n)
    if n == int(n) and abs(n) < 1e15:
        return str(int(n))
    return repr(n)


def opa_repr(v: Any, top: bool = False) -> str:
    """Render a value the way OPA's sprintf(%v) does.

    Top-level strings print raw; nested strings print JSON-quoted. Sets print
    as {...} in sorted term order; objects sort keys. This matches the message
    text Gatekeeper produces for e.g.
    'you must provide labels: {"gatekeeper"}'
    (/root/reference/library/general/requiredlabels/template.yaml).
    """
    t = type_name(v)
    if t == "null":
        return "null"
    if t == "boolean":
        return "true" if v else "false"
    if t == "number":
        return _num_str(v)
    if t == "string":
        if top:
            return v
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if t == "array":
        return "[" + ", ".join(opa_repr(x) for x in v) + "]"
    if t == "set":
        if not v:
            return "set()"
        return "{" + ", ".join(opa_repr(x) for x in sorted(v, key=sort_key)) + "}"
    if t == "object":
        items = sorted(v.items(), key=lambda kv: sort_key(kv[0]))
        return "{" + ", ".join(f"{opa_repr(k)}: {opa_repr(x)}" for k, x in items) + "}"
    raise TypeError(t)


def is_truthy(v: Any) -> bool:
    """Rego expression satisfaction: everything but `false` is satisfied."""
    return v is not False
