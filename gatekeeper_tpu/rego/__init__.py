"""Rego frontend: lexer, parser, AST, and a topdown-style interpreter.

This is the semantics core of the framework. The interpreter is the oracle
that defines "correct" for every compiled TPU kernel, and doubles as the CPU
fallback driver for templates outside the vectorizable subset (the hybrid
routing described in SURVEY.md §7). It covers the Rego dialect used by the
reference's policy library (/root/reference/library) and its target matching
library (/root/reference/pkg/target/target_template_source.go).
"""

from .ast import (  # noqa: F401
    Module,
    Rule,
    RuleHead,
    Body,
    Expr,
    Term,
    Scalar,
    Var,
    Wildcard,
    Ref,
    ArrayTerm,
    ObjectTerm,
    SetTerm,
    Call,
    Comprehension,
    UnaryMinus,
    BinOp,
    Assign,
    Unify,
    NotExpr,
    SomeDecl,
    Every,
)
from .lexer import Lexer, Token, LexError  # noqa: F401
from .parser import Parser, ParseError, parse_module, parse_query  # noqa: F401
from .interp import Interpreter, RegoError, Undefined  # noqa: F401
