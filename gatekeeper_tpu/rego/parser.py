"""Recursive-descent Rego parser.

Produces the AST in ast.py. Grammar coverage is the dialect exercised by the
reference's policy library (/root/reference/library), target matching library
(/root/reference/pkg/target/target_template_source.go) and constraint hook
glue (/root/reference/vendor/.../frameworks/constraint/pkg/client/regolib/
src.go): complete/partial/function rules with multiple clauses, default
rules, comprehensions, refs, `not`, `some`, `with` modifiers, and the infix
operator set.

Newline discipline: newlines separate body expressions at bracket depth 0
and are insignificant inside (), [], {} — mirroring OPA's scanner behavior.
"""

from __future__ import annotations

from typing import List, Optional

from .ast import (
    ArrayTerm,
    Assign,
    BinOp,
    Body,
    Call,
    Comprehension,
    Every,
    Expr,
    Import,
    Module,
    NotExpr,
    ObjectTerm,
    Ref,
    Rule,
    RuleHead,
    Scalar,
    SetTerm,
    SomeDecl,
    Term,
    TermExpr,
    UnaryMinus,
    Unify,
    Var,
    Wildcard,
    WithExpr,
    WithModifier,
)
from .lexer import Token, tokenize

COMPARE_OPS = {"==", "!=", "<", "<=", ">", ">="}


class ParseError(Exception):
    def __init__(self, msg: str, tok: Optional[Token] = None):
        loc = f" (line {tok.line}, near {tok.value!r})" if tok else ""
        super().__init__(msg + loc)


class Parser:
    def __init__(self, src: str):
        self.toks = tokenize(src)
        self.i = 0
        self.depth = 0  # bracket nesting; newlines skipped when > 0
        self.wild_counter = 0
        # When parsing the first term inside [...] or {...}, a top-level '|'
        # separates the comprehension head from its body and must not be
        # consumed as set union. Parens reset this (see _parse_primary).
        self.no_union = False

    # -- token helpers ------------------------------------------------------

    def peek(self, off: int = 0) -> Token:
        j = self.i
        seen = 0
        while j < len(self.toks):
            t = self.toks[j]
            if t.kind == "newline" and self.depth > 0:
                j += 1
                continue
            if seen == off:
                return t
            seen += 1
            j += 1
        return self.toks[-1]

    def next(self) -> Token:
        while True:
            t = self.toks[self.i]
            self.i += 1
            if t.kind == "newline" and self.depth > 0:
                continue
            return t

    def skip_newlines(self) -> None:
        while self.peek().kind == "newline":
            self.next()

    def at_punct(self, p: str) -> bool:
        t = self.peek()
        return t.kind == "punct" and t.value == p

    def at_keyword(self, k: str) -> bool:
        t = self.peek()
        return t.kind == "keyword" and t.value == k

    def expect_punct(self, p: str) -> Token:
        t = self.next()
        if t.kind != "punct" or t.value != p:
            raise ParseError(f"expected {p!r}", t)
        return t

    def expect_ident(self) -> Token:
        t = self.next()
        if t.kind != "ident":
            raise ParseError("expected identifier", t)
        return t

    def open(self, p: str) -> None:
        self.expect_punct(p)
        self.depth += 1

    def close(self, p: str) -> None:
        self.expect_punct(p)
        self.depth -= 1

    # -- module / rules -----------------------------------------------------

    def parse_module(self) -> Module:
        self.skip_newlines()
        t = self.next()
        if not (t.kind == "keyword" and t.value == "package"):
            raise ParseError("expected 'package'", t)
        pkg = self._parse_package_path()
        mod = Module(package=pkg, line=t.line)
        self.skip_newlines()
        while self.at_keyword("import"):
            mod.imports.append(self._parse_import())
            self.skip_newlines()
        while self.peek().kind != "eof":
            mod.rules.append(self._parse_rule())
            self.skip_newlines()
        return mod

    def _parse_package_path(self) -> List[str]:
        parts = []
        while True:
            t = self.next()
            if t.kind == "ident":
                parts.append(t.value)
            elif t.kind == "string":
                parts.append(t.value)
            else:
                raise ParseError("expected package path segment", t)
            if self.at_punct("."):
                self.next()
                continue
            if self.at_punct("["):
                # package templates["admission.k8s.gatekeeper.sh"]["Kind"]
                self.open("[")
                seg = self.next()
                if seg.kind != "string":
                    raise ParseError("expected string in package path", seg)
                parts.append(seg.value)
                self.close("]")
                continue
            break
        return parts

    def _parse_import(self) -> Import:
        t = self.next()  # 'import'
        path = []
        while True:
            seg = self.next()
            if seg.kind not in ("ident", "keyword", "string"):
                raise ParseError("expected import path segment", seg)
            path.append(str(seg.value))
            if self.at_punct("."):
                self.next()
                continue
            break
        alias = None
        if self.at_keyword("as"):
            self.next()
            alias = self.expect_ident().value
        return Import(path=path, alias=alias, line=t.line)

    def _parse_rule(self) -> Rule:
        is_default = False
        if self.at_keyword("default"):
            self.next()
            is_default = True
        start = self.peek()
        head = self._parse_rule_head()
        body: Body = []
        if self.at_punct("{"):
            body = self._parse_body_block()
        rule = Rule(head=head, body=body, is_default=is_default, line=start.line)
        if self.at_keyword("else"):
            raise ParseError("'else' rules are not supported", self.peek())
        return rule

    def _parse_rule_head(self) -> RuleHead:
        name_tok = self.expect_ident()
        head = RuleHead(name=name_tok.value, line=name_tok.line)
        if self.at_punct("("):
            head.kind = "func"
            head.args = []
            self.open("(")
            if not self.at_punct(")"):
                while True:
                    head.args.append(self.parse_term())
                    if self.at_punct(","):
                        self.next()
                        continue
                    break
            self.close(")")
        elif self.at_punct("["):
            self.open("[")
            head.key = self.parse_term()
            self.close("]")
            head.kind = "set"
        if self.at_punct("=") or self.at_punct(":="):
            self.next()
            head.value = self.parse_term()
            if head.kind == "set":
                head.kind = "object"
            elif head.kind != "func":
                head.kind = "complete"
        if head.kind == "complete" and head.value is None:
            head.value = Scalar(True, line=head.line)
        if head.kind == "func" and head.value is None:
            head.value = Scalar(True, line=head.line)
        return head

    def _parse_body_block(self) -> Body:
        self.expect_punct("{")
        # newlines inside a rule body are significant: do NOT bump depth
        body: Body = []
        self.skip_newlines()
        while not self.at_punct("}"):
            body.append(self.parse_expr())
            # separator: newline(s) or ';'
            while self.at_punct(";") or self.peek().kind == "newline":
                self.next()
        self.expect_punct("}")
        return body

    # -- expressions --------------------------------------------------------

    def parse_expr(self) -> Expr:
        expr = self._parse_expr_inner()
        if self.at_keyword("with"):
            mods = []
            while self.at_keyword("with"):
                wt = self.next()
                target = self.parse_term()
                if not self.at_keyword("as"):
                    raise ParseError("expected 'as' in with modifier", self.peek())
                self.next()
                value = self.parse_term()
                mods.append(WithModifier(target=target, value=value, line=wt.line))
            return WithExpr(expr=expr, mods=mods)
        return expr

    def _parse_expr_inner(self) -> Expr:
        t = self.peek()
        if t.kind == "keyword" and t.value == "not":
            self.next()
            inner = self._parse_expr_inner()
            return NotExpr(expr=inner, line=t.line)
        if t.kind == "keyword" and t.value == "some":
            self.next()
            names = [self.expect_ident().value]
            while self.at_punct(","):
                self.next()
                names.append(self.expect_ident().value)
            # `some x in xs` membership form is not used by the corpus
            if self.at_keyword("in"):
                raise ParseError("'some .. in ..' is not supported", self.peek())
            return SomeDecl(names=names, line=t.line)
        if t.kind == "keyword" and t.value == "every":
            raise ParseError("'every' is not supported", t)

        lhs = self.parse_term()
        nxt = self.peek()
        if nxt.kind == "punct" and nxt.value == ":=":
            self.next()
            value = self.parse_term()
            return Assign(target=lhs, value=value, line=t.line)
        if nxt.kind == "punct" and nxt.value == "=":
            self.next()
            rhs = self.parse_term()
            return Unify(lhs=lhs, rhs=rhs, line=t.line)
        return TermExpr(term=lhs, line=t.line)

    # -- terms with precedence ---------------------------------------------
    # compare < | < & < +- < */% < unary < postfix

    def parse_term(self) -> Term:
        return self._parse_compare()

    def _parse_term_no_union(self) -> Term:
        saved = self.no_union
        self.no_union = True
        try:
            return self.parse_term()
        finally:
            self.no_union = saved

    def _parse_term_union_ok(self) -> Term:
        saved = self.no_union
        self.no_union = False
        try:
            return self.parse_term()
        finally:
            self.no_union = saved

    def _parse_compare(self) -> Term:
        lhs = self._parse_union()
        t = self.peek()
        if t.kind == "punct" and t.value in COMPARE_OPS:
            self.next()
            rhs = self._parse_union()
            return BinOp(op=t.value, lhs=lhs, rhs=rhs, line=t.line)
        return lhs

    def _parse_union(self) -> Term:
        lhs = self._parse_intersect()
        while self.at_punct("|") and not self.no_union:
            t = self.next()
            rhs = self._parse_intersect()
            lhs = BinOp(op="|", lhs=lhs, rhs=rhs, line=t.line)
        return lhs

    def _parse_intersect(self) -> Term:
        lhs = self._parse_additive()
        while self.at_punct("&"):
            t = self.next()
            rhs = self._parse_additive()
            lhs = BinOp(op="&", lhs=lhs, rhs=rhs, line=t.line)
        return lhs

    def _parse_additive(self) -> Term:
        lhs = self._parse_multiplicative()
        while self.at_punct("+") or self.at_punct("-"):
            t = self.next()
            rhs = self._parse_multiplicative()
            lhs = BinOp(op=t.value, lhs=lhs, rhs=rhs, line=t.line)
        return lhs

    def _parse_multiplicative(self) -> Term:
        lhs = self._parse_unary()
        while self.at_punct("*") or self.at_punct("/") or self.at_punct("%"):
            t = self.next()
            rhs = self._parse_unary()
            lhs = BinOp(op=t.value, lhs=lhs, rhs=rhs, line=t.line)
        return lhs

    def _parse_unary(self) -> Term:
        if self.at_punct("-"):
            t = self.next()
            operand = self._parse_unary()
            if isinstance(operand, Scalar) and isinstance(operand.value, (int, float)):
                return Scalar(-operand.value, line=t.line)
            return UnaryMinus(operand=operand, line=t.line)
        return self._parse_postfix()

    def _parse_postfix(self) -> Term:
        base = self._parse_primary()
        # A dotted identifier chain followed by '(' is a call.
        while True:
            if self.at_punct("."):
                self.next()
                attr = self.next()
                if attr.kind not in ("ident", "keyword"):
                    raise ParseError("expected attribute name", attr)
                nxt = self.peek()
                if (
                    nxt.kind == "punct"
                    and nxt.value == "("
                    and self._is_name_chain(base)
                ):
                    name = self._name_chain_str(base) + "." + str(attr.value)
                    base = self._parse_call_args(name, attr.line)
                else:
                    base = self._ref_append(base, Scalar(str(attr.value), line=attr.line))
            elif self.at_punct("["):
                t = self.peek()
                self.open("[")
                idx = self._parse_term_union_ok()
                self.close("]")
                base = self._ref_append(base, idx, line=t.line)
            elif self.at_punct("(") and self._is_name_chain(base):
                name = self._name_chain_str(base)
                base = self._parse_call_args(name, self.peek().line)
            else:
                break
        return base

    @staticmethod
    def _is_name_chain(t: Term) -> bool:
        if isinstance(t, Var):
            return True
        if isinstance(t, Ref) and isinstance(t.head, Var):
            return all(
                isinstance(op, Scalar) and isinstance(op.value, str) for op in t.ops
            )
        return False

    @staticmethod
    def _name_chain_str(t: Term) -> str:
        if isinstance(t, Var):
            return t.name
        assert isinstance(t, Ref)
        parts = [t.head.name] + [op.value for op in t.ops]  # type: ignore[union-attr]
        return ".".join(parts)

    def _parse_call_args(self, name: str, line: int) -> Call:
        self.open("(")
        args: List[Term] = []
        if not self.at_punct(")"):
            while True:
                args.append(self._parse_term_union_ok())
                if self.at_punct(","):
                    self.next()
                    continue
                break
        self.close(")")
        return Call(name=name, args=args, line=line)

    @staticmethod
    def _ref_append(base: Term, op: Term, line: int = 0) -> Ref:
        if isinstance(base, Ref):
            base.ops.append(op)
            return base
        return Ref(head=base, ops=[op], line=getattr(base, "line", line))

    def _parse_primary(self) -> Term:
        t = self.peek()
        if t.kind == "string":
            self.next()
            return Scalar(t.value, line=t.line)
        if t.kind == "number":
            self.next()
            return Scalar(t.value, line=t.line)
        if t.kind == "keyword":
            if t.value in ("true", "false"):
                self.next()
                return Scalar(t.value == "true", line=t.line)
            if t.value == "null":
                self.next()
                return Scalar(None, line=t.line)
            raise ParseError("unexpected keyword in term", t)
        if t.kind == "ident":
            self.next()
            if t.value == "_":
                self.wild_counter += 1
                return Wildcard(line=t.line, uid=self.wild_counter)
            return Var(name=t.value, line=t.line)
        if t.kind == "punct":
            if t.value == "_":
                self.next()
                self.wild_counter += 1
                return Wildcard(line=t.line, uid=self.wild_counter)
            if t.value == "(":
                self.open("(")
                inner = self._parse_term_union_ok()
                self.close(")")
                return inner
            if t.value == "[":
                return self._parse_array(t)
            if t.value == "{":
                return self._parse_brace(t)
        raise ParseError("unexpected token in term", t)

    def _parse_array(self, t: Token) -> Term:
        self.open("[")
        if self.at_punct("]"):
            self.close("]")
            return ArrayTerm(items=[], line=t.line)
        first = self._parse_term_no_union()
        if self.at_punct("|"):
            self.next()
            body = self._parse_comprehension_body("]")
            return Comprehension(kind="array", head=first, body=body, line=t.line)
        items = [first]
        while self.at_punct(","):
            self.next()
            if self.at_punct("]"):
                break
            items.append(self._parse_term_union_ok())
        self.close("]")
        return ArrayTerm(items=items, line=t.line)

    def _parse_brace(self, t: Token) -> Term:
        self.open("{")
        if self.at_punct("}"):
            self.close("}")
            return ObjectTerm(items=[], line=t.line)
        first = self._parse_term_no_union()
        if self.at_punct(":"):
            self.next()
            value = self._parse_term_no_union()
            if self.at_punct("|"):
                self.next()
                body = self._parse_comprehension_body("}")
                return Comprehension(
                    kind="object", head=value, key=first, body=body, line=t.line
                )
            items = [(first, value)]
            while self.at_punct(","):
                self.next()
                if self.at_punct("}"):
                    break
                k = self.parse_term()
                self.expect_punct(":")
                v = self.parse_term()
                items.append((k, v))
            self.close("}")
            return ObjectTerm(items=items, line=t.line)
        if self.at_punct("|"):
            self.next()
            body = self._parse_comprehension_body("}")
            return Comprehension(kind="set", head=first, body=body, line=t.line)
        items = [first]
        while self.at_punct(","):
            self.next()
            if self.at_punct("}"):
                break
            items.append(self._parse_term_union_ok())
        self.close("}")
        return SetTerm(items=items, line=t.line)

    def _parse_comprehension_body(self, closer: str) -> Body:
        # inside a comprehension we're within brackets, so newlines are
        # already skipped; statements are separated by ';'
        saved = self.no_union
        self.no_union = False
        try:
            body: Body = []
            body.append(self.parse_expr())
            while self.at_punct(";"):
                self.next()
                if self.at_punct(closer):
                    break
                body.append(self.parse_expr())
            self.close(closer)
            return body
        finally:
            self.no_union = saved

    # -- queries ------------------------------------------------------------

    def parse_query(self) -> Body:
        """Parse a semicolon/newline-separated query (for tests/tools)."""
        body: Body = []
        self.skip_newlines()
        while self.peek().kind != "eof":
            body.append(self.parse_expr())
            while self.at_punct(";") or self.peek().kind == "newline":
                self.next()
        return body


def parse_module(src: str) -> Module:
    return Parser(src).parse_module()


def parse_query(src: str) -> Body:
    return Parser(src).parse_query()
