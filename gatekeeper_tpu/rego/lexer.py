"""Rego lexer.

Token stream for the parser. Mirrors the surface syntax accepted by the
vendored OPA scanner (/root/reference/vendor/github.com/open-policy-agent/
opa/ast/parser.go) for the dialect used in Gatekeeper's library.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional


class LexError(Exception):
    def __init__(self, msg: str, line: int):
        super().__init__(f"line {line}: {msg}")
        self.line = line


@dataclass
class Token:
    kind: str  # ident, string, rawstring, number, punct, keyword, eof
    value: Any
    line: int

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Token({self.kind}, {self.value!r}, L{self.line})"


KEYWORDS = {
    "package",
    "import",
    "default",
    "not",
    "with",
    "as",
    "some",
    "in",
    "every",
    "else",
    "true",
    "false",
    "null",
}

# Multi-char puncts first (longest match wins).
PUNCTS = [
    ":=",
    "==",
    "!=",
    "<=",
    ">=",
    "{",
    "}",
    "[",
    "]",
    "(",
    ")",
    ",",
    ";",
    ":",
    ".",
    "|",
    "&",
    "+",
    "-",
    "*",
    "/",
    "%",
    "<",
    ">",
    "=",
]


class Lexer:
    def __init__(self, src: str):
        self.src = src
        self.pos = 0
        self.line = 1
        self.tokens: List[Token] = []

    def error(self, msg: str) -> LexError:
        return LexError(msg, self.line)

    def peek(self, off: int = 0) -> str:
        p = self.pos + off
        return self.src[p] if p < len(self.src) else ""

    def tokenize(self) -> List[Token]:
        while self.pos < len(self.src):
            c = self.src[self.pos]
            if c == "\n":
                # newlines are significant separators between body exprs
                self._emit("newline", "\n")
                self.pos += 1
                self.line += 1
            elif c in " \t\r":
                self.pos += 1
            elif c == "#":
                while self.pos < len(self.src) and self.src[self.pos] != "\n":
                    self.pos += 1
            elif c == '"':
                self._string()
            elif c == "`":
                self._raw_string()
            elif c.isdigit() or (
                c == "." and self.peek(1).isdigit()
            ):
                self._number()
            elif c.isalpha() or c == "_":
                self._ident()
            else:
                self._punct()
        self._emit("eof", None)
        return self.tokens

    def _emit(self, kind: str, value: Any) -> None:
        # collapse runs of newlines
        if kind == "newline" and self.tokens and self.tokens[-1].kind == "newline":
            return
        self.tokens.append(Token(kind, value, self.line))

    def _string(self) -> None:
        start_line = self.line
        self.pos += 1
        out = []
        while True:
            if self.pos >= len(self.src):
                raise LexError("unterminated string", start_line)
            c = self.src[self.pos]
            if c == '"':
                self.pos += 1
                break
            if c == "\n":
                raise LexError("newline in string", start_line)
            if c == "\\":
                self.pos += 1
                e = self.peek()
                self.pos += 1
                if e == "n":
                    out.append("\n")
                elif e == "t":
                    out.append("\t")
                elif e == "r":
                    out.append("\r")
                elif e == '"':
                    out.append('"')
                elif e == "\\":
                    out.append("\\")
                elif e == "/":
                    out.append("/")
                elif e == "u":
                    hexs = self.src[self.pos : self.pos + 4]
                    try:
                        out.append(chr(int(hexs, 16)))
                    except ValueError:
                        raise LexError("bad unicode escape", start_line)
                    if len(hexs) != 4:
                        raise LexError("bad unicode escape", start_line)
                    self.pos += 4
                else:
                    raise LexError(f"bad escape \\{e}", start_line)
            else:
                out.append(c)
                self.pos += 1
        self.tokens.append(Token("string", "".join(out), start_line))

    def _raw_string(self) -> None:
        start_line = self.line
        self.pos += 1
        end = self.src.find("`", self.pos)
        if end < 0:
            raise LexError("unterminated raw string", start_line)
        text = self.src[self.pos : end]
        self.line += text.count("\n")
        self.pos = end + 1
        self.tokens.append(Token("string", text, start_line))

    def _number(self) -> None:
        start = self.pos
        while self.peek().isdigit():
            self.pos += 1
        is_float = False
        if self.peek() == "." and self.peek(1).isdigit():
            is_float = True
            self.pos += 1
            while self.peek().isdigit():
                self.pos += 1
        if self.peek() in "eE":
            nxt = self.peek(1)
            if nxt.isdigit() or (nxt in "+-" and self.peek(2).isdigit()):
                is_float = True
                self.pos += 1
                if self.peek() in "+-":
                    self.pos += 1
                while self.peek().isdigit():
                    self.pos += 1
        text = self.src[start : self.pos]
        self.tokens.append(
            Token("number", float(text) if is_float else int(text), self.line)
        )

    def _ident(self) -> None:
        start = self.pos
        while self.peek().isalnum() or self.peek() == "_":
            self.pos += 1
        name = self.src[start : self.pos]
        if name in KEYWORDS:
            self.tokens.append(Token("keyword", name, self.line))
        else:
            self.tokens.append(Token("ident", name, self.line))

    def _punct(self) -> None:
        for p in PUNCTS:
            if self.src.startswith(p, self.pos):
                self.tokens.append(Token("punct", p, self.line))
                self.pos += len(p)
                return
        raise self.error(f"unexpected character {self.src[self.pos]!r}")


def tokenize(src: str) -> List[Token]:
    return Lexer(src).tokenize()
