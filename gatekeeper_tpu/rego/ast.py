"""AST for the Rego dialect used by Gatekeeper's policy library.

Shapes follow the OPA grammar (reference: the vendored OPA parser at
/root/reference/vendor/github.com/open-policy-agent/opa/ast/) but are
re-modeled as plain Python dataclasses; only the constructs exercised by
the reference's 26 library templates, its target matching library, and the
constraint-framework hook glue are represented.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple


class Node:
    line: int = 0


# ---------------------------------------------------------------------------
# Terms


@dataclass
class Term(Node):
    pass


@dataclass
class Scalar(Term):
    """String, int, float, bool, or None (null)."""

    value: Any
    line: int = 0


@dataclass
class Var(Term):
    name: str
    line: int = 0


@dataclass
class Wildcard(Term):
    """`_` — an anonymous, always-fresh variable."""

    line: int = 0
    # unique id assigned by the parser so each `_` is a distinct variable
    uid: int = 0

    @property
    def name(self) -> str:
        return f"$wild{self.uid}"


@dataclass
class Ref(Term):
    """A reference: head term followed by operand terms.

    `input.review.object.spec.containers[_].name` has head Var("input") and
    operands [Scalar("review"), Scalar("object"), ..., Wildcard(), Scalar("name")].
    """

    head: Term
    ops: List[Term] = field(default_factory=list)
    line: int = 0


@dataclass
class ArrayTerm(Term):
    items: List[Term] = field(default_factory=list)
    line: int = 0


@dataclass
class ObjectTerm(Term):
    items: List[Tuple[Term, Term]] = field(default_factory=list)
    line: int = 0


@dataclass
class SetTerm(Term):
    items: List[Term] = field(default_factory=list)
    line: int = 0


@dataclass
class Call(Term):
    """Builtin or user function call: name is a dotted path string."""

    name: str
    args: List[Term] = field(default_factory=list)
    line: int = 0


@dataclass
class Comprehension(Term):
    """Array / set / object comprehension.

    kind: "array" | "set" | "object"
    For object comprehensions `key` is set; otherwise only `head`.
    """

    kind: str
    head: Term
    body: "Body"
    key: Optional[Term] = None
    line: int = 0


@dataclass
class UnaryMinus(Term):
    operand: Term
    line: int = 0


@dataclass
class BinOp(Term):
    """Infix operator term: arithmetic, comparison, set ops.

    op in {"+", "-", "*", "/", "%", "&", "|",
           "==", "!=", "<", "<=", ">", ">="}
    """

    op: str
    lhs: Term
    rhs: Term
    line: int = 0


# ---------------------------------------------------------------------------
# Expressions (body statements)


@dataclass
class Expr(Node):
    pass


@dataclass
class TermExpr(Expr):
    """A bare term used as an expression (truthiness / definedness check)."""

    term: Term
    line: int = 0


@dataclass
class Assign(Expr):
    """`pattern := value` — declarative assignment."""

    target: Term
    value: Term
    line: int = 0


@dataclass
class Unify(Expr):
    """`a = b` — bidirectional unification."""

    lhs: Term
    rhs: Term
    line: int = 0


@dataclass
class NotExpr(Expr):
    expr: Expr
    line: int = 0


@dataclass
class SomeDecl(Expr):
    names: List[str] = field(default_factory=list)
    line: int = 0


@dataclass
class Every(Expr):
    """`every x in xs { body }` — not used by the reference library but kept
    for forward compatibility; the parser accepts it."""

    key: Optional[str]
    value: str
    domain: Term
    body: "Body" = None  # type: ignore[assignment]
    line: int = 0


@dataclass
class WithModifier(Node):
    target: Term  # a Ref like input / data.inventory
    value: Term
    line: int = 0


@dataclass
class WithExpr(Expr):
    """expr with target as value [with ...]."""

    expr: Expr
    mods: List[WithModifier] = field(default_factory=list)
    line: int = 0


Body = List[Expr]


# ---------------------------------------------------------------------------
# Rules / modules


@dataclass
class RuleHead(Node):
    name: str
    # function arguments (None if not a function)
    args: Optional[List[Term]] = None
    # partial rule key (the term inside [...]); None for complete rules
    key: Optional[Term] = None
    # rule value (term after =); None means implicit `true`
    value: Optional[Term] = None
    # kind: "complete" | "set" | "object" | "func"
    kind: str = "complete"
    line: int = 0


@dataclass
class Rule(Node):
    head: RuleHead
    body: Body = field(default_factory=list)
    is_default: bool = False
    else_rule: Optional["Rule"] = None
    line: int = 0


@dataclass
class Import(Node):
    path: List[str] = field(default_factory=list)
    alias: Optional[str] = None
    line: int = 0


@dataclass
class Module(Node):
    package: List[str] = field(default_factory=list)
    imports: List[Import] = field(default_factory=list)
    rules: List[Rule] = field(default_factory=list)
    line: int = 0

    @property
    def package_path(self) -> str:
        return ".".join(self.package)
