"""Body safety analysis: OPA-style expression reordering.

OPA's compiler reorders rule-body literals so that every variable is bound
before it is consumed (the reference relies on this, e.g.
`selectors := [s | s = concat(":", [key, val]); val = obj.spec.selector[key]]`
in /root/reference/library/general/uniqueserviceselector/template.yaml where
`key`/`val` are textually used before being bound). This module implements
the equivalent greedy topological reorder, shared by the interpreter and the
TPU compiler's lowering pass.
"""

from __future__ import annotations

from typing import List, Set, Tuple

from . import ast as A


def all_vars(node, known: Set[str]) -> Set[str]:
    """Every variable name mentioned in a term/expr, excluding known
    (rule/document/import) names and wildcards."""
    out: Set[str] = set()
    _collect_vars(node, known, out)
    return out


def _collect_vars(node, known: Set[str], out: Set[str]) -> None:
    if isinstance(node, A.Var):
        if node.name not in known:
            out.add(node.name)
    elif isinstance(node, A.Wildcard) or isinstance(node, A.Scalar):
        pass
    elif isinstance(node, A.Ref):
        _collect_vars(node.head, known, out)
        for op in node.ops:
            _collect_vars(op, known, out)
    elif isinstance(node, A.Call):
        for a in node.args:
            _collect_vars(a, known, out)
    elif isinstance(node, A.BinOp):
        _collect_vars(node.lhs, known, out)
        _collect_vars(node.rhs, known, out)
    elif isinstance(node, A.UnaryMinus):
        _collect_vars(node.operand, known, out)
    elif isinstance(node, A.ArrayTerm) or isinstance(node, A.SetTerm):
        for x in node.items:
            _collect_vars(x, known, out)
    elif isinstance(node, A.ObjectTerm):
        for k, v in node.items:
            _collect_vars(k, known, out)
            _collect_vars(v, known, out)
    elif isinstance(node, A.Comprehension):
        # comprehension-local vars stay local; only propagate outward needs
        out |= comprehension_needed(node, known)
    elif isinstance(node, A.TermExpr):
        _collect_vars(node.term, known, out)
    elif isinstance(node, A.Assign):
        _collect_vars(node.target, known, out)
        _collect_vars(node.value, known, out)
    elif isinstance(node, A.Unify):
        _collect_vars(node.lhs, known, out)
        _collect_vars(node.rhs, known, out)
    elif isinstance(node, A.NotExpr):
        _collect_vars(node.expr, known, out)
    elif isinstance(node, A.SomeDecl):
        out |= set(node.names)
    elif isinstance(node, A.WithExpr):
        _collect_vars(node.expr, known, out)
        for m in node.mods:
            _collect_vars(m.value, known, out)
    return


def needed_value(term: A.Term, known: Set[str]) -> Set[str]:
    """Vars that must be bound before `term` is evaluated in value position.

    Bracket operands of refs may be bound by enumeration and object/array
    patterns in ref-operand position may bind by set-membership unification,
    so those contribute nothing.
    """
    if isinstance(term, (A.Scalar, A.Wildcard)):
        return set()
    if isinstance(term, A.Var):
        return {term.name} if term.name not in known else set()
    if isinstance(term, A.Ref):
        out = needed_value(term.head, known)
        for op in term.ops:
            out |= needed_pattern(op, known)
        return out
    if isinstance(term, A.Call):
        out: Set[str] = set()
        for a in term.args:
            out |= needed_value(a, known)
        return out
    if isinstance(term, A.BinOp):
        return needed_value(term.lhs, known) | needed_value(term.rhs, known)
    if isinstance(term, A.UnaryMinus):
        return needed_value(term.operand, known)
    if isinstance(term, (A.ArrayTerm, A.SetTerm)):
        out = set()
        for x in term.items:
            out |= needed_value(x, known)
        return out
    if isinstance(term, A.ObjectTerm):
        out = set()
        for k, v in term.items:
            out |= needed_value(k, known) | needed_value(v, known)
        return out
    if isinstance(term, A.Comprehension):
        return comprehension_needed(term, known)
    return set()


def needed_pattern(term: A.Term, known: Set[str]) -> Set[str]:
    """Vars needed when `term` appears in a bindable (pattern) position."""
    if isinstance(term, (A.Var, A.Wildcard, A.Scalar)):
        return set()
    if isinstance(term, A.ArrayTerm):
        out: Set[str] = set()
        for x in term.items:
            out |= needed_pattern(x, known)
        return out
    if isinstance(term, A.ObjectTerm):
        out = set()
        for k, v in term.items:
            out |= needed_value(k, known)
            out |= needed_pattern(v, known)
        return out
    return needed_value(term, known)


def comprehension_needed(term: A.Comprehension, known: Set[str]) -> Set[str]:
    """Outer vars a comprehension requires: referenced vars that can never be
    bound by its own body (fixpoint over schedulability)."""
    referenced: Set[str] = set()
    for e in term.body:
        _collect_vars(e, known, referenced)
    head_vars: Set[str] = set()
    _collect_vars(term.head, known, head_vars)
    if term.key is not None:
        _collect_vars(term.key, known, head_vars)
    referenced_all = referenced | head_vars

    bound: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for e in term.body:
            if can_schedule(e, bound, known):
                ev = all_vars(e, known)
                if not ev <= bound:
                    bound |= ev
                    changed = True
    return referenced_all - bound


def expr_needed(expr: A.Expr, known: Set[str]) -> Set[str]:
    if isinstance(expr, A.TermExpr):
        return needed_value(expr.term, known)
    if isinstance(expr, A.Assign):
        return needed_value(expr.value, known) | needed_pattern(expr.target, known)
    if isinstance(expr, A.NotExpr):
        # negated expressions must be ground
        return all_vars(expr.expr, known)
    if isinstance(expr, A.SomeDecl):
        return set()
    if isinstance(expr, A.WithExpr):
        out = expr_needed(expr.expr, known)
        for m in expr.mods:
            out |= needed_value(m.value, known)
        return out
    if isinstance(expr, A.Unify):
        # handled specially in can_schedule
        return needed_value(expr.lhs, known) | needed_value(expr.rhs, known)
    return set()


def can_schedule(expr: A.Expr, bound: Set[str], known: Set[str]) -> bool:
    if isinstance(expr, A.Unify):
        nl = needed_value(expr.lhs, known)
        nr = needed_value(expr.rhs, known)
        return nl <= bound or nr <= bound
    if isinstance(expr, A.WithExpr):
        mods_ok = all(needed_value(m.value, known) <= bound for m in expr.mods)
        return mods_ok and can_schedule(expr.expr, bound, known)
    return expr_needed(expr, known) <= bound


def reorder_body(
    body: List[A.Expr], bound0: Set[str], known: Set[str]
) -> List[A.Expr]:
    """Greedy safety reorder; stable for already-safe bodies. If no
    expression is schedulable (genuinely unsafe body), remaining expressions
    are appended in order and the evaluator reports the unsafe var."""
    remaining = list(body)
    ordered: List[A.Expr] = []
    bound = set(bound0)
    while remaining:
        for idx, e in enumerate(remaining):
            if can_schedule(e, bound, known):
                break
        else:
            idx = 0
        e = remaining.pop(idx)
        ordered.append(e)
        bound |= all_vars(e, known)
    return ordered


def module_known(mod: A.Module, rule_names: Set[str]) -> Set[str]:
    known = set(rule_names) | {"input", "data"}
    for imp in mod.imports:
        known.add(imp.alias or imp.path[-1])
    return known
