"""Offline template + mutator + provider linting CLI.

    python -m gatekeeper_tpu.analysis deploy/ [more paths...]
        [--json] [--baseline FILE] [--write-baseline FILE] [--strict]
    python -m gatekeeper_tpu.analysis mutators deploy/ [more paths...]
        [--json] [--baseline FILE] [--write-baseline FILE]
    python -m gatekeeper_tpu.analysis providers deploy/ [more paths...]
        [--json] [--baseline FILE] [--write-baseline FILE]

Default mode scans the given files/directories for ConstraintTemplate
YAML documents (directories recurse over *.yaml / *.yml; explicit
*.rego file args are analyzed as a bare template entry module), runs
the static vectorizability analyzer on each, and prints one report per
template.

`mutators` mode scans for Assign/AssignMetadata/ModifySet documents,
reports location-path parse errors and cross-mutator schema conflicts
with stable GK-M0xx codes (docs/mutation.md), and compares against a
baseline manifest ({"mutators": {id: [codes]}}) so CI pins the shipped
example mutators clean.

`providers` mode scans for externaldata.gatekeeper.sh Provider
documents and reports spec problems with stable GK-P0xx codes
(docs/externaldata.md): unreachable URL schemes, missing timeouts,
fail-open providers with no cache to fall back on. Baseline manifest:
{"providers": {id: [codes]}}.

Exit status:
  0  every template analyzed, no INVALID verdicts, no baseline
     regressions
  1  an INVALID template, a baseline regression (a template whose
     recorded verdict was better than the current one), or --strict
     with any template below VECTORIZED
  2  usage / no templates found

`--baseline FILE` compares against a checked-in manifest (JSON:
{"templates": {kind: verdict}}) so CI pins the library's vectorization
coverage; `--write-baseline FILE` (re)generates it. New templates (not
in the manifest) are allowed; a verdict *improvement* is reported but
passes — refresh the baseline to lock it in.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, Iterable, List, Tuple

from .analyzer import analyze_modules, analyze_template
from .report import VERDICT_ORDER, VectorizabilityReport


def _iter_template_docs(path: str) -> Iterable[Tuple[str, Dict[str, Any]]]:
    import yaml

    with open(path) as f:
        try:
            docs = list(yaml.safe_load_all(f))
        except yaml.YAMLError as e:
            raise SystemExit(f"error: {path}: YAML parse error: {e}")
    for doc in docs:
        if isinstance(doc, dict) and doc.get("kind") == "ConstraintTemplate":
            yield path, doc


def collect_templates(
    paths: List[str],
) -> List[Tuple[str, Any]]:
    """-> [(source path, template dict | rego source str)]."""
    out: List[Tuple[str, Any]] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for fn in sorted(files):
                    if fn.endswith((".yaml", ".yml")):
                        out.extend(
                            _iter_template_docs(os.path.join(root, fn))
                        )
        elif p.endswith((".yaml", ".yml")):
            out.extend(_iter_template_docs(p))
        elif p.endswith(".rego"):
            with open(p) as f:
                out.append((p, f.read()))
        else:
            raise SystemExit(f"error: unsupported path {p!r}")
    return out


def _analyze_one(source: str, obj: Any) -> VectorizabilityReport:
    if isinstance(obj, str):  # bare .rego module
        from ..constraint.errors import InvalidTemplateError
        from ..constraint.regocompile import parse_template_module
        from .report import INVALID

        kind = os.path.splitext(os.path.basename(source))[0]
        try:
            module = parse_template_module(obj)
        except InvalidTemplateError as e:
            rep = VectorizabilityReport(kind=kind)
            rep.add("GK-V008", str(e), severity=INVALID)
            return rep
        return analyze_modules(kind, [module])
    return analyze_template(obj)


def _worse(a: str, b: str) -> bool:
    return VERDICT_ORDER.index(a) > VERDICT_ORDER.index(b)


def _iter_mutator_docs(path: str):
    import yaml

    from ..mutation.lint import is_mutator_doc

    with open(path) as f:
        try:
            docs = list(yaml.safe_load_all(f))
        except yaml.YAMLError as e:
            raise SystemExit(f"error: {path}: YAML parse error: {e}")
    for doc in docs:
        if is_mutator_doc(doc):
            yield path, doc


def collect_mutators(paths: List[str]) -> List[Tuple[str, Dict[str, Any]]]:
    out: List[Tuple[str, Dict[str, Any]]] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for fn in sorted(files):
                    if fn.endswith((".yaml", ".yml")):
                        out.extend(
                            _iter_mutator_docs(os.path.join(root, fn))
                        )
        elif p.endswith((".yaml", ".yml")):
            out.extend(_iter_mutator_docs(p))
        else:
            raise SystemExit(f"error: unsupported path {p!r}")
    return out


def run_mutators(argv: List[str]) -> int:
    """`mutators` mode: GK-M0xx lint + baseline enforcement."""
    from ..mutation.lint import lint_mutators

    ap = argparse.ArgumentParser(
        prog="python -m gatekeeper_tpu.analysis mutators",
        description="Offline mutator linter (path grammar + conflicts)",
    )
    ap.add_argument("paths", nargs="+", help="mutator YAML files or dirs")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--baseline", help="code manifest to compare against")
    ap.add_argument(
        "--write-baseline", help="write the current codes to FILE"
    )
    args = ap.parse_args(argv)

    entries = collect_mutators(args.paths)
    if not entries:
        print("no mutators found", file=sys.stderr)
        return 2

    lints = lint_mutators(entries)

    failures: List[str] = []
    baseline: Dict[str, List[str]] = {}
    if args.baseline:
        with open(args.baseline) as f:
            baseline = (json.load(f) or {}).get("mutators", {})
        for lint in lints:
            want = baseline.get(lint.id)
            if want is None:
                continue  # new mutator: allowed
            new_codes = sorted(set(lint.codes) - set(want))
            if new_codes:
                failures.append(
                    f"{lint.id}: new diagnostics vs baseline: "
                    f"{', '.join(new_codes)}"
                )
    else:
        # no baseline: any diagnostic is a failure (lint mode)
        for lint in lints:
            if not lint.ok:
                failures.append(lint.render())

    if args.write_baseline:
        manifest = {
            "mutators": {
                lint.id: sorted(lint.codes) for lint in lints
            }
        }
        with open(args.write_baseline, "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
            f.write("\n")

    if args.json:
        print(
            json.dumps(
                {
                    "mutators": [lint.to_dict() for lint in lints],
                    "failures": failures,
                },
                indent=2,
            )
        )
    else:
        for lint in lints:
            print(f"[{lint.source}] {lint.render()}")
        if failures:
            print("\nFAIL:", file=sys.stderr)
            for f_ in failures:
                print(f"  {f_}", file=sys.stderr)
        else:
            n_ok = sum(1 for lint in lints if lint.ok)
            print(
                f"\nOK: {len(lints)} mutator(s): clean={n_ok} "
                f"flagged={len(lints) - n_ok}"
            )
    return 1 if failures else 0


def _iter_provider_docs(path: str):
    import yaml

    from ..externaldata import is_provider_doc

    with open(path) as f:
        try:
            docs = list(yaml.safe_load_all(f))
        except yaml.YAMLError as e:
            raise SystemExit(f"error: {path}: YAML parse error: {e}")
    for doc in docs:
        if is_provider_doc(doc):
            yield path, doc


def collect_providers(paths: List[str]) -> List[Tuple[str, Dict[str, Any]]]:
    out: List[Tuple[str, Dict[str, Any]]] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for fn in sorted(files):
                    if fn.endswith((".yaml", ".yml")):
                        out.extend(
                            _iter_provider_docs(os.path.join(root, fn))
                        )
        elif p.endswith((".yaml", ".yml")):
            out.extend(_iter_provider_docs(p))
        else:
            raise SystemExit(f"error: unsupported path {p!r}")
    return out


def run_providers(argv: List[str]) -> int:
    """`providers` mode: GK-P0xx lint + baseline enforcement
    (mirrors the `mutators` mode contract)."""
    from ..externaldata.lint import lint_providers

    ap = argparse.ArgumentParser(
        prog="python -m gatekeeper_tpu.analysis providers",
        description=(
            "Offline external-data Provider linter (spec + failure "
            "posture)"
        ),
    )
    ap.add_argument("paths", nargs="+", help="provider YAML files or dirs")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--baseline", help="code manifest to compare against")
    ap.add_argument(
        "--write-baseline", help="write the current codes to FILE"
    )
    args = ap.parse_args(argv)

    entries = collect_providers(args.paths)
    if not entries:
        print("no Providers found", file=sys.stderr)
        return 2

    lints = lint_providers(entries)

    failures: List[str] = []
    if args.baseline:
        with open(args.baseline) as f:
            baseline = (json.load(f) or {}).get("providers", {})
        for lint in lints:
            want = baseline.get(lint.id)
            if want is None:
                continue  # new provider: allowed
            new_codes = sorted(set(lint.codes) - set(want))
            if new_codes:
                failures.append(
                    f"{lint.id}: new diagnostics vs baseline: "
                    f"{', '.join(new_codes)}"
                )
    else:
        for lint in lints:
            if not lint.ok:
                failures.append(lint.render())

    if args.write_baseline:
        manifest = {
            "providers": {
                lint.id: sorted(lint.codes) for lint in lints
            }
        }
        with open(args.write_baseline, "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
            f.write("\n")

    if args.json:
        print(
            json.dumps(
                {
                    "providers": [lint.to_dict() for lint in lints],
                    "failures": failures,
                },
                indent=2,
            )
        )
    else:
        for lint in lints:
            print(f"[{lint.source}] {lint.render()}")
        if failures:
            print("\nFAIL:", file=sys.stderr)
            for f_ in failures:
                print(f"  {f_}", file=sys.stderr)
        else:
            n_ok = sum(1 for lint in lints if lint.ok)
            print(
                f"\nOK: {len(lints)} provider(s): clean={n_ok} "
                f"flagged={len(lints) - n_ok}"
            )
    return 1 if failures else 0


def run(argv: List[str]) -> int:
    if argv and argv[0] == "mutators":
        return run_mutators(argv[1:])
    if argv and argv[0] == "providers":
        return run_providers(argv[1:])
    ap = argparse.ArgumentParser(
        prog="python -m gatekeeper_tpu.analysis",
        description="Static vectorizability linter for ConstraintTemplates",
    )
    ap.add_argument("paths", nargs="+", help="template YAML files or dirs")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--baseline", help="verdict manifest to compare against")
    ap.add_argument(
        "--write-baseline", help="write the current verdicts to FILE"
    )
    ap.add_argument(
        "--strict",
        action="store_true",
        help="fail on any verdict below VECTORIZED",
    )
    args = ap.parse_args(argv)

    entries = collect_templates(args.paths)
    if not entries:
        print("no ConstraintTemplates found", file=sys.stderr)
        return 2

    reports: List[Tuple[str, VectorizabilityReport]] = [
        (src, _analyze_one(src, obj)) for src, obj in entries
    ]

    failures: List[str] = []
    for _src, rep in reports:
        if rep.verdict == "INVALID":
            failures.append(f"{rep.kind}: INVALID")
        elif args.strict and rep.verdict != "VECTORIZED":
            failures.append(f"{rep.kind}: {rep.verdict} (strict)")

    baseline: Dict[str, str] = {}
    if args.baseline:
        with open(args.baseline) as f:
            baseline = (json.load(f) or {}).get("templates", {})
        for _src, rep in reports:
            want = baseline.get(rep.kind)
            if want is not None and _worse(rep.verdict, want):
                failures.append(
                    f"{rep.kind}: regressed {want} -> {rep.verdict}"
                )

    if args.write_baseline:
        manifest = {
            "templates": {
                rep.kind: rep.verdict for _src, rep in reports
            }
        }
        with open(args.write_baseline, "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
            f.write("\n")

    if args.json:
        print(
            json.dumps(
                {
                    "reports": [
                        dict(rep.to_dict(), source=src)
                        for src, rep in reports
                    ],
                    "failures": failures,
                },
                indent=2,
            )
        )
    else:
        for src, rep in reports:
            print(f"[{src}] {rep.render()}")
        if failures:
            print("\nFAIL:", file=sys.stderr)
            for f_ in failures:
                print(f"  {f_}", file=sys.stderr)
        else:
            counts: Dict[str, int] = {}
            for _src, rep in reports:
                counts[rep.verdict] = counts.get(rep.verdict, 0) + 1
            summary = ", ".join(
                f"{v}={counts[v]}" for v in VERDICT_ORDER if v in counts
            )
            print(f"\nOK: {len(reports)} template(s): {summary}")
    return 1 if failures else 0


def main() -> None:
    raise SystemExit(run(sys.argv[1:]))
