"""Offline template + mutator + provider + corpus linting CLI.

    python -m gatekeeper_tpu.analysis deploy/ [more paths...]
        [--json] [--baseline FILE] [--write-baseline FILE] [--strict]
    python -m gatekeeper_tpu.analysis mutators deploy/ [more paths...]
        [--json] [--baseline FILE] [--write-baseline FILE]
    python -m gatekeeper_tpu.analysis providers deploy/ [more paths...]
        [--json] [--baseline FILE] [--write-baseline FILE]
    python -m gatekeeper_tpu.analysis corpus deploy/ [more paths...]
        [--json] [--baseline FILE] [--write-baseline FILE]
    python -m gatekeeper_tpu.analysis ir deploy/ [more paths...]
        [--json] [--baseline FILE] [--write-baseline FILE]
    python -m gatekeeper_tpu.analysis canary deploy/ [more paths...]
        [--json] [--baseline FILE] [--write-baseline FILE]
    python -m gatekeeper_tpu.analysis all [deploy/policies]

Default mode scans the given files/directories for ConstraintTemplate
YAML documents (directories recurse over *.yaml / *.yml; explicit
*.rego file args are analyzed as a bare template entry module), runs
the static vectorizability analyzer on each, and prints one report per
template.

`mutators` mode scans for Assign/AssignMetadata/ModifySet documents,
reports location-path parse errors and cross-mutator schema conflicts
with stable GK-M0xx codes (docs/mutation.md), and compares against a
baseline manifest ({"mutators": {id: [codes]}}) so CI pins the shipped
example mutators clean.

`providers` mode scans for externaldata.gatekeeper.sh Provider
documents and reports spec problems with stable GK-P0xx codes
(docs/externaldata.md): unreachable URL schemes, missing timeouts,
fail-open providers with no cache to fall back on. Baseline manifest:
{"providers": {id: [codes]}}.

`corpus` mode runs the whole-corpus cross-plane pass (GK-C0xx,
docs/analysis.md §Corpus analysis) over every template, constraint,
mutator and Provider found under the given paths together: missing
providers, orphan constraints, parameter/schema mismatches, dead and
shadowed matches, mutate↔validate admission fights. Baseline
manifest: {"corpus": {subject: [codes]}}.

`ir` mode compiles every template and constraint into the fused
program IR and runs the program-level static analysis (GK-P01x,
docs/analysis.md §IR analysis): feature liveness (which token columns
any compiled program can observe), abstract interpretation over the
burned-in constraint parameters (always/never-firing rules, dead
parameters, no-op checks, unreachable branches), and the fused-path
taxonomy for anything routed to the interpreter. Baseline manifest:
{"ir": {subject: [codes]}}.

`canary` mode runs the verdict-integrity derivability gate (GK-I0xx,
docs/robustness.md §Verdict integrity): every ConstraintTemplate must
derive at least one synthetic canary review the host interpreter
convicts — otherwise its golden digests all pin the empty verdict and
device corruption suppressing its violations is undetectable.
External-data templates run against pinned stub provider responses,
never skipped. Baseline manifest: {"canary": {kind: [codes]}}.

`all` mode is the one-shot repo gate: templates + mutators +
providers + corpus + ir + canary over one directory (default
`deploy/policies`), each compared against its conventional checked-in
baseline when present (`analysis-baseline.json`,
`mutators-baseline.json`, `providers-baseline.json`,
`corpus-baseline.json`, `ir-baseline.json`, `canary-baseline.json` in
that directory), folded into a single exit code.

Shared contract across all subcommands (normalized in PR 15 — they
had grown ad hoc per PR):
  * `--baseline FILE` compares against a checked-in manifest; new
    subjects (absent from the manifest) are allowed; a subject gaining
    a code (or, for templates, a worse verdict) fails.
  * Without a baseline, ANY diagnostic fails (pure lint mode).
  * `--write-baseline FILE` (re)generates the manifest (sorted,
    trailing newline) regardless of pass/fail.
  * Exit status: 0 clean / baseline-clean; 1 failures; 2 usage or
    nothing found to lint (`all` only exits 2 when NO plane found
    anything).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, Iterable, List, Tuple

from .analyzer import analyze_modules, analyze_template
from .report import VERDICT_ORDER, VectorizabilityReport


def _iter_template_docs(path: str) -> Iterable[Tuple[str, Dict[str, Any]]]:
    import yaml

    with open(path) as f:
        try:
            docs = list(yaml.safe_load_all(f))
        except yaml.YAMLError as e:
            raise SystemExit(f"error: {path}: YAML parse error: {e}")
    for doc in docs:
        if isinstance(doc, dict) and doc.get("kind") == "ConstraintTemplate":
            yield path, doc


def collect_templates(
    paths: List[str],
) -> List[Tuple[str, Any]]:
    """-> [(source path, template dict | rego source str)]."""
    out: List[Tuple[str, Any]] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for fn in sorted(files):
                    if fn.endswith((".yaml", ".yml")):
                        out.extend(
                            _iter_template_docs(os.path.join(root, fn))
                        )
        elif p.endswith((".yaml", ".yml")):
            out.extend(_iter_template_docs(p))
        elif p.endswith(".rego"):
            with open(p) as f:
                out.append((p, f.read()))
        else:
            raise SystemExit(f"error: unsupported path {p!r}")
    return out


def _analyze_one(source: str, obj: Any) -> VectorizabilityReport:
    if isinstance(obj, str):  # bare .rego module
        from ..constraint.errors import InvalidTemplateError
        from ..constraint.regocompile import parse_template_module
        from .report import INVALID

        kind = os.path.splitext(os.path.basename(source))[0]
        try:
            module = parse_template_module(obj)
        except InvalidTemplateError as e:
            rep = VectorizabilityReport(kind=kind)
            rep.add("GK-V008", str(e), severity=INVALID)
            return rep
        return analyze_modules(kind, [module])
    return analyze_template(obj)


def _worse(a: str, b: str) -> bool:
    return VERDICT_ORDER.index(a) > VERDICT_ORDER.index(b)


# ---------------------------------------------------------------------------
# shared baseline/report plumbing (one contract for every code-lint mode)


def _load_code_baseline(path: str, key: str) -> Dict[str, List[str]]:
    with open(path) as f:
        return (json.load(f) or {}).get(key, {})


def _compare_code_baseline(lints, baseline: Dict[str, List[str]]
                           ) -> List[str]:
    """New-code regressions vs a manifest; new subjects are allowed."""
    failures: List[str] = []
    for lint in lints:
        want = baseline.get(lint.id)
        if want is None:
            continue  # new subject: allowed
        new_codes = sorted(set(lint.codes) - set(want))
        if new_codes:
            failures.append(
                f"{lint.id}: new diagnostics vs baseline: "
                f"{', '.join(new_codes)}"
            )
    return failures


def _lint_failures(lints) -> List[str]:
    """No-baseline mode: any diagnostic is a failure."""
    return [lint.render() for lint in lints if not lint.ok]


def _write_code_baseline(path: str, key: str, lints) -> None:
    manifest = {key: {lint.id: sorted(lint.codes) for lint in lints}}
    with open(path, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
        f.write("\n")


def _emit_code_lints(args, key: str, noun: str, lints,
                     failures: List[str]) -> None:
    if args.json:
        print(
            json.dumps(
                {
                    key: [lint.to_dict() for lint in lints],
                    "failures": failures,
                },
                indent=2,
            )
        )
        return
    for lint in lints:
        print(f"[{lint.source}] {lint.render()}")
    if failures:
        print("\nFAIL:", file=sys.stderr)
        for f_ in failures:
            print(f"  {f_}", file=sys.stderr)
    else:
        n_ok = sum(1 for lint in lints if lint.ok)
        print(
            f"\nOK: {len(lints)} {noun}(s): clean={n_ok} "
            f"flagged={len(lints) - n_ok}"
        )


def _run_code_lints(args, key: str, noun: str, lints) -> int:
    """The shared tail of every code-lint subcommand: baseline compare
    (or pure lint), optional manifest write, report, exit code."""
    if args.baseline:
        failures = _compare_code_baseline(
            lints, _load_code_baseline(args.baseline, key)
        )
    else:
        failures = _lint_failures(lints)
    if args.write_baseline:
        _write_code_baseline(args.write_baseline, key, lints)
    _emit_code_lints(args, key, noun, lints, failures)
    return 1 if failures else 0


def _iter_mutator_docs(path: str):
    import yaml

    from ..mutation.lint import is_mutator_doc

    with open(path) as f:
        try:
            docs = list(yaml.safe_load_all(f))
        except yaml.YAMLError as e:
            raise SystemExit(f"error: {path}: YAML parse error: {e}")
    for doc in docs:
        if is_mutator_doc(doc):
            yield path, doc


def collect_mutators(paths: List[str]) -> List[Tuple[str, Dict[str, Any]]]:
    out: List[Tuple[str, Dict[str, Any]]] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for fn in sorted(files):
                    if fn.endswith((".yaml", ".yml")):
                        out.extend(
                            _iter_mutator_docs(os.path.join(root, fn))
                        )
        elif p.endswith((".yaml", ".yml")):
            out.extend(_iter_mutator_docs(p))
        else:
            raise SystemExit(f"error: unsupported path {p!r}")
    return out


def run_mutators(argv: List[str]) -> int:
    """`mutators` mode: GK-M0xx lint + baseline enforcement."""
    from ..mutation.lint import lint_mutators

    ap = argparse.ArgumentParser(
        prog="python -m gatekeeper_tpu.analysis mutators",
        description="Offline mutator linter (path grammar + conflicts)",
    )
    ap.add_argument("paths", nargs="+", help="mutator YAML files or dirs")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--baseline", help="code manifest to compare against")
    ap.add_argument(
        "--write-baseline", help="write the current codes to FILE"
    )
    args = ap.parse_args(argv)

    entries = collect_mutators(args.paths)
    if not entries:
        print("no mutators found", file=sys.stderr)
        return 2

    return _run_code_lints(args, "mutators", "mutator",
                           lint_mutators(entries))


def _iter_provider_docs(path: str):
    import yaml

    from ..externaldata import is_provider_doc

    with open(path) as f:
        try:
            docs = list(yaml.safe_load_all(f))
        except yaml.YAMLError as e:
            raise SystemExit(f"error: {path}: YAML parse error: {e}")
    for doc in docs:
        if is_provider_doc(doc):
            yield path, doc


def collect_providers(paths: List[str]) -> List[Tuple[str, Dict[str, Any]]]:
    out: List[Tuple[str, Dict[str, Any]]] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for fn in sorted(files):
                    if fn.endswith((".yaml", ".yml")):
                        out.extend(
                            _iter_provider_docs(os.path.join(root, fn))
                        )
        elif p.endswith((".yaml", ".yml")):
            out.extend(_iter_provider_docs(p))
        else:
            raise SystemExit(f"error: unsupported path {p!r}")
    return out


def run_providers(argv: List[str]) -> int:
    """`providers` mode: GK-P0xx lint + baseline enforcement
    (mirrors the `mutators` mode contract)."""
    from ..externaldata.lint import lint_providers

    ap = argparse.ArgumentParser(
        prog="python -m gatekeeper_tpu.analysis providers",
        description=(
            "Offline external-data Provider linter (spec + failure "
            "posture)"
        ),
    )
    ap.add_argument("paths", nargs="+", help="provider YAML files or dirs")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--baseline", help="code manifest to compare against")
    ap.add_argument(
        "--write-baseline", help="write the current codes to FILE"
    )
    args = ap.parse_args(argv)

    entries = collect_providers(args.paths)
    if not entries:
        print("no Providers found", file=sys.stderr)
        return 2

    return _run_code_lints(args, "providers", "provider",
                           lint_providers(entries))


def _iter_constraint_docs(path: str):
    import yaml

    from ..constraint.templates import CONSTRAINT_GROUP

    with open(path) as f:
        try:
            docs = list(yaml.safe_load_all(f))
        except yaml.YAMLError as e:
            raise SystemExit(f"error: {path}: YAML parse error: {e}")
    for doc in docs:
        if isinstance(doc, dict) and str(
            doc.get("apiVersion", "")
        ).partition("/")[0] == CONSTRAINT_GROUP:
            yield path, doc


def collect_constraints(paths: List[str]) -> List[Tuple[str, Dict[str, Any]]]:
    out: List[Tuple[str, Dict[str, Any]]] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for fn in sorted(files):
                    if fn.endswith((".yaml", ".yml")):
                        out.extend(
                            _iter_constraint_docs(os.path.join(root, fn))
                        )
        elif p.endswith((".yaml", ".yml")):
            out.extend(_iter_constraint_docs(p))
        else:
            raise SystemExit(f"error: unsupported path {p!r}")
    return out


def run_corpus(argv: List[str]) -> int:
    """`corpus` mode: whole-corpus GK-C0xx pass + baseline
    enforcement (docs/analysis.md §Corpus analysis)."""
    from .corpus import corpus_from_docs

    ap = argparse.ArgumentParser(
        prog="python -m gatekeeper_tpu.analysis corpus",
        description=(
            "Whole-corpus cross-plane linter (templates + constraints "
            "+ mutators + Providers together)"
        ),
    )
    ap.add_argument("paths", nargs="+", help="policy YAML files or dirs")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--baseline", help="code manifest to compare against")
    ap.add_argument(
        "--write-baseline", help="write the current codes to FILE"
    )
    args = ap.parse_args(argv)

    template_docs = [
        (src, doc)
        for src, doc in collect_templates(args.paths)
        if isinstance(doc, dict)  # bare .rego has no corpus identity
    ]
    constraint_docs = collect_constraints(args.paths)
    mutator_docs = collect_mutators(args.paths)
    provider_docs = collect_providers(args.paths)
    if not (template_docs or constraint_docs or mutator_docs
            or provider_docs):
        print("no policy documents found", file=sys.stderr)
        return 2

    report = corpus_from_docs(
        template_docs, constraint_docs, mutator_docs, provider_docs
    )
    # per-subject lints ride the shared baseline tail; the corpus-level
    # rollup (dead/prunable/shadowed) prints alongside
    flagged = [lint for lint in report.lints]
    rc = _run_code_lints(args, "corpus", "subject", flagged)
    if not args.json:
        print(
            f"corpus: dead={len(report.dead_keys)} "
            f"prunable={len(report.prunable_keys)} "
            f"shadowed={len(report.shadowed)}"
        )
    return rc


def run_ir(argv: List[str]) -> int:
    """`ir` mode: compile every template + constraint found under the
    given paths into the fused program IR and run the program-level
    static analysis (GK-P01x, docs/analysis.md §IR analysis): abstract
    interpretation over burned-in parameters, feature liveness, and
    the fused-path taxonomy."""
    from .ir import ir_from_docs

    ap = argparse.ArgumentParser(
        prog="python -m gatekeeper_tpu.analysis ir",
        description=(
            "Program-IR static analysis (liveness + abstract "
            "interpretation over compiled templates/constraints)"
        ),
    )
    ap.add_argument("paths", nargs="+", help="policy YAML files or dirs")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--baseline", help="code manifest to compare against")
    ap.add_argument(
        "--write-baseline", help="write the current codes to FILE"
    )
    args = ap.parse_args(argv)

    template_docs = [
        doc
        for _src, doc in collect_templates(args.paths)
        if isinstance(doc, dict)  # bare .rego has no IR identity
    ]
    constraint_docs = [doc for _src, doc in collect_constraints(args.paths)]
    if not template_docs:
        print("no ConstraintTemplates found", file=sys.stderr)
        return 2

    report = ir_from_docs(template_docs + constraint_docs)
    rc = _run_code_lints(args, "ir", "subject", report.lints)
    if not args.json:
        live = report.liveness or {}
        print(
            f"ir: programs={live.get('programs', 0)} "
            f"maskable={live.get('maskable', 0)} "
            f"keep_all={live.get('keep_all')} "
            f"live_patterns={live.get('live_patterns')}"
            f"/{live.get('patterns_total')} "
            f"certificates={len(report.certificates)}"
        )
    return rc


def run_canary(argv: List[str]) -> int:
    """`canary` mode: the verdict-integrity derivability gate
    (GK-I0xx, docs/robustness.md §Verdict integrity). Every template
    must derive at least one synthetic canary the host interpreter
    convicts; external-data templates get pinned stub provider
    responses — they are never silently skipped."""
    from .canarygate import canary_lints

    ap = argparse.ArgumentParser(
        prog="python -m gatekeeper_tpu.analysis canary",
        description=(
            "Verdict-integrity canary derivability gate (every "
            "template must convict a synthetic canary review)"
        ),
    )
    ap.add_argument("paths", nargs="+", help="policy YAML files or dirs")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--baseline", help="code manifest to compare against")
    ap.add_argument(
        "--write-baseline", help="write the current codes to FILE"
    )
    args = ap.parse_args(argv)

    template_docs = [
        (src, doc)
        for src, doc in collect_templates(args.paths)
        if isinstance(doc, dict)  # bare .rego carries no constraints
    ]
    if not template_docs:
        print("no ConstraintTemplates found", file=sys.stderr)
        return 2

    lints = canary_lints(
        template_docs,
        collect_constraints(args.paths),
        collect_providers(args.paths),
    )
    return _run_code_lints(args, "canary", "template", lints)


def run_all(argv: List[str]) -> int:
    """`all` mode: the one-shot repo gate. Runs templates + mutators +
    providers + corpus over one directory against their conventional
    baselines (when present) and folds the exit codes: any plane's
    failure fails the gate; a plane with nothing to lint is skipped
    (exit 2 only when NOTHING was found at all)."""
    ap = argparse.ArgumentParser(
        prog="python -m gatekeeper_tpu.analysis all",
        description="Run every analysis plane against a policy tree",
    )
    ap.add_argument(
        "path", nargs="?", default="deploy/policies",
        help="policy tree (default deploy/policies)",
    )
    ap.add_argument("--json", action="store_true", help="JSON output")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.path):
        print(f"error: not a directory: {args.path!r}", file=sys.stderr)
        return 2

    planes = [
        ("templates", run, "analysis-baseline.json"),
        ("mutators", run_mutators, "mutators-baseline.json"),
        ("providers", run_providers, "providers-baseline.json"),
        ("corpus", run_corpus, "corpus-baseline.json"),
        ("ir", run_ir, "ir-baseline.json"),
        ("canary", run_canary, "canary-baseline.json"),
    ]
    results: Dict[str, int] = {}
    for name, fn, baseline_name in planes:
        sub_argv = [args.path]
        baseline = os.path.join(args.path, baseline_name)
        if os.path.exists(baseline):
            sub_argv += ["--baseline", baseline]
        if args.json:
            sub_argv.append("--json")
        print(f"== {name} ==")
        results[name] = fn(sub_argv)

    ran = {n: rc for n, rc in results.items() if rc != 2}
    print("\n== gate ==")
    for name, _fn, _b in planes:
        rc = results[name]
        state = "SKIP (nothing found)" if rc == 2 else (
            "OK" if rc == 0 else "FAIL"
        )
        print(f"  {name}: {state}")
    if not ran:
        print("nothing to lint", file=sys.stderr)
        return 2
    return 1 if any(rc == 1 for rc in ran.values()) else 0


def run(argv: List[str]) -> int:
    if argv and argv[0] == "mutators":
        return run_mutators(argv[1:])
    if argv and argv[0] == "providers":
        return run_providers(argv[1:])
    if argv and argv[0] == "corpus":
        return run_corpus(argv[1:])
    if argv and argv[0] == "ir":
        return run_ir(argv[1:])
    if argv and argv[0] == "canary":
        return run_canary(argv[1:])
    if argv and argv[0] == "all":
        return run_all(argv[1:])
    ap = argparse.ArgumentParser(
        prog="python -m gatekeeper_tpu.analysis",
        description="Static vectorizability linter for ConstraintTemplates",
    )
    ap.add_argument("paths", nargs="+", help="template YAML files or dirs")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--baseline", help="verdict manifest to compare against")
    ap.add_argument(
        "--write-baseline", help="write the current verdicts to FILE"
    )
    ap.add_argument(
        "--strict",
        action="store_true",
        help="fail on any verdict below VECTORIZED",
    )
    args = ap.parse_args(argv)

    entries = collect_templates(args.paths)
    if not entries:
        print("no ConstraintTemplates found", file=sys.stderr)
        return 2

    reports: List[Tuple[str, VectorizabilityReport]] = [
        (src, _analyze_one(src, obj)) for src, obj in entries
    ]

    failures: List[str] = []
    for _src, rep in reports:
        if rep.verdict == "INVALID":
            failures.append(f"{rep.kind}: INVALID")
        elif args.strict and rep.verdict != "VECTORIZED":
            failures.append(f"{rep.kind}: {rep.verdict} (strict)")

    baseline: Dict[str, str] = {}
    if args.baseline:
        with open(args.baseline) as f:
            baseline = (json.load(f) or {}).get("templates", {})
        for _src, rep in reports:
            want = baseline.get(rep.kind)
            if want is not None and _worse(rep.verdict, want):
                failures.append(
                    f"{rep.kind}: regressed {want} -> {rep.verdict}"
                )

    if args.write_baseline:
        manifest = {
            "templates": {
                rep.kind: rep.verdict for _src, rep in reports
            }
        }
        with open(args.write_baseline, "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
            f.write("\n")

    if args.json:
        print(
            json.dumps(
                {
                    "reports": [
                        dict(rep.to_dict(), source=src)
                        for src, rep in reports
                    ],
                    "failures": failures,
                },
                indent=2,
            )
        )
    else:
        for src, rep in reports:
            print(f"[{src}] {rep.render()}")
        if failures:
            print("\nFAIL:", file=sys.stderr)
            for f_ in failures:
                print(f"  {f_}", file=sys.stderr)
        else:
            counts: Dict[str, int] = {}
            for _src, rep in reports:
                counts[rep.verdict] = counts.get(rep.verdict, 0) + 1
            summary = ", ".join(
                f"{v}={counts[v]}" for v in VERDICT_ORDER if v in counts
            )
            print(f"\nOK: {len(reports)} template(s): {summary}")
    return 1 if failures else 0


def main() -> None:
    raise SystemExit(run(sys.argv[1:]))
