"""Canary-derivability gate (docs/robustness.md §Verdict integrity).

A template that cannot derive a violating canary is invisible to the
verdict-integrity plane: every golden digest it contributes pins the
EMPTY verdict set, so a device silently suppressing that template's
violations can never trip a canary mismatch. This plane proves, for
every ConstraintTemplate in a policy tree, that
`integrity.canary.synth_reviews` derives at least one review the host
interpreter convicts — the same derivation the live plane performs per
program signature when it builds golden sidecars.

Templates that call `external_data` are NOT skipped: the gate binds an
ExternalDataSystem whose fetcher answers every key with a pinned,
deterministic response (and synthesizes a stub Provider for any
referenced-but-undeclared provider name), so the interpreter pass runs
end-to-end offline. Keys carrying a `:latest` tag — every even-indexed
canary image — answer with an error entry while everything else
resolves cleanly, so error-gated external-data templates convict the
violating canaries and pass the compliant ones. Pinning (rather than
live fetching) is what keeps the derivation deterministic, the same
property live golden sidecars rely on.

GK-I0xx codes (one lint row per template, `analysis canary` / the
`all` gate):

  * GK-I001 — no violating canary derivable (all golden digests would
    pin the empty verdict);
  * GK-I002 — template or constraint rejected at load;
  * GK-I003 — host interpreter error while deriving a golden verdict.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Tuple

from ..integrity.canary import (
    DEFAULT_K,
    result_digest,
    synth_agent_reviews,
    synth_reviews,
)

__all__ = ["CanaryLint", "PinnedStubFetcher", "canary_lints"]

K8S_TARGET = "admission.k8s.gatekeeper.sh"


class PinnedStubFetcher:
    """Deterministic offline provider responses for the gate's
    interpreter pass: no sockets, same answer on every run."""

    def fetch(self, provider, keys: List[str]
              ) -> Tuple[List[Dict[str, Any]], str]:
        items = []
        for k in keys:
            bad = ":latest" in k or "bad" in k
            items.append(
                {
                    "key": k,
                    "value": "" if bad else f"pinned:{k}",
                    "error": "integrity canary: pinned denial" if bad
                    else "",
                }
            )
        return items, ""


def _stub_provider_obj(name: str) -> Dict[str, Any]:
    """A synthesized Provider CR for a referenced-but-undeclared
    provider name. The URL is never dialed — PinnedStubFetcher answers
    first — but must still parse as reachable."""
    return {
        "apiVersion": "externaldata.gatekeeper.sh/v1alpha1",
        "kind": "Provider",
        "metadata": {"name": name},
        "spec": {"url": "http://integrity-canary.invalid", "timeout": 1},
    }


def _synth_params(doc: Dict[str, Any]) -> Dict[str, Any]:
    """Plausible violating parameters mined from a template's
    openAPIV3Schema, for templates the tree ships no constraint for: a
    required-X string array gets one key no canary carries, an
    allow-list gets one value no canary matches. Best-effort — an
    unrecognised shape synthesizes nothing for that property."""
    schema = (
        (((doc.get("spec") or {}).get("crd") or {}).get("spec") or {})
        .get("validation", {})
        .get("openAPIV3Schema", {})
    )
    props = schema.get("properties") or {}
    params: Dict[str, Any] = {}
    for name, prop in props.items():
        if not isinstance(prop, dict):
            continue
        t = prop.get("type")
        if t == "array":
            items = prop.get("items") or {}
            if items.get("type") == "object":
                entry: Dict[str, Any] = {}
                for k2, p2 in (items.get("properties") or {}).items():
                    if isinstance(p2, dict) and p2.get("type") == "string":
                        entry[k2] = (
                            "" if "regex" in k2.lower()
                            else "integrity-canary/required"
                        )
                params[name] = [entry or {"key": "integrity-canary/required"}]
            else:
                params[name] = ["integrity-canary.invalid/"]
        elif t == "string":
            params[name] = "integrity-canary"
        elif t in ("integer", "number"):
            params[name] = 1
        elif t == "boolean":
            params[name] = True
    return params


def _default_constraint(
    kind: str, doc: Dict[str, Any], agent: bool
) -> Dict[str, Any]:
    """A synthesized constraint for a template the policy tree ships
    without one — the canary set still has to derive. The admission
    target's match is omitted entirely (an absent kind selector
    defaults to wildcard, so both canary object shapes match without
    naming any target-specific vocabulary here); the agent target
    matches every tool. Parameters are schema-mined."""
    from ..constraint.templates import CONSTRAINT_API_VERSION

    spec: Dict[str, Any] = {"match": {"tools": ["*"]}} if agent else {}
    params = _synth_params(doc)
    if params:
        spec["parameters"] = params
    return {
        "apiVersion": CONSTRAINT_API_VERSION,
        "kind": kind,
        "metadata": {"name": f"integrity-canary-{kind.lower()}"},
        "spec": spec,
    }


@dataclass
class CanaryLint:
    """One template's derivability row (the shared code-lint shape:
    `id`/`codes`/`ok`/`render`/`to_dict`, so the canary plane rides the
    same baseline/report plumbing as every other subcommand)."""

    id: str
    source: str
    codes: List[str] = field(default_factory=list)
    messages: List[str] = field(default_factory=list)
    canaries: int = 0
    violating: int = 0
    external_data: bool = False
    providers: List[str] = field(default_factory=list)
    digests: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.codes

    def render(self) -> str:
        head = (
            f"{self.id}: canaries={self.canaries} "
            f"violating={self.violating}"
            + (" external_data(stubbed)" if self.external_data else "")
        )
        if self.ok:
            return f"{head} OK"
        probs = "; ".join(
            f"{c}: {m}" for c, m in zip(self.codes, self.messages)
        )
        return f"{head} {probs}"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "template": self.id,
            "source": self.source,
            "codes": list(self.codes),
            "messages": list(self.messages),
            "canaries": self.canaries,
            "violating": self.violating,
            "external_data": self.external_data,
            "providers": list(self.providers),
            "digests": list(self.digests),
        }


def canary_lints(
    template_docs: List[Tuple[str, Dict[str, Any]]],
    constraint_docs: List[Tuple[str, Dict[str, Any]]],
    provider_docs: List[Tuple[str, Dict[str, Any]]],
    k: int = DEFAULT_K,
) -> List[CanaryLint]:
    """One CanaryLint per template: load it (alone) into a numpy-mode
    client with the tree's constraints of its kind, derive the canary
    set, and replay it through the host interpreter — the golden
    derivation path. A template is clean when at least one canary
    convicts."""
    from ..constraint import Backend, K8sValidationTarget, TpuDriver
    from ..externaldata import ExternalDataSystem, ProviderError
    from .analyzer import analyze_template

    by_kind: Dict[str, List[Dict[str, Any]]] = {}
    for _src, c in constraint_docs:
        by_kind.setdefault(str(c.get("kind") or ""), []).append(c)

    lints: List[CanaryLint] = []
    for src, doc in template_docs:
        kind = str(
            (((doc.get("spec") or {}).get("crd") or {}).get("spec") or {})
            .get("names", {})
            .get("kind")
            or (doc.get("metadata") or {}).get("name")
            or src
        )
        lint = CanaryLint(id=kind, source=src)
        lints.append(lint)

        rep = analyze_template(doc)
        referenced = sorted(
            {c.provider for c in rep.external_calls if c.provider}
        )
        lint.external_data = bool(rep.external_calls)
        lint.providers = referenced

        targets = (doc.get("spec") or {}).get("targets") or []
        tgt_name = str(
            (targets[0] or {}).get("target") if targets else ""
        ) or K8S_TARGET
        agent = tgt_name == "agent.action.gatekeeper.sh"
        if agent:
            from ..agentaction import AgentActionTarget

            tgt = AgentActionTarget()
        else:
            tgt = K8sValidationTarget()

        drv = TpuDriver(use_jax=False)
        cl = Backend(drv).new_client(tgt)
        if lint.external_data:
            system = ExternalDataSystem(fetcher=PinnedStubFetcher())
            declared = set()
            for _psrc, pobj in provider_docs:
                try:
                    declared.add(system.upsert(pobj).name)
                except ProviderError:
                    continue  # the providers lint plane owns spec bugs
            for name in referenced:
                if name not in declared:
                    system.upsert(_stub_provider_obj(name))
            cl.set_external_data(system)

        try:
            cl.add_template(doc)
            cons = by_kind.get(kind) or [
                _default_constraint(kind, doc, agent)
            ]
            for c in cons:
                cl.add_constraint(c)
        except Exception as e:
            lint.codes.append("GK-I002")
            lint.messages.append(f"template/constraint rejected: {e}")
            continue

        constraints = drv._constraints(tgt_name)
        reviews = (
            synth_agent_reviews(constraints, k=k)
            if agent
            else synth_reviews(constraints, k=k)
        )
        closure = drv._interp_closure(tgt_name, constraints)
        lint.canaries = len(reviews)
        derived = True
        for review in reviews:
            try:
                results = closure(review)
            except Exception as e:
                lint.codes.append("GK-I003")
                lint.messages.append(
                    f"interpreter error deriving golden verdict: {e}"
                )
                derived = False
                break
            lint.digests.append(result_digest(results))
            if results:
                lint.violating += 1
        if derived and lint.violating == 0:
            lint.codes.append("GK-I001")
            lint.messages.append(
                "no violating canary derivable: every golden digest "
                "would pin the empty verdict set, so device corruption "
                "suppressing this template's violations is undetectable"
            )
    return lints
