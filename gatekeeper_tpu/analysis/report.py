"""Vectorizability report model: verdict lattice + stable diagnostics.

The analyzer (`analysis/analyzer.py`) classifies every ConstraintTemplate
ahead of compilation into a four-point verdict lattice ordered by how
much of the template's evaluation stays on-device:

    VECTORIZED > PARTIAL_ROWS > INTERPRETER > INVALID

  * VECTORIZED    — every construct is inside the symbolic compiler's
                    exact subset: the compiled program's counts (and,
                    where branch plans exist, renders) are exact.
  * PARTIAL_ROWS  — compiles, but only as a *screen*: some conditions
                    (inventory joins, builtins/comprehensions outside
                    the exact subset) over-approximate and the flagged
                    rows re-check on the interpreter.
  * INTERPRETER   — the template cannot compile even as a screen (the
                    construct aborts every retry of
                    `engine.programs.compile_program`); the driver must
                    route it wholesale to the interpreter.
  * INVALID       — the template is broken in a way no engine can
                    evaluate soundly (unsafe variables, bad entrypoint);
                    admission should reject it with the diagnostics.

Diagnostics carry stable `GK-Vxxx` codes so metrics, CI baselines, and
operator tooling can key on them across releases (docs/analysis.md has a
minimal Rego repro for each).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# -- verdict lattice --------------------------------------------------------

VECTORIZED = "VECTORIZED"
PARTIAL_ROWS = "PARTIAL_ROWS"
INTERPRETER = "INTERPRETER"
INVALID = "INVALID"

# descending order: index = badness (meet = max index)
VERDICT_ORDER: Tuple[str, ...] = (
    VECTORIZED,
    PARTIAL_ROWS,
    INTERPRETER,
    INVALID,
)


def verdict_meet(a: str, b: str) -> str:
    """Lattice meet: the worse of two verdicts."""
    return VERDICT_ORDER[
        max(VERDICT_ORDER.index(a), VERDICT_ORDER.index(b))
    ]


# -- diagnostic codes -------------------------------------------------------

# code -> (slug, verdict the diagnostic caps the template at)
CODES: Dict[str, Tuple[str, str]] = {
    "GK-V001": ("unsupported-builtin", PARTIAL_ROWS),
    "GK-V002": ("unbounded-comprehension", PARTIAL_ROWS),
    "GK-V003": ("cross-join-over-cap", INTERPRETER),
    "GK-V004": ("dynamic-ref-head", INTERPRETER),
    "GK-V005": ("unsafe-var", INVALID),
    "GK-V006": ("inventory-dependent", PARTIAL_ROWS),
    "GK-V007": ("unsupported-construct", INTERPRETER),
    "GK-V008": ("invalid-entrypoint", INVALID),
}

# compiler-disagreement sentinel: the analyzer predicted compilable but
# `CompileUnsupported` was raised anyway. Never produced by the analyzer
# itself — the driver emits it when the consistency assertion fires.
CODE_MISMATCH = "GK-V999"


@dataclass(frozen=True)
class Diagnostic:
    """One finding, anchored to a rule/line when known."""

    code: str
    message: str
    rule: str = ""
    line: int = 0
    severity: str = ""  # verdict cap; filled from CODES when empty

    def cap(self) -> str:
        if self.severity:
            return self.severity
        return CODES.get(self.code, ("", PARTIAL_ROWS))[1]

    @property
    def slug(self) -> str:
        return CODES.get(self.code, ("unknown", ""))[0]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "code": self.code,
            "slug": self.slug,
            "message": self.message,
            "rule": self.rule,
            "line": self.line,
        }

    def render(self) -> str:
        loc = f" rule={self.rule}" if self.rule else ""
        ln = f":{self.line}" if self.line else ""
        return f"{self.code} {self.slug}{loc}{ln}: {self.message}"


@dataclass
class VectorizabilityReport:
    """Per-template analysis outcome (one report per constraint kind)."""

    kind: str
    verdict: str = VECTORIZED
    diagnostics: List[Diagnostic] = field(default_factory=list)

    def add(
        self,
        code: str,
        message: str,
        rule: str = "",
        line: int = 0,
        severity: str = "",
    ) -> None:
        d = Diagnostic(
            code=code, message=message, rule=rule, line=line,
            severity=severity,
        )
        self.diagnostics.append(d)
        self.verdict = verdict_meet(self.verdict, d.cap())

    @property
    def compilable(self) -> bool:
        """May the driver attempt `compile_program` at all?"""
        return self.verdict in (VECTORIZED, PARTIAL_ROWS)

    @property
    def codes(self) -> List[str]:
        return sorted({d.code for d in self.diagnostics})

    def primary_code(self) -> Optional[str]:
        """The diagnostic code that set the verdict (worst cap, first
        occurrence) — the machine-readable 'why' for routing metrics."""
        worst: Optional[Diagnostic] = None
        for d in self.diagnostics:
            if worst is None or (
                VERDICT_ORDER.index(d.cap())
                > VERDICT_ORDER.index(worst.cap())
            ):
                worst = d
        return worst.code if worst is not None else None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "verdict": self.verdict,
            "codes": self.codes,
            "diagnostics": [d.to_dict() for d in self.diagnostics],
        }

    def render(self) -> str:
        lines = [f"{self.kind}: {self.verdict}"]
        for d in self.diagnostics:
            lines.append(f"  {d.render()}")
        return "\n".join(lines)
