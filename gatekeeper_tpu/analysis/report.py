"""Vectorizability report model: verdict lattice + stable diagnostics.

The analyzer (`analysis/analyzer.py`) classifies every ConstraintTemplate
ahead of compilation into a four-point verdict lattice ordered by how
much of the template's evaluation stays on-device:

    VECTORIZED > PARTIAL_ROWS > INTERPRETER > INVALID

  * VECTORIZED    — every construct is inside the symbolic compiler's
                    exact subset: the compiled program's counts (and,
                    where branch plans exist, renders) are exact.
  * PARTIAL_ROWS  — compiles, but only as a *screen*: some conditions
                    (inventory joins, builtins/comprehensions outside
                    the exact subset) over-approximate and the flagged
                    rows re-check on the interpreter.
  * INTERPRETER   — the template cannot compile even as a screen (the
                    construct aborts every retry of
                    `engine.programs.compile_program`); the driver must
                    route it wholesale to the interpreter.
  * INVALID       — the template is broken in a way no engine can
                    evaluate soundly (unsafe variables, bad entrypoint);
                    admission should reject it with the diagnostics.

Diagnostics carry stable `GK-Vxxx` codes so metrics, CI baselines, and
operator tooling can key on them across releases (docs/analysis.md has a
minimal Rego repro for each).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# -- verdict lattice --------------------------------------------------------

VECTORIZED = "VECTORIZED"
PARTIAL_ROWS = "PARTIAL_ROWS"
INTERPRETER = "INTERPRETER"
INVALID = "INVALID"

# descending order: index = badness (meet = max index)
VERDICT_ORDER: Tuple[str, ...] = (
    VECTORIZED,
    PARTIAL_ROWS,
    INTERPRETER,
    INVALID,
)


def verdict_meet(a: str, b: str) -> str:
    """Lattice meet: the worse of two verdicts."""
    return VERDICT_ORDER[
        max(VERDICT_ORDER.index(a), VERDICT_ORDER.index(b))
    ]


# -- diagnostic codes -------------------------------------------------------

# code -> (slug, verdict the diagnostic caps the template at)
CODES: Dict[str, Tuple[str, str]] = {
    "GK-V001": ("unsupported-builtin", PARTIAL_ROWS),
    "GK-V002": ("unbounded-comprehension", PARTIAL_ROWS),
    "GK-V003": ("cross-join-over-cap", INTERPRETER),
    "GK-V004": ("dynamic-ref-head", INTERPRETER),
    "GK-V005": ("unsafe-var", INVALID),
    "GK-V006": ("inventory-dependent", PARTIAL_ROWS),
    "GK-V007": ("unsupported-construct", INTERPRETER),
    "GK-V008": ("invalid-entrypoint", INVALID),
    # external_data(provider, keys): compiles as a screen whose per-row
    # bits come from the batch-prefetched response cache — fully
    # cache-hit rows stay fused, cold-miss/error rows re-check on the
    # interpreter (docs/externaldata.md)
    "GK-V009": ("external-data", PARTIAL_ROWS),
}

# compiler-disagreement sentinel: the analyzer predicted compilable but
# `CompileUnsupported` was raised anyway. Never produced by the analyzer
# itself — the driver emits it when the consistency assertion fires.
CODE_MISMATCH = "GK-V999"


@dataclass(frozen=True)
class Diagnostic:
    """One finding, anchored to a rule/line when known."""

    code: str
    message: str
    rule: str = ""
    line: int = 0
    severity: str = ""  # verdict cap; filled from CODES when empty

    def cap(self) -> str:
        if self.severity:
            return self.severity
        return CODES.get(self.code, ("", PARTIAL_ROWS))[1]

    @property
    def slug(self) -> str:
        return CODES.get(self.code, ("unknown", ""))[0]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "code": self.code,
            "slug": self.slug,
            "message": self.message,
            "rule": self.rule,
            "line": self.line,
        }

    def render(self) -> str:
        loc = f" rule={self.rule}" if self.rule else ""
        ln = f":{self.line}" if self.line else ""
        return f"{self.code} {self.slug}{loc}{ln}: {self.message}"


@dataclass
class ExternalDataCall:
    """One recorded external_data call site (GK-V009). Drives the batch
    plane: `extractable` calls (literal provider + input-derived keys
    expression) prefetch per micro-batch; `error_gated` calls (the rule
    body provably requires a non-empty response.errors) additionally
    let the fused screen skip rows whose keys are all clean cache hits.

    `keys_term`/`module` are live AST handles for the extraction
    micro-evaluation (externaldata/extract.py) — deliberately excluded
    from to_dict()."""

    provider: Optional[str] = None
    rule: str = ""
    line: int = 0
    extractable: bool = False
    error_gated: bool = False
    respvar: Optional[str] = None
    keys_term: Any = None
    module: Any = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "provider": self.provider,
            "rule": self.rule,
            "line": self.line,
            "extractable": self.extractable,
            "error_gated": self.error_gated,
        }


@dataclass
class VectorizabilityReport:
    """Per-template analysis outcome (one report per constraint kind)."""

    kind: str
    verdict: str = VECTORIZED
    diagnostics: List[Diagnostic] = field(default_factory=list)
    # external_data call sites (GK-V009); empty for ordinary templates
    external_calls: List[ExternalDataCall] = field(default_factory=list)

    def add(
        self,
        code: str,
        message: str,
        rule: str = "",
        line: int = 0,
        severity: str = "",
    ) -> None:
        d = Diagnostic(
            code=code, message=message, rule=rule, line=line,
            severity=severity,
        )
        self.diagnostics.append(d)
        self.verdict = verdict_meet(self.verdict, d.cap())

    @property
    def compilable(self) -> bool:
        """May the driver attempt `compile_program` at all?"""
        return self.verdict in (VECTORIZED, PARTIAL_ROWS)

    @property
    def codes(self) -> List[str]:
        return sorted({d.code for d in self.diagnostics})

    def extdata_mode(self) -> Optional[str]:
        """The fused-screen mode for the template's external calls:
        None  — no external_data calls, or some call is unextractable
                (no prefetch possible; coarse all-rows screen);
        "all" — every call is extractable: the batch plane prefetches,
                but the screen routes every matching row (a violation
                may fire on response *values*, so key cleanliness
                proves nothing);
        "err" — extractable AND every call is provably error-gated:
                rows whose keys are all clean cache hits can never
                violate through the external path, so the screen skips
                them — the fully-cache-hit batch stays fused."""
        if not self.external_calls:
            return None
        if not all(
            c.extractable and c.provider for c in self.external_calls
        ):
            return None
        if all(c.error_gated for c in self.external_calls):
            return "err"
        return "all"

    def external_providers(self) -> List[str]:
        return sorted(
            {c.provider for c in self.external_calls if c.provider}
        )

    def primary_code(self) -> Optional[str]:
        """The diagnostic code that set the verdict (worst cap, first
        occurrence) — the machine-readable 'why' for routing metrics."""
        worst: Optional[Diagnostic] = None
        for d in self.diagnostics:
            if worst is None or (
                VERDICT_ORDER.index(d.cap())
                > VERDICT_ORDER.index(worst.cap())
            ):
                worst = d
        return worst.code if worst is not None else None

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "kind": self.kind,
            "verdict": self.verdict,
            "codes": self.codes,
            "diagnostics": [d.to_dict() for d in self.diagnostics],
        }
        if self.external_calls:
            out["external_data"] = {
                "mode": self.extdata_mode(),
                "providers": self.external_providers(),
                "calls": [c.to_dict() for c in self.external_calls],
            }
        return out

    def render(self) -> str:
        lines = [f"{self.kind}: {self.verdict}"]
        for d in self.diagnostics:
            lines.append(f"  {d.render()}")
        return "\n".join(lines)
