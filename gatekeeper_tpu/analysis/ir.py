"""Program-IR static analysis: feature liveness, abstract
interpretation, and residual-specialization certificates.

PR 14 made the *policy set* analyzable (GK-C0xx corpus diagnostics);
this plane analyzes the compiled *programs* themselves — the Expr DAGs
`engine/symbolic.py` emits per (template, constraint-params) pair, with
the constraint's concrete `parameters` burned in as abstract constants.
Three artifacts come out of one walk:

  1. **Feature-liveness masks** — the exact set of schema-path patterns
     a program population can ever read. A token whose path matches no
     live pattern is *provably dead*: no `ESelPattern`/`ECapture` gate
     ever selects it, so the encoder may drop it before padding and the
     host-side flatten/encode cost (ROADMAP item 1's fixed per-batch
     tax) shrinks with it. Soundness rests on PAD EQUIVALENCE, proved
     per program (see `program_liveness`): dropping a dead token is
     indistinguishable from turning it into one more pad slot, and
     compiled programs are already pad-count-invariant (bucketed L/G
     padding varies batch to batch in production).

  2. **GK-P0xx diagnostics** through the same report/CLI/baseline
     machinery as the template (GK-Vxxx) and corpus (GK-Cxxx) planes:
     always-true / never-firing rules, parameters that provably cannot
     affect the verdict, interval-provable no-op checks, unreachable
     render branches, and the exact `CompileUnsupported` reason-code
     taxonomy for templates off the fused path.

  3. **Specialization certificates** — branches provably foldable under
     the current corpus (condition abstractly constant), handed to the
     planner as the foundation for residual sub-programs.

The abstract domain is a constant + interval + nullability product
(`AbsVal`): every transfer function over-approximates the concrete
numpy/jax semantics of `engine/exprs.py`, so a `const`/interval claim
is a proof, never a heuristic. Diagnostics here are advisory (the
baseline contract pins them); the *liveness* result feeds the serving
path, which is why `program_liveness` refuses (keep-all) rather than
guesses whenever pad equivalence cannot be established.

Code allocation note: GK-P001..P006 belong to the provider lint; the
IR plane starts at GK-P010 to keep the GK-P namespace collision-free.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import (
    Any,
    Callable,
    Dict,
    FrozenSet,
    Iterable,
    Iterator,
    List,
    Optional,
    Set,
    Tuple,
)

import numpy as np

from ..engine.exprs import (
    ECapture,
    EConstSlot,
    EFullN,
    EGatherElem,
    EGroup,
    EGroupPresent,
    EIsInConst,
    ELit,
    EMap,
    EReduce,
    EReduceAxis,
    ERowFeature,
    ESelPattern,
    EStrTable,
    ETokCol,
    Expr,
)
from ..engine.programs import Program

__all__ = [
    "IR_CODES",
    "IrDiagnostic",
    "IrLint",
    "IrReport",
    "Certificate",
    "ProgramLiveness",
    "analyze_program",
    "corpus_liveness",
    "ir_from_docs",
    "ir_from_programs",
    "pattern_reads",
    "program_liveness",
    "row_feature_pids",
]


# stable code -> (severity, one-line meaning). Like the corpus plane,
# ANY diagnostic flags the subject for baseline purposes; severity is
# reader-facing triage only.
IR_CODES: Dict[str, Tuple[str, str]] = {
    "GK-P010": ("warn", "violation rule provably fires on every row"),
    "GK-P011": ("warn", "violation rule provably never fires"),
    "GK-P012": ("info", "constraint parameter cannot affect the verdict"),
    "GK-P013": ("info", "interval-provable no-op check"),
    "GK-P014": ("info", "unreachable violation branch"),
    "GK-P015": ("info", "template off the fused path (reason code)"),
    "GK-P016": ("info", "program not liveness-maskable (keep-all)"),
}


@dataclass
class IrDiagnostic:
    """One IR finding, attached to one subject."""

    code: str
    subject: str  # "template:<Kind>" | "constraint:<Kind>/<name>"
    message: str
    path: str = ""  # provenance (branch index, const slot, ...)

    @property
    def severity(self) -> str:
        return IR_CODES.get(self.code, ("error", ""))[0]

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "code": self.code,
            "severity": self.severity,
            "subject": self.subject,
            "message": self.message,
        }
        if self.path:
            out["path"] = self.path
        return out

    def render(self) -> str:
        where = f" @ {self.path}" if self.path else ""
        return f"[{self.code}] {self.subject}{where}: {self.message}"


@dataclass
class IrLint:
    """Per-subject rollup (the CorpusLint shape the CLI baseline
    machinery expects: id, source, codes, ok, render)."""

    id: str
    source: str = ""
    diagnostics: List[IrDiagnostic] = field(default_factory=list)

    def add(self, diag: IrDiagnostic) -> None:
        for d in self.diagnostics:
            if d.code == diag.code and d.message == diag.message:
                return
        self.diagnostics.append(diag)

    @property
    def codes(self) -> List[str]:
        return sorted({d.code for d in self.diagnostics})

    @property
    def ok(self) -> bool:
        return not self.diagnostics

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "source": self.source,
            "ok": self.ok,
            "codes": self.codes,
            "diagnostics": [d.to_dict() for d in self.diagnostics],
        }

    def render(self) -> str:
        if self.ok:
            return f"{self.id}: ok"
        lines = [f"{self.id}:"]
        for d in self.diagnostics:
            lines.append(f"  {d.render()}")
        return "\n".join(lines)


@dataclass
class Certificate:
    """Residual-specialization certificate: one branch of one compiled
    program is provably foldable under the current corpus. `fold` is
    "dead" (condition constant False: the branch can be dropped from a
    residual sub-program) or "always" (constant True: the condition
    test can be elided). Consumed by the planner as metadata only —
    nothing in the serving path acts on a certificate yet."""

    subject: str
    kind: str
    branch: int
    fold: str  # "dead" | "always"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "subject": self.subject,
            "kind": self.kind,
            "branch": self.branch,
            "fold": self.fold,
        }


@dataclass
class IrReport:
    """Whole-corpus IR outcome: per-subject lints + the serving feeds."""

    lints: List[IrLint] = field(default_factory=list)
    certificates: List[Certificate] = field(default_factory=list)
    # subject -> "exact" | "screen" | "interpreter:<reason-slug>"
    fused: Dict[str, str] = field(default_factory=dict)
    # corpus feature-liveness summary (see corpus_liveness)
    liveness: Dict[str, Any] = field(default_factory=dict)
    subjects: int = 0

    def lint_for(self, subject_id: str, source: str = "") -> IrLint:
        for lint in self.lints:
            if lint.id == subject_id:
                return lint
        lint = IrLint(id=subject_id, source=source)
        self.lints.append(lint)
        return lint

    @property
    def diagnostics(self) -> List[IrDiagnostic]:
        return [d for lint in self.lints for d in lint.diagnostics]

    @property
    def codes(self) -> List[str]:
        return sorted({d.code for d in self.diagnostics})

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for d in self.diagnostics:
            out[d.code] = out.get(d.code, 0) + 1
        return out

    @property
    def ok(self) -> bool:
        return all(lint.ok for lint in self.lints)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "subjects": self.subjects,
            "ok": self.ok,
            "counts": self.counts(),
            "fused": dict(sorted(self.fused.items())),
            "liveness": self.liveness,
            "certificates": [c.to_dict() for c in self.certificates],
            "lints": [lint.to_dict() for lint in self.lints],
        }

    def render(self) -> str:
        lines = []
        for lint in self.lints:
            if not lint.ok:
                lines.append(lint.render())
        counts = self.counts()
        summary = ", ".join(
            f"{c}={counts[c]}" for c in sorted(counts)
        ) or "clean"
        live = self.liveness or {}
        lines.append(
            f"ir: {self.subjects} subject(s), {summary}; "
            f"maskable={live.get('maskable', 0)}/"
            f"{live.get('programs', 0)} "
            f"live_patterns={live.get('live_patterns')} "
            f"certificates={len(self.certificates)}"
        )
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# IR walking


def _expr_children(e: Expr) -> Tuple[Expr, ...]:
    if isinstance(e, (EStrTable, EIsInConst)):
        return (e.ids,)
    if isinstance(e, EMap):
        return tuple(e.args)
    if isinstance(e, (EReduce, EReduceAxis)):
        return (e.child,)
    if isinstance(e, EGroup):
        return (e.mask,) if e.value is None else (e.mask, e.value)
    if isinstance(e, EGroupPresent):
        return (e.mask,)
    if isinstance(e, EGatherElem):
        return (e.elem,)
    return ()


def _iter_dag(roots: Iterable[Expr]) -> Iterator[Expr]:
    """Every node of the DAGs under `roots`, each exactly once."""
    seen: Set[int] = set()
    stack = [r for r in roots if isinstance(r, Expr)]
    while stack:
        e = stack.pop()
        if id(e) in seen:
            continue
        seen.add(id(e))
        yield e
        stack.extend(_expr_children(e))


def _plan_exprs(obj: Any, out: List[Expr], toksets: List[Any]) -> None:
    """Collect Expr leaves (and RTokSet plan nodes) from a render plan
    tree (engine/render.py RVal dataclasses), structure-generically so
    new plan node kinds degrade to 'walk their fields' instead of
    silently hiding reads."""
    if obj is None or isinstance(obj, (str, bytes, int, float, bool)):
        return
    if isinstance(obj, Expr):
        out.append(obj)
        return
    if isinstance(obj, (list, tuple, set, frozenset)):
        for x in obj:
            _plan_exprs(x, out, toksets)
        return
    if isinstance(obj, dict):
        for x in obj.values():
            _plan_exprs(x, out, toksets)
        return
    if type(obj).__name__ == "RTokSet":
        toksets.append(obj)
    d = getattr(obj, "__dict__", None)
    if d is not None and type(obj).__module__.endswith("engine.render"):
        for x in d.values():
            _plan_exprs(x, out, toksets)


def _program_roots(
    program: Program,
) -> Tuple[List[Expr], List[Expr], List[Any]]:
    """-> (all root exprs, render-sensitive roots needing the
    pad-equivalence proof at EQ level, RTokSet plan nodes needing it at
    EQ_FALSE level). The count expr is always first."""
    roots: List[Expr] = [program.expr]
    guarded: List[Expr] = []
    toksets: List[Any] = []
    for f in program.flags or ():
        roots.append(f)
        guarded.append(f)
    for br in program.branches or ():
        cond = getattr(br, "cond", None)
        if isinstance(cond, Expr):
            roots.append(cond)
            guarded.append(cond)
        plan = getattr(br, "plan", None)
        extra: List[Expr] = []
        _plan_exprs(plan, extra, toksets)
        roots.extend(extra)
    for ts in toksets:
        for attr in ("mask", "elem_ids"):
            e = getattr(ts, attr, None)
            if isinstance(e, Expr):
                roots.append(e)
    return roots, guarded, toksets


def pattern_reads(program: Program) -> FrozenSet[int]:
    """Every pattern index the program can gate a token read through
    (ESelPattern membership and ECapture capture gathers), across the
    count expr, safety flags, render branch conditions, and render
    plans."""
    roots, _, _ = _program_roots(program)
    out: Set[int] = set()
    for e in _iter_dag(roots):
        if isinstance(e, (ESelPattern, ECapture)):
            out.add(e.pattern_idx)
    return frozenset(out)


def row_feature_pids(names: Iterable[str]) -> FrozenSet[int]:
    """Pattern indices probed by per-row feature planes. The
    "invdup:<leaf>:<mirror>:<se>:<g+g+...>" features gather tokens at
    the leaf, mirror, and guard patterns over the encoded corpus
    (TpuDriver._row_feature_bits), so those patterns must stay live;
    "extdata:*" features read raw reviews, never the token table."""
    out: Set[int] = set()
    for name in names:
        if not name.startswith("invdup:"):
            continue
        parts = name.split(":")
        if len(parts) < 5:
            continue
        try:
            out.add(int(parts[1]))
            out.add(int(parts[2]))
            out.update(int(x) for x in parts[4].split("+") if x)
        except ValueError:
            continue
    return frozenset(out)


# ---------------------------------------------------------------------------
# Pad-equivalence liveness proof
#
# Masked encoding drops tokens matching no live pattern. That is sound
# for a program iff every token-space intermediate takes the SAME value
# at a dead token as at a pad slot (spath=idx0=idx1=kind=vid=-1,
# vnum=0): then the masked table is just the unmasked table with dead
# slots turned into (fewer) pad slots, and compiled programs are
# already pad-count-invariant — L and G buckets vary per batch in
# production, so any reduction's pad contribution is necessarily its
# identity. We prove per-node a three-point attribute:
#
#   EQF  value is False/0 at dead tokens AND at pad slots
#   EQV  value is equal at dead tokens and pad slots (possibly unknown)
#   NEQ  no proof (e.g. raw ETokCol columns: kind differs from -1)
#
# and require EQV at every token-axis-eliminating site (EReduce /
# EReduceAxis over "tok") and EQF for every EGroup/EGroupPresent mask
# (group scatters read idx0/idx1, which DO differ between dead and pad,
# so the mask itself must disable dead slots) and every render RTokSet
# mask (set enumeration has no pad-correctness argument to lean on).
# Any violation makes the program non-maskable: the corpus falls back
# to keep-all encoding, which is always parity-safe.

EQF, EQV, NEQ = 0, 1, 2


def _pad_dp(
    e: Expr, memo: Dict[int, int], violations: List[str]
) -> int:
    hit = memo.get(id(e))
    if hit is not None:
        return hit
    memo[id(e)] = EQV  # cycle guard (DAGs only, but stay safe)
    d = _pad_dp_compute(e, memo, violations)
    memo[id(e)] = d
    return d


def _pad_dp_compute(
    e: Expr, memo: Dict[int, int], violations: List[str]
) -> int:
    if isinstance(e, ESelPattern):
        # live patterns never match a dead token's path; pads fail the
        # spath >= 0 gate
        return EQF
    if isinstance(e, ECapture):
        return EQV  # -1 at dead (no match) and at pad (spath gate)
    if isinstance(e, ETokCol):
        return NEQ
    if isinstance(e, (ELit, EFullN, EConstSlot, ERowFeature)):
        return EQV
    if isinstance(e, EStrTable):
        d = _pad_dp(e.ids, memo, violations)
        if d == NEQ:
            return NEQ
        # captured-id lookups read row -1 -> default at dead AND pad
        if isinstance(e.ids, ECapture) and not e.default:
            return EQF
        return EQV
    if isinstance(e, EIsInConst):
        d = _pad_dp(e.ids, memo, violations)
        if d == NEQ:
            return NEQ
        # const member sets exclude the -1 pad sentinel by construction
        if isinstance(e.ids, ECapture):
            return EQF
        return EQV
    if isinstance(e, EMap):
        ds = [_pad_dp(a, memo, violations) for a in e.args]
        if e.name == "maskfill":
            # IR contract with engine/symbolic.py: args = [mask, value],
            # result is a constant fill wherever mask is False. A mask
            # that is provably False at both dead and pad slots makes
            # the output the fill constant at both, whatever the value
            # column does there.
            if ds[0] == EQF:
                return EQV
            return EQV if all(d != NEQ for d in ds) else NEQ
        if e.name == "and":
            if any(d == EQF for d in ds):
                return EQF
            return EQV if all(d != NEQ for d in ds) else NEQ
        if e.name == "or":
            if all(d == EQF for d in ds):
                return EQF
            return EQV if all(d != NEQ for d in ds) else NEQ
        # not / cmp* / arith* / where / generic elementwise: equal
        # inputs give equal outputs
        return EQV if all(d != NEQ for d in ds) else NEQ
    if isinstance(e, EReduce):
        d = _pad_dp(e.child, memo, violations)
        if e.child.space and e.child.space[-1] == "tok":
            if d == NEQ:
                violations.append(
                    f"reduce-{e.how} over tok axis of a value that "
                    "differs between dead and pad tokens"
                )
            return EQV
        return d
    if isinstance(e, EReduceAxis):
        d = _pad_dp(e.child, memo, violations)
        if e.axis == "tok":
            if d == NEQ:
                violations.append(
                    f"reduce-{e.how} over named tok axis of a value "
                    "that differs between dead and pad tokens"
                )
            return EQV
        return d
    if isinstance(e, (EGroup, EGroupPresent)):
        dm = _pad_dp(e.mask, memo, violations)
        if dm != EQF:
            violations.append(
                "group scatter mask not provably False at dead tokens "
                "(idx0/idx1 differ between dead and pad)"
            )
        if isinstance(e, EGroup) and e.value is not None:
            # value is only read where the mask holds, but walk it for
            # nested violations all the same
            _pad_dp(e.value, memo, violations)
        return EQV
    if isinstance(e, EGatherElem):
        _pad_dp(e.elem, memo, violations)
        return NEQ  # gathers through idx0/idx1: dead != pad (default)
    # unknown node kind: refuse to certify anything about it
    violations.append(f"unknown IR node {type(e).__name__}")
    return NEQ


@dataclass
class ProgramLiveness:
    """Per-program liveness verdict: the pattern read set, and whether
    the pad-equivalence proof went through (maskable=False forces
    keep-all encoding for any corpus containing this program)."""

    pids: FrozenSet[int]
    maskable: bool
    violations: Tuple[str, ...] = ()


def program_liveness(program: Program) -> ProgramLiveness:
    roots, guarded, toksets = _program_roots(program)
    memo: Dict[int, int] = {}
    violations: List[str] = []
    # the full walk (count expr first) surfaces reduction/group/unknown
    # violations everywhere
    for r in roots:
        _pad_dp(r, memo, violations)
    # render-sensitive roots: branch conds and safety flags are
    # host-reduced over the token axes, so they need the proof at their
    # own top level too
    for g in guarded:
        if "tok" in g.space and _pad_dp(g, memo, violations) == NEQ:
            violations.append(
                "render branch condition / safety flag differs between "
                "dead and pad tokens"
            )
    for ts in toksets:
        mask = getattr(ts, "mask", None)
        if isinstance(mask, Expr) and (
            _pad_dp(mask, memo, violations) != EQF
        ):
            violations.append(
                "render token-set mask not provably False at dead tokens"
            )
    pids = frozenset(
        e.pattern_idx
        for e in _iter_dag(roots)
        if isinstance(e, (ESelPattern, ECapture))
    )
    return ProgramLiveness(
        pids=pids,
        maskable=not violations,
        violations=tuple(dict.fromkeys(violations)),
    )


def corpus_liveness(
    programs: Iterable[Optional[Program]],
    extra_pids: Iterable[int] = (),
) -> Optional[FrozenSet[int]]:
    """Union liveness over a program population sharing one encoded
    corpus. Returns the live pattern-index set, or None when any
    program is non-maskable (keep-all: encode everything). Interpreter
    -routed constraints (None programs) read raw reviews, never the
    token table, so they do not constrain liveness."""
    live: Set[int] = set(extra_pids)
    for p in programs:
        if p is None:
            continue
        pl = program_liveness(p)
        if not pl.maskable:
            return None
        live |= pl.pids
        live |= row_feature_pids(p.row_features)
    return frozenset(live)


# ---------------------------------------------------------------------------
# Abstract interpretation (constant + interval + nullability)

_INF = math.inf


@dataclass(frozen=True)
class AbsVal:
    """Abstract value: interval [lo, hi] over the numeric reading of
    the node (bools as 0/1), `const` when the value is provably the
    same everywhere, `maybe_absent` when some lattice point is the
    pad/default sentinel rather than document data (the nullability
    bit: a `const` claim with maybe_absent=True still means every
    element equals const, sentinel included)."""

    lo: float = -_INF
    hi: float = _INF
    const: Optional[float] = None
    maybe_absent: bool = False

    @staticmethod
    def constant(v: Any, maybe_absent: bool = False) -> "AbsVal":
        f = float(v)
        return AbsVal(lo=f, hi=f, const=f, maybe_absent=maybe_absent)

    @staticmethod
    def interval(
        lo: float, hi: float, maybe_absent: bool = False
    ) -> "AbsVal":
        if lo == hi and not math.isinf(lo):
            return AbsVal(lo=lo, hi=hi, const=lo, maybe_absent=maybe_absent)
        return AbsVal(lo=lo, hi=hi, maybe_absent=maybe_absent)

    def join(self, other: "AbsVal") -> "AbsVal":
        const = (
            self.const
            if self.const is not None and self.const == other.const
            else None
        )
        out = AbsVal(
            lo=min(self.lo, other.lo),
            hi=max(self.hi, other.hi),
            const=const,
            maybe_absent=self.maybe_absent or other.maybe_absent,
        )
        return out


TOP = AbsVal()
BOOL = AbsVal(lo=0.0, hi=1.0)


def _abs_const_slot(consts: Dict[str, np.ndarray], slot: str) -> AbsVal:
    arr = consts.get(slot)
    if arr is None:
        return TOP
    a = np.asarray(arr)
    if a.size == 0:
        return TOP
    if a.ndim == 0:
        try:
            return AbsVal.constant(float(a))
        except (TypeError, ValueError):
            return TOP
    try:
        return AbsVal.interval(float(a.min()), float(a.max()))
    except (TypeError, ValueError):
        return TOP


_TOKCOL_BOUNDS = {
    "spath": AbsVal(lo=-1.0, hi=_INF, maybe_absent=True),
    "idx0": AbsVal(lo=-1.0, hi=_INF, maybe_absent=True),
    "idx1": AbsVal(lo=-1.0, hi=_INF, maybe_absent=True),
    "kind": AbsVal(lo=-1.0, hi=5.0, maybe_absent=True),
    "vid": AbsVal(lo=-1.0, hi=_INF, maybe_absent=True),
    "vnum": AbsVal(maybe_absent=True),
}


class _AbsInterp:
    """One abstract pass over a program's DAGs. Collects interval
    no-op findings (`noop_checks`) on the way: comparison nodes whose
    outcome is provably constant while a constraint parameter slot
    feeds the comparison."""

    def __init__(self, consts: Dict[str, np.ndarray]):
        self.consts = consts
        self.memo: Dict[int, AbsVal] = {}
        self.slot_refs: Set[str] = set()
        self.noop_checks: List[str] = []
        self._has_slot: Dict[int, bool] = {}

    def has_slot(self, e: Expr) -> bool:
        hit = self._has_slot.get(id(e))
        if hit is None:
            hit = isinstance(e, (EConstSlot, EIsInConst)) or any(
                self.has_slot(c) for c in _expr_children(e)
            )
            self._has_slot[id(e)] = hit
        return hit

    def eval(self, e: Expr) -> AbsVal:
        hit = self.memo.get(id(e))
        if hit is not None:
            return hit
        self.memo[id(e)] = TOP  # cycle guard
        v = self._eval(e)
        self.memo[id(e)] = v
        return v

    def _eval(self, e: Expr) -> AbsVal:
        if isinstance(e, (ELit, EFullN)):
            try:
                return AbsVal.constant(float(e.value))
            except (TypeError, ValueError):
                return TOP
        if isinstance(e, EConstSlot):
            self.slot_refs.add(e.slot)
            return _abs_const_slot(self.consts, e.slot)
        if isinstance(e, ERowFeature):
            return BOOL
        if isinstance(e, ETokCol):
            return _TOKCOL_BOUNDS.get(e.col, TOP)
        if isinstance(e, ESelPattern):
            return AbsVal(lo=0.0, hi=1.0, maybe_absent=True)
        if isinstance(e, ECapture):
            return AbsVal(lo=-1.0, hi=_INF, maybe_absent=True)
        if isinstance(e, EStrTable):
            self.eval(e.ids)
            try:
                default = AbsVal.constant(float(e.default))
            except (TypeError, ValueError):
                default = TOP
            return TOP.join(default)
        if isinstance(e, EIsInConst):
            self.slot_refs.add(e.slot)
            self.eval(e.ids)
            members = np.asarray(self.consts.get(e.slot, ()))
            if members.size == 0 or bool((members == -1).all()):
                # empty member set: provably False membership
                return AbsVal.constant(0.0)
            return BOOL
        if isinstance(e, EMap):
            return self._eval_map(e)
        if isinstance(e, EReduce):
            return self._eval_reduce(e.child, e.how)
        if isinstance(e, EReduceAxis):
            return self._eval_reduce(e.child, e.how)
        if isinstance(e, EGroup):
            val = (
                self.eval(e.value)
                if e.value is not None
                else self.eval(e.mask)
            )
            self.eval(e.mask)
            if e.how == "any":
                return BOOL
            if e.how == "sum":
                lo = min(0.0, val.lo)
                if val.const == 0.0:
                    return AbsVal.constant(0.0)
                return AbsVal.interval(
                    lo, _INF if val.hi > 0 else 0.0
                )
            try:
                init = AbsVal.constant(float(e.init), maybe_absent=True)
            except (TypeError, ValueError):
                init = TOP
            return val.join(init)
        if isinstance(e, EGroupPresent):
            self.eval(e.mask)
            return BOOL
        if isinstance(e, EGatherElem):
            v = self.eval(e.elem)
            try:
                default = AbsVal.constant(
                    float(e.default), maybe_absent=True
                )
            except (TypeError, ValueError):
                default = TOP
            return v.join(default)
        return TOP

    def _eval_map(self, e: EMap) -> AbsVal:
        vs = [self.eval(a) for a in e.args]
        name = e.name
        if name == "and":
            if any(v.const == 0.0 for v in vs):
                return AbsVal.constant(0.0)
            if all(v.const is not None and v.const != 0.0 for v in vs):
                return AbsVal.constant(1.0)
            return BOOL
        if name == "or":
            if any(v.const is not None and v.const != 0.0 for v in vs):
                return AbsVal.constant(1.0)
            if all(v.const == 0.0 for v in vs):
                return AbsVal.constant(0.0)
            return BOOL
        if name == "not":
            (v,) = vs
            if v.const is not None:
                return AbsVal.constant(0.0 if v.const != 0.0 else 1.0)
            return BOOL
        if name.startswith("cmp") and len(vs) == 2:
            out = _abs_cmp(name[3:], vs[0], vs[1])
            if out.const is not None and any(
                self.has_slot(a) for a in e.args
            ):
                self.noop_checks.append(
                    f"{name[3:]} comparison is constant "
                    f"{'True' if out.const else 'False'}"
                )
            return out
        if name.startswith("arith") and len(vs) == 2:
            return _abs_arith(name[5:], vs[0], vs[1])
        if name == "where" and len(vs) == 3:
            c, t, f = vs
            if c.const is not None:
                return t if c.const != 0.0 else f
            return t.join(f)
        return TOP

    def _eval_reduce(self, child: Expr, how: str) -> AbsVal:
        v = self.eval(child)
        if how in ("any", "all"):
            if v.const is not None:
                return AbsVal.constant(0.0 if v.const == 0.0 else 1.0)
            return BOOL
        if how == "sum":
            if v.const == 0.0:
                return AbsVal.constant(0.0)
            lo = 0.0 if v.lo >= 0 else -_INF
            hi = 0.0 if v.hi <= 0 else _INF
            return AbsVal.interval(lo, hi)
        if how == "max":
            return AbsVal.interval(v.lo, v.hi, maybe_absent=v.maybe_absent)
        return TOP


def _abs_cmp(op: str, a: AbsVal, b: AbsVal) -> AbsVal:
    if a.const is not None and b.const is not None:
        res = {
            "==": a.const == b.const,
            "!=": a.const != b.const,
            "<": a.const < b.const,
            "<=": a.const <= b.const,
            ">": a.const > b.const,
            ">=": a.const >= b.const,
        }.get(op)
        if res is not None:
            return AbsVal.constant(1.0 if res else 0.0)
    if op in ("<", "<="):
        if a.hi < b.lo or (op == "<=" and a.hi <= b.lo):
            return AbsVal.constant(1.0)
        if a.lo > b.hi or (op == "<" and a.lo >= b.hi):
            return AbsVal.constant(0.0)
    if op in (">", ">="):
        if a.lo > b.hi or (op == ">=" and a.lo >= b.hi):
            return AbsVal.constant(1.0)
        if a.hi < b.lo or (op == ">" and a.hi <= b.lo):
            return AbsVal.constant(0.0)
    if op == "==" and (a.hi < b.lo or b.hi < a.lo):
        return AbsVal.constant(0.0)
    if op == "!=" and (a.hi < b.lo or b.hi < a.lo):
        return AbsVal.constant(1.0)
    return BOOL


def _abs_arith(op: str, a: AbsVal, b: AbsVal) -> AbsVal:
    if a.const is not None and b.const is not None:
        try:
            res = {
                "+": a.const + b.const,
                "-": a.const - b.const,
                "*": a.const * b.const,
            }.get(op)
            if res is None and op == "/" and b.const != 0:
                res = a.const / b.const
            if res is None and op == "%" and b.const != 0:
                res = a.const % b.const
            if res is not None:
                return AbsVal.constant(res)
        except (OverflowError, ZeroDivisionError):
            return TOP
    if op == "+":
        return AbsVal.interval(a.lo + b.lo, a.hi + b.hi)
    if op == "-":
        return AbsVal.interval(a.lo - b.hi, a.hi - b.lo)
    return TOP


def analyze_program(
    subject: str,
    kind: str,
    program: Program,
    params: Any = None,
) -> Tuple[List[IrDiagnostic], List[Certificate]]:
    """Abstract-interpret one compiled program; -> (diagnostics,
    specialization certificates)."""
    diags: List[IrDiagnostic] = []
    certs: List[Certificate] = []
    interp = _AbsInterp(program.consts)
    final = interp.eval(program.expr)
    for f in program.flags or ():
        interp.eval(f)
    branch_vals: List[Optional[AbsVal]] = []
    for br in program.branches or ():
        cond = getattr(br, "cond", None)
        branch_vals.append(
            interp.eval(cond) if isinstance(cond, Expr) else None
        )
    screen_note = " (screen: over-approximate)" if program.screen else ""
    if final.lo >= 1.0:
        diags.append(
            IrDiagnostic(
                code="GK-P010",
                subject=subject,
                message=(
                    "violation count is provably >= "
                    f"{int(final.lo)} on every row{screen_note}"
                ),
            )
        )
    elif final.hi <= 0.0:
        diags.append(
            IrDiagnostic(
                code="GK-P011",
                subject=subject,
                message=(
                    "violation count is provably 0 on every row"
                    f"{screen_note}: rule can never fire"
                ),
            )
        )
    unused = sorted(set(program.consts) - interp.slot_refs)
    if unused:
        diags.append(
            IrDiagnostic(
                code="GK-P012",
                subject=subject,
                message=(
                    "constant slots burned from parameters but never "
                    f"read by the program: {', '.join(unused)}"
                ),
                path=f"consts[{','.join(unused)}]",
            )
        )
    for msg in dict.fromkeys(interp.noop_checks):
        diags.append(
            IrDiagnostic(
                code="GK-P013",
                subject=subject,
                message=f"no-op check: {msg}",
            )
        )
    for i, bv in enumerate(branch_vals):
        if bv is None:
            continue
        if bv.const == 0.0:
            diags.append(
                IrDiagnostic(
                    code="GK-P014",
                    subject=subject,
                    message=(
                        f"render branch {i} condition is provably "
                        "False: unreachable"
                    ),
                    path=f"branches[{i}]",
                )
            )
            certs.append(
                Certificate(
                    subject=subject, kind=kind, branch=i, fold="dead"
                )
            )
        elif bv.const is not None:
            certs.append(
                Certificate(
                    subject=subject, kind=kind, branch=i, fold="always"
                )
            )
    return diags, certs


def _analyze_into(
    report: IrReport,
    subject: str,
    kind: str,
    program: Program,
    params: Any,
) -> None:
    """Shared per-program analysis: diagnostics + certificates +
    maskability into the report."""
    lint = report.lint_for(subject)
    diags, certs = analyze_program(subject, kind, program, params)
    for d in diags:
        lint.add(d)
    report.certificates.extend(certs)
    pl = program_liveness(program)
    if not pl.maskable:
        lint.add(
            IrDiagnostic(
                code="GK-P016",
                subject=subject,
                message=(
                    "not liveness-maskable (keep-all encoding): "
                    + "; ".join(pl.violations[:3])
                ),
            )
        )


def _finish_liveness(report: IrReport, programs: List[Program]) -> None:
    live = corpus_liveness(programs)
    report.liveness = {
        "programs": len(programs),
        "maskable": sum(
            1 for p in programs if program_liveness(p).maskable
        ),
        "keep_all": live is None,
        "live_patterns": (len(live) if live is not None else None),
    }
    report.subjects = len(report.lints)
    for lint in report.lints:
        # the CLI prints `[source]` per row; the fused-path taxonomy
        # entry is the most useful provenance an IR subject has
        if not lint.source:
            lint.source = report.fused.get(lint.id, "")
    report.lints.sort(key=lambda lint: lint.id)


def ir_from_programs(
    items: Iterable[Tuple[str, str, Optional[Program], Any]],
    fallback_codes: Optional[Dict[str, str]] = None,
) -> IrReport:
    """Driver-side IR report over already-compiled programs. `items`
    is (subject, kind, Program-or-None, params); a None program is an
    interpreter-routed constraint whose fallback reason (the analyzer's
    GK-V code, from the driver's fallback table) becomes its fused-path
    taxonomy entry."""
    report = IrReport()
    programs: List[Program] = []
    for subject, kind, prog, params in items:
        lint = report.lint_for(subject)
        if prog is None:
            code = (fallback_codes or {}).get(kind) or "GK-V007"
            report.fused[subject] = f"interpreter:{code}"
            lint.add(
                IrDiagnostic(
                    code="GK-P015",
                    subject=subject,
                    message=(
                        f"off the fused path (analyzer code {code})"
                    ),
                    path=f"reason={code}",
                )
            )
            continue
        report.fused[subject] = "screen" if prog.screen else "exact"
        programs.append(prog)
        _analyze_into(report, subject, kind, prog, params)
    _finish_liveness(report, programs)
    return report


# ---------------------------------------------------------------------------
# Offline corpus runner (the CLI `ir` mode)


def _doc_kind(doc: Dict[str, Any]) -> str:
    k = doc.get("kind")
    return k if isinstance(k, str) else ""


def ir_from_docs(
    docs: Iterable[Dict[str, Any]],
    liveness_probe: Optional[Callable[[List[Program]], Any]] = None,
) -> IrReport:
    """Offline IR analysis over raw YAML docs (templates +
    constraints), mirroring corpus_from_docs' doc classification. Every
    subject gets a lint row (clean included) so the baseline pins the
    whole corpus. Compilation runs against a throwaway vocab with no
    oracle: templates whose helpers need the interpreter oracle
    off-line report the same reason taxonomy the live driver would."""
    from ..constraint import regocompile
    from ..constraint.templates import ConstraintTemplate
    from ..engine.programs import compile_program
    from ..engine.symbolic import CompilerEnv, CompileUnsupported
    from ..engine.tables import StrTables
    from ..flatten.vocab import Vocab

    docs = [d for d in docs if isinstance(d, dict)]
    templates = [d for d in docs if _doc_kind(d) == "ConstraintTemplate"]
    report = IrReport()

    vocab = Vocab()
    from ..engine.patterns import PatternRegistry

    patterns = PatternRegistry(vocab)
    tables = StrTables(vocab)

    mods_by_kind: Dict[str, Any] = {}
    for tdoc in templates:
        kind = ""
        try:
            ct = ConstraintTemplate.from_dict(tdoc)
            ct.validate_names()
            kind = ct.kind
            spec = ct.targets[0]
            mods_by_kind[kind] = regocompile.compile_template_modules(
                ct.kind, spec.target, spec.rego, spec.libs
            )
        except Exception as e:  # invalid templates: own-plane concern
            kind = kind or (
                ((tdoc.get("spec") or {}).get("crd") or {})
                .get("spec", {})
                .get("names", {})
                .get("kind", "")
            ) or "<invalid>"
            report.lint_for(f"template:{kind}").add(
                IrDiagnostic(
                    code="GK-P015",
                    subject=f"template:{kind}",
                    message=f"template did not parse: {e}",
                    path="reason=other",
                )
            )
            report.fused[f"template:{kind}"] = "interpreter:other"

    constraints = [
        d for d in docs if _doc_kind(d) in mods_by_kind
    ]

    def _compile(kind: str, params: Any, subject: str):
        env = CompilerEnv(
            vocab,
            patterns,
            tables,
            oracle_fn=None,
            oracle_ns=f"ir|{subject}",
            oracle_ns_shared=f"ir|{kind}",
            template_kind=kind,
        )
        return compile_program(env, mods_by_kind[kind], params)

    programs: List[Program] = []
    for kind, mods in sorted(mods_by_kind.items()):
        tsub = f"template:{kind}"
        lint = report.lint_for(tsub)
        try:
            tprog = _compile(kind, {}, tsub)
            report.fused[tsub] = "screen" if tprog.screen else "exact"
        except CompileUnsupported as e:
            slug = getattr(getattr(e, "code", None), "value", "other")
            report.fused[tsub] = f"interpreter:{slug}"
            lint.add(
                IrDiagnostic(
                    code="GK-P015",
                    subject=tsub,
                    message=f"off the fused path: {e} (reason={slug})",
                    path=f"reason={slug}",
                )
            )
        except Exception as e:
            report.fused[tsub] = "interpreter:other"
            lint.add(
                IrDiagnostic(
                    code="GK-P015",
                    subject=tsub,
                    message=f"compilation failed: {e}",
                    path="reason=other",
                )
            )

    for cdoc in sorted(
        constraints,
        key=lambda d: (
            _doc_kind(d),
            str((d.get("metadata") or {}).get("name", "")),
        ),
    ):
        kind = _doc_kind(cdoc)
        name = str((cdoc.get("metadata") or {}).get("name", ""))
        subject = f"constraint:{kind}/{name}"
        lint = report.lint_for(subject)
        params = (cdoc.get("spec") or {}).get("parameters") or {}
        try:
            prog = _compile(kind, params, subject)
            report.fused[subject] = "screen" if prog.screen else "exact"
        except CompileUnsupported as e:
            slug = getattr(getattr(e, "code", None), "value", "other")
            report.fused[subject] = f"interpreter:{slug}"
            lint.add(
                IrDiagnostic(
                    code="GK-P015",
                    subject=subject,
                    message=f"off the fused path: {e} (reason={slug})",
                    path=f"reason={slug}",
                )
            )
            continue
        except Exception as e:
            report.fused[subject] = "interpreter:other"
            lint.add(
                IrDiagnostic(
                    code="GK-P015",
                    subject=subject,
                    message=f"compilation failed: {e}",
                    path="reason=other",
                )
            )
            continue
        programs.append(prog)
        _analyze_into(report, subject, kind, prog, params)

    _finish_liveness(report, programs)
    report.liveness["patterns_total"] = patterns.n_patterns
    if liveness_probe is not None:
        liveness_probe(programs)
    return report
