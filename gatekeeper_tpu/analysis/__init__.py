"""Static vectorizability analysis for ConstraintTemplate Rego.

Public surface:

  * `analyze_template(dict)` / `analyze_modules(kind, modules)` — run
    the analyzer; returns a `VectorizabilityReport`.
  * `VectorizabilityReport` / `Diagnostic` — the structured outcome:
    a verdict from the lattice `VECTORIZED | PARTIAL_ROWS |
    INTERPRETER | INVALID` plus stable `GK-Vxxx` diagnostics.
  * `python -m gatekeeper_tpu.analysis <paths...>` — offline template
    linting + CI baseline enforcement (see `cli.py` / docs/analysis.md).

The analyzer is consulted by `constraint/client.py` at template
admission (INVALID templates are rejected with the diagnostics) and by
`constraint/tpudriver.py` ahead of compilation (INTERPRETER templates
route without a try/except around `compile_program`).
"""

from .analyzer import Analyzer, analyze_modules, analyze_template  # noqa: F401
from .report import (  # noqa: F401
    CODE_MISMATCH,
    CODES,
    Diagnostic,
    INTERPRETER,
    INVALID,
    PARTIAL_ROWS,
    VECTORIZED,
    VectorizabilityReport,
    verdict_meet,
)
