"""Static vectorizability analysis for ConstraintTemplate Rego.

Public surface:

  * `analyze_template(dict)` / `analyze_modules(kind, modules)` — run
    the analyzer; returns a `VectorizabilityReport`.
  * `VectorizabilityReport` / `Diagnostic` — the structured outcome:
    a verdict from the lattice `VECTORIZED | PARTIAL_ROWS |
    INTERPRETER | INVALID` plus stable `GK-Vxxx` diagnostics.
  * `python -m gatekeeper_tpu.analysis <paths...>` — offline template
    linting + CI baseline enforcement (see `cli.py` / docs/analysis.md).

The analyzer is consulted by `constraint/client.py` at template
admission (INVALID templates are rejected with the diagnostics) and by
`constraint/tpudriver.py` ahead of compilation (INTERPRETER templates
route without a try/except around `compile_program`).

A second, program-level plane lives in `ir.py` (PR 16): abstract
interpretation and feature liveness over the compiled program IR, with
stable `GK-P01x` codes, the `python -m gatekeeper_tpu.analysis ir`
CLI mode, and the driver-side liveness masking consumed by
`constraint/tpudriver.py`.
"""

from .analyzer import Analyzer, analyze_modules, analyze_template  # noqa: F401
from .ir import (  # noqa: F401
    Certificate,
    IR_CODES,
    IrDiagnostic,
    IrLint,
    IrReport,
    corpus_liveness,
    ir_from_docs,
    ir_from_programs,
    program_liveness,
)
from .report import (  # noqa: F401
    CODE_MISMATCH,
    CODES,
    Diagnostic,
    INTERPRETER,
    INVALID,
    PARTIAL_ROWS,
    VECTORIZED,
    VectorizabilityReport,
    verdict_meet,
)
