"""AST lint for jax.jit call sites (GK-J0xx).

jax.jit's static-argument contract fails at TRACE time, long after the
code that broke it was merged: `static_argnames` naming a parameter the
wrapped function no longer has is silently ignored (the argument is
traced, every distinct value recompiles), and a static parameter whose
default is an unhashable container raises `ValueError: unhashable
static arguments` only on the first call that uses the default. Both
are statically decidable from the AST, so this lint runs as a tier-1
test over the whole package (tests/test_jit_lint.py) instead of
waiting for a TPU to notice.

Covered shapes:

  * `@partial(jax.jit, static_argnames=..., static_argnums=...)`
    decorating a `def` (engine/matchkernel.py idiom);
  * `jax.jit(fn, static_argnames=..., ...)` where `fn` resolves to a
    `def` in the same file (parallel/sharding.py idiom).

Codes:

  GK-J001  static_argnames names a parameter absent from the wrapped
           function's signature (drifted argnames)
  GK-J002  static_argnums is out of range for the wrapped function's
           positional parameters
  GK-J003  a static parameter's default value is an unhashable literal
           (list/dict/set): the first defaulted call raises

Names/nums that are not literal constants (computed at runtime) are
skipped — the lint only reports what it can prove.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["JitFinding", "lint_file", "lint_source", "lint_paths"]

JIT_CODES: Dict[str, str] = {
    "GK-J001": "static_argnames drifted from the function signature",
    "GK-J002": "static_argnums out of positional range",
    "GK-J003": "static parameter defaults to an unhashable literal",
}


@dataclass(frozen=True)
class JitFinding:
    file: str
    line: int
    code: str
    message: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.code}] {self.message}"


def _is_jax_jit(node: ast.AST) -> bool:
    """`jax.jit` or a bare `jit` (from jax import jit)."""
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return isinstance(node.value, ast.Name) and node.value.id == "jax"
    return isinstance(node, ast.Name) and node.id == "jit"


def _const_str_seq(node: ast.AST) -> Optional[List[str]]:
    """A literal str or tuple/list of literal strs, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[str] = []
        for el in node.elts:
            if not (
                isinstance(el, ast.Constant)
                and isinstance(el.value, str)
            ):
                return None
            out.append(el.value)
        return out
    return None


def _const_int_seq(node: ast.AST) -> Optional[List[int]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[int] = []
        for el in node.elts:
            if not (
                isinstance(el, ast.Constant)
                and isinstance(el.value, int)
                and not isinstance(el.value, bool)
            ):
                return None
            out.append(el.value)
        return out
    return None


def _fn_params(fn: ast.AST) -> Optional[Tuple[List[str], bool, Dict[str, ast.AST]]]:
    """-> (positional param names, has *args, {param: default-node}) for
    a def/lambda, None for anything else."""
    if not isinstance(
        fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
    ):
        return None
    a = fn.args
    pos = [p.arg for p in a.posonlyargs + a.args]
    names = pos + [p.arg for p in a.kwonlyargs]
    defaults: Dict[str, ast.AST] = {}
    pos_defaults = a.defaults
    for param, d in zip(pos[len(pos) - len(pos_defaults):], pos_defaults):
        defaults[param] = d
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if d is not None:
            defaults[p.arg] = d
    return names, a.vararg is not None, defaults


_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.DictComp, ast.ListComp,
               ast.SetComp)


def _check_site(
    file: str,
    call: ast.Call,
    fn: Optional[ast.AST],
    out: List[JitFinding],
) -> None:
    """One jit(...) call (or partial(jax.jit, ...) decorator) against
    the wrapped function's AST, when it could be resolved."""
    argnames: Optional[List[str]] = None
    argnums: Optional[List[int]] = None
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            argnames = _const_str_seq(kw.value)
        elif kw.arg == "static_argnums":
            argnums = _const_int_seq(kw.value)
    if fn is None or (argnames is None and argnums is None):
        return
    sig = _fn_params(fn)
    if sig is None:
        return
    names, has_vararg, defaults = sig
    static: List[str] = []
    for n in argnames or ():
        if n not in names:
            out.append(
                JitFinding(
                    file,
                    call.lineno,
                    "GK-J001",
                    f"static_argnames={n!r} is not a parameter of the "
                    "wrapped function (drifted after a signature "
                    "change?): jax silently traces it instead",
                )
            )
        else:
            static.append(n)
    n_pos = len(names)
    for i in argnums or ():
        idx = i if i >= 0 else n_pos + i
        if not has_vararg and not (0 <= idx < n_pos):
            out.append(
                JitFinding(
                    file,
                    call.lineno,
                    "GK-J002",
                    f"static_argnums={i} is out of range for a "
                    f"{n_pos}-parameter function",
                )
            )
        elif 0 <= idx < n_pos:
            static.append(names[idx])
    for n in static:
        d = defaults.get(n)
        if d is not None and isinstance(d, _UNHASHABLE):
            out.append(
                JitFinding(
                    file,
                    call.lineno,
                    "GK-J003",
                    f"static parameter {n!r} defaults to an unhashable "
                    f"{type(d).__name__.lower()} literal: the first "
                    "defaulted call raises at trace time",
                )
            )


class _Visitor(ast.NodeVisitor):
    def __init__(self, file: str):
        self.file = file
        self.findings: List[JitFinding] = []
        # name -> def node, per enclosing-scope stack (closest wins)
        self._scopes: List[Dict[str, ast.AST]] = [{}]

    def _resolve(self, name: str) -> Optional[ast.AST]:
        for scope in reversed(self._scopes):
            if name in scope:
                return scope[name]
        return None

    def _visit_fn(self, node) -> None:
        self._scopes[-1][node.name] = node
        for dec in node.decorator_list:
            if isinstance(dec, ast.Call) and self._is_partial_jit(dec):
                _check_site(self.file, dec, node, self.findings)
            elif isinstance(dec, ast.Call) and _is_jax_jit(dec.func):
                _check_site(self.file, dec, node, self.findings)
        self._scopes.append({})
        self.generic_visit(node)
        self._scopes.pop()

    @staticmethod
    def _is_partial_jit(call: ast.Call) -> bool:
        f = call.func
        is_partial = (
            isinstance(f, ast.Name) and f.id == "partial"
        ) or (
            isinstance(f, ast.Attribute) and f.attr == "partial"
        )
        return bool(
            is_partial and call.args and _is_jax_jit(call.args[0])
        )

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._scopes.append({})
        self.generic_visit(node)
        self._scopes.pop()

    def visit_Call(self, node: ast.Call) -> None:
        if _is_jax_jit(node.func) and node.args:
            target = node.args[0]
            fn: Optional[ast.AST] = None
            if isinstance(target, ast.Lambda):
                fn = target
            elif isinstance(target, ast.Name):
                fn = self._resolve(target.id)
            _check_site(self.file, node, fn, self.findings)
        self.generic_visit(node)


def lint_source(source: str, file: str = "<string>") -> List[JitFinding]:
    try:
        tree = ast.parse(source, filename=file)
    except SyntaxError as e:
        return [
            JitFinding(file, e.lineno or 0, "GK-J000",
                       f"file does not parse: {e.msg}")
        ]
    v = _Visitor(file)
    v.visit(tree)
    return v.findings


def lint_file(path: str) -> List[JitFinding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path)


def lint_paths(paths: Iterable[str]) -> List[JitFinding]:
    out: List[JitFinding] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        out.extend(lint_file(os.path.join(root, fn)))
        elif p.endswith(".py"):
            out.extend(lint_file(p))
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    import sys

    paths = list(argv if argv is not None else sys.argv[1:]) or ["."]
    findings = lint_paths(paths)
    for f in findings:
        print(f.render())
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
