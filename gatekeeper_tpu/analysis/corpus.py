"""Corpus-wide static analysis: the cross-plane semantic gate.

PR 1's analyzer judges each ConstraintTemplate in isolation; this
module judges the *corpus* — templates + constraints + mutators +
providers together — and emits stable ``GK-C0xx`` diagnostics through
the same report/CLI/baseline machinery the per-plane linters use:

==========  ========  =====================================================
code        severity  meaning
==========  ========  =====================================================
GK-C001     error     template calls ``external_data`` naming a provider
                      that is not registered
GK-C002     error     constraint references a kind with no live template
GK-C003     warn      error-gated template (extdata_mode "err") consumes a
                      fail-open provider — the deny-on-error proof can
                      never fire because errors resolve open
GK-C004     error     constraint ``spec.parameters`` violates the template
                      CRD's openAPIV3Schema (wrong type / missing
                      required), with path provenance
GK-C005     warn      constraint parameter key unknown to the template's
                      declared schema (the permissive CRD validator lets
                      it through; a typo'd knob silently does nothing)
GK-C006     warn      dead match: the constraint's compiled match IR is
                      PROVABLY unsatisfiable — no review can select it
GK-C007     warn      shadowed constraint: another constraint with the
                      same kind, parameters and enforcementAction has a
                      provably-superset match
GK-C008     error     admission fight: a mutator's written (path, value)
                      provably lands in a validator's deny set — exhibited
                      by a concrete witness object that admits clean
                      pre-mutation and violates post-mutation
==========  ========  =====================================================

Provable vs heuristic (docs/analysis.md §Corpus analysis):

* GK-C001/C002/C004 are exact — registry lookups and schema walks.
* GK-C006 deadness uses a small set of *sound* proofs over the match
  IR (the same dict ``handler.match_ir`` hands the locality planner),
  each one verified against the ``constraint.match`` oracle semantics:

  - P1  ``kinds`` present with no satisfiable entry (an entry is
        satisfiable iff it is a dict whose ``apiGroups``/``kinds`` are
        both non-empty lists);
  - P2  ``scope`` present with a value outside {"*", "Cluster",
        "Namespaced"} — ``matches_scope`` rejects every review;
  - P3  ``scope: Namespaced`` (which defeats the empty-namespace
        selector bypass) plus ``namespaces`` that is non-list, empty,
        or an all-string list fully covered by string entries of
        ``excludedNamespaces``;
  - P4  ``labelSelector.matchLabels`` non-dict and not one of the
        empty forms the oracle tolerates;
  - P5  ``labelSelector.matchExpressions`` carrying a same-key
        contradiction (DoesNotExist vs Exists / In-with-values).

  Anything not covered by a proof is assumed live — the analyzer
  never guesses a constraint dead.
* GK-C007 superset is dimension-wise conservative (equal canonical IR
  fast path, else each dimension equal-or-strictly-looser); it can
  miss shadows, never invents them.
* GK-C008 is witness-based: the pair is only reported when a concrete
  review was constructed that both match blocks select, the mutator's
  ``apply`` actually changed it, and the template's violation rule
  (evaluated through the stock interpreter) fires on the mutated
  object but not the original. Pairs where no witness could be built
  are skipped, not guessed.

Verdict-safe static pruning: a dead constraint may be excluded from
``PartitionPlan`` dispatch rows ONLY when it also has no
``namespaceSelector`` — the autoreject path (a review whose namespace
context is missing) emits results for ns-selector constraints
*without consulting the match*, so excluding those would change
merged verdicts. ``CorpusReport.prunable_keys`` encodes exactly that:
``dead AND NOT match_needs_ns_selector``. Shadowed constraints only
warn — each live constraint owns its violation message.

``CorpusPlane`` is the serving-side wrapper: it recomputes the report
off the request path when the constraint/mutation churn generation
moves (debounced), exports ``corpus_diagnostics_total{code}`` gauges,
snapshots into ``/readyz`` ``stats.analysis.corpus``, and hands the
partition planner its generation-matched prunable key set.
"""

from __future__ import annotations

import copy
import json
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "CORPUS_CODES",
    "CorpusDiagnostic",
    "CorpusLint",
    "CorpusReport",
    "CorpusPlane",
    "analyze_corpus",
    "corpus_from_docs",
    "corpus_from_live",
    "match_is_dead",
    "match_subsumes",
]

# stable code -> (severity, one-line meaning). Severity "error" fails
# an un-baselined run; "warn" reports but the subject still counts as
# flagged (the baseline pins both kinds).
CORPUS_CODES: Dict[str, Tuple[str, str]] = {
    "GK-C001": ("error", "external_data provider not registered"),
    "GK-C002": ("error", "constraint kind has no live template"),
    "GK-C003": ("warn", "error-gated template behind fail-open provider"),
    "GK-C004": ("error", "constraint parameters violate template schema"),
    "GK-C005": ("warn", "constraint parameter unknown to template schema"),
    "GK-C006": ("warn", "dead match: provably unsatisfiable"),
    "GK-C007": ("warn", "shadowed by a superset constraint"),
    "GK-C008": ("error", "mutator writes a value a validator denies"),
}

_SCOPE_VALUES = ("*", "Cluster", "Namespaced")


@dataclass
class CorpusDiagnostic:
    """One corpus finding, attached to one subject."""

    code: str
    subject: str  # "template:<Kind>" | "constraint:<Kind>/<name>" | ...
    message: str
    path: str = ""  # provenance (spec.parameters.labels[0], ...)

    @property
    def severity(self) -> str:
        return CORPUS_CODES.get(self.code, ("error", ""))[0]

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "code": self.code,
            "severity": self.severity,
            "subject": self.subject,
            "message": self.message,
        }
        if self.path:
            out["path"] = self.path
        return out

    def render(self) -> str:
        where = f" @ {self.path}" if self.path else ""
        return f"[{self.code}] {self.subject}{where}: {self.message}"


@dataclass
class CorpusLint:
    """Per-subject rollup (the MutatorLint/ProviderLint shape the CLI
    baseline machinery expects: id, source, codes, ok, render)."""

    id: str
    source: str = ""
    diagnostics: List[CorpusDiagnostic] = field(default_factory=list)

    def add(self, diag: CorpusDiagnostic) -> None:
        for d in self.diagnostics:
            if d.code == diag.code and d.message == diag.message:
                return
        self.diagnostics.append(diag)

    @property
    def codes(self) -> List[str]:
        return sorted({d.code for d in self.diagnostics})

    @property
    def ok(self) -> bool:
        return not self.diagnostics

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "source": self.source,
            "ok": self.ok,
            "codes": self.codes,
            "diagnostics": [d.to_dict() for d in self.diagnostics],
        }

    def render(self) -> str:
        if self.ok:
            return f"{self.id}: ok"
        lines = [f"{self.id}:"]
        for d in self.diagnostics:
            lines.append(f"  {d.render()}")
        return "\n".join(lines)


@dataclass
class CorpusReport:
    """Whole-corpus outcome: per-subject lints + the planner feeds."""

    lints: List[CorpusLint] = field(default_factory=list)
    # constraint keys ("Kind/name", the partition planner's row ids)
    dead_keys: List[str] = field(default_factory=list)
    # dead AND no namespaceSelector: safe to exclude from dispatch rows
    prunable_keys: List[str] = field(default_factory=list)
    # shadowed key -> the key that shadows it
    shadowed: Dict[str, str] = field(default_factory=dict)
    subjects: int = 0

    def lint_for(self, subject_id: str, source: str = "") -> CorpusLint:
        for lint in self.lints:
            if lint.id == subject_id:
                return lint
        lint = CorpusLint(id=subject_id, source=source)
        self.lints.append(lint)
        return lint

    @property
    def diagnostics(self) -> List[CorpusDiagnostic]:
        return [d for lint in self.lints for d in lint.diagnostics]

    @property
    def codes(self) -> List[str]:
        return sorted({d.code for d in self.diagnostics})

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for d in self.diagnostics:
            out[d.code] = out.get(d.code, 0) + 1
        return out

    @property
    def ok(self) -> bool:
        return all(lint.ok for lint in self.lints)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "subjects": self.subjects,
            "ok": self.ok,
            "counts": self.counts(),
            "dead_keys": sorted(self.dead_keys),
            "prunable_keys": sorted(self.prunable_keys),
            "shadowed": dict(sorted(self.shadowed.items())),
            "lints": [lint.to_dict() for lint in self.lints],
        }

    def render(self) -> str:
        lines = []
        for lint in self.lints:
            if not lint.ok:
                lines.append(lint.render())
        counts = self.counts()
        summary = ", ".join(
            f"{c}={counts[c]}" for c in sorted(counts)
        ) or "clean"
        lines.append(
            f"corpus: {self.subjects} subject(s), {summary}; "
            f"dead={len(self.dead_keys)} prunable={len(self.prunable_keys)} "
            f"shadowed={len(self.shadowed)}"
        )
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# dead-match proofs (sound against constraint.match oracle semantics)


def _kinds_entry_satisfiable(entry: Any) -> bool:
    """Mirror of any_kind_selector_matches' per-entry guard: an entry
    contributes a possible match iff it is a dict whose apiGroups and
    kinds are BOTH non-empty lists (a non-list side short-circuits the
    isinstance gate; an empty list can never contain "*" nor a name)."""
    if not isinstance(entry, dict):
        return False
    groups = entry.get("apiGroups", ["*"])
    kinds = entry.get("kinds", ["*"])
    if not isinstance(groups, list) or not isinstance(kinds, list):
        return False
    return bool(groups) and bool(kinds)


def match_is_dead(ir: Any) -> Tuple[bool, str]:
    """(dead, proof) — True only when NO review can satisfy the match
    IR, by one of the sound proofs P1..P5 documented in the module
    docstring. Non-dict IRs (opaque custom-target match forms) are
    never judged."""
    if not isinstance(ir, dict):
        return False, ""

    # P1: kinds present but no entry satisfiable
    if "kinds" in ir:
        kinds = ir.get("kinds")
        if not isinstance(kinds, list):
            return True, "P1: kinds is not a list"
        if not any(_kinds_entry_satisfiable(e) for e in kinds):
            return True, "P1: no satisfiable kinds entry"

    # P2: scope present with an unrecognized value -> matches_scope
    # returns False for every review (including null / wrong case)
    if "scope" in ir and ir.get("scope") not in _SCOPE_VALUES:
        return True, f"P2: invalid scope {ir.get('scope')!r}"

    # P3: Namespaced scope forces review.namespace != "", which defeats
    # the empty-namespace selector bypass — the namespaces list is then
    # load-bearing for every candidate review
    if ir.get("scope") == "Namespaced" and "namespaces" in ir:
        nss = ir.get("namespaces")
        if not isinstance(nss, list):
            return True, "P3: namespaces is not a list"
        if not nss:
            return True, "P3: namespaces is empty"
        excl = ir.get("excludedNamespaces")
        if (
            isinstance(excl, list)
            and all(isinstance(n, str) for n in nss)
            and all(
                any(isinstance(e, str) and e == n for e in excl)
                for n in nss
            )
        ):
            return True, "P3: namespaces fully excluded"

    sel = ir.get("labelSelector")
    if isinstance(sel, dict):
        # P4: non-dict matchLabels (outside the tolerated empty forms)
        # makes matches_label_selector reject every object
        if "matchLabels" in sel:
            ml = sel.get("matchLabels")
            if not isinstance(ml, dict) and ml not in ([], ""):
                return True, "P4: matchLabels is not an object"
        # P5: same-key contradiction in matchExpressions
        exprs = sel.get("matchExpressions")
        if isinstance(exprs, list):
            absent_keys = set()
            present_keys = set()
            for e in exprs:
                if not isinstance(e, dict) or "operator" not in e:
                    continue
                key = e.get("key")
                if not isinstance(key, str):
                    continue
                op = e.get("operator")
                if op == "DoesNotExist":
                    absent_keys.add(key)
                elif op == "Exists":
                    present_keys.add(key)
                elif op == "In":
                    # In with a non-empty values list violates when the
                    # key is absent (count positive + no match)
                    vals = e.get("values")
                    if isinstance(vals, (list, dict, str)) and vals:
                        present_keys.add(key)
            clash = absent_keys & present_keys
            if clash:
                k = sorted(clash)[0]
                return True, f"P5: contradictory selector on key {k!r}"

    return False, ""


# ---------------------------------------------------------------------------
# subsumption (conservative dimension-wise superset)


def _canon(value: Any) -> str:
    return json.dumps(value, sort_keys=True, default=str)


def _dim_superset_kinds(a: Any, b: Any, present_a: bool, present_b: bool
                        ) -> bool:
    if not present_a:
        return True  # absent = wildcard
    if not present_b:
        # A constrains kinds, B doesn't: A superset only if A contains
        # an explicit full wildcard entry
        return isinstance(a, list) and any(
            isinstance(e, dict)
            and "*" in (e.get("apiGroups") or [])
            and "*" in (e.get("kinds") or [])
            for e in a
        )
    if _canon(a) == _canon(b):
        return True
    if not isinstance(a, list) or not isinstance(b, list):
        return False
    if any(
        isinstance(e, dict)
        and "*" in (e.get("apiGroups") or [])
        and "*" in (e.get("kinds") or [])
        for e in a
    ):
        return True
    # entry-wise containment by canonical equality
    a_set = {_canon(e) for e in a}
    return all(_canon(e) in a_set for e in b)


def _dim_superset_namespaces(a: Any, b: Any, present_a: bool,
                             present_b: bool) -> bool:
    if not present_a:
        return True
    if not present_b:
        return False
    if _canon(a) == _canon(b):
        return True
    if not isinstance(a, list) or not isinstance(b, list):
        return False
    if not all(isinstance(n, str) for n in a + b):
        return False
    return set(b) <= set(a)


def match_subsumes(a_ir: Any, b_ir: Any) -> bool:
    """True when A's match provably selects a superset of B's. Equal
    canonical IR is the fast path; otherwise every dimension must be
    equal-or-looser on A's side. Conservative: False on anything not
    provably looser (opaque IRs, selector differences)."""
    if _canon(a_ir) == _canon(b_ir):
        return True
    if not isinstance(a_ir, dict) or not isinstance(b_ir, dict):
        return False

    if not _dim_superset_kinds(
        a_ir.get("kinds"), b_ir.get("kinds"),
        "kinds" in a_ir, "kinds" in b_ir,
    ):
        return False

    # scope: equal, or A absent/wildcard
    if "scope" in a_ir:
        if a_ir.get("scope") == "*":
            pass
        elif "scope" not in b_ir or a_ir.get("scope") != b_ir.get("scope"):
            return False

    if not _dim_superset_namespaces(
        a_ir.get("namespaces"), b_ir.get("namespaces"),
        "namespaces" in a_ir, "namespaces" in b_ir,
    ):
        return False

    # excludedNamespaces: A must exclude a subset of what B excludes
    if "excludedNamespaces" in a_ir:
        ea, eb = a_ir.get("excludedNamespaces"), b_ir.get(
            "excludedNamespaces"
        )
        if _canon(ea) != _canon(eb):
            if not (
                isinstance(ea, list)
                and isinstance(eb, list)
                and all(isinstance(n, str) for n in ea + eb)
                and set(ea) <= set(eb)
            ):
                return False

    # selectors: must be canonically equal (or absent on A's side); the
    # namespaceSelector also drives the autoreject path, so only exact
    # agreement is treated as comparable
    for dim in ("labelSelector", "namespaceSelector"):
        if dim in a_ir or dim in b_ir:
            if _canon(a_ir.get(dim)) != _canon(b_ir.get(dim)):
                if dim == "labelSelector" and dim not in a_ir:
                    continue  # absent labelSelector matches everything
                return False
    return True


# ---------------------------------------------------------------------------
# witness construction for the mutate<->validate fight pass


def _first_concrete_kind(ir: Any) -> Optional[Tuple[str, str]]:
    """(group, kind) the match accepts, preferring concrete names."""
    if not isinstance(ir, dict) or "kinds" not in ir:
        return "", "Pod"
    kinds = ir.get("kinds")
    if not isinstance(kinds, list):
        return None
    wildcard = None
    for e in kinds:
        if not _kinds_entry_satisfiable(e):
            continue
        groups = e.get("apiGroups", ["*"])
        names = e.get("kinds", ["*"])
        g = next((x for x in groups if x != "*"), None)
        k = next((x for x in names if x != "*"), None)
        if not isinstance(g, str):
            g = "" if "*" in groups else None
        if g is None:
            continue
        if k is None and "*" in names:
            wildcard = (g, "Pod")
            continue
        if isinstance(k, str):
            return g, k
    return wildcard


def _witness_for_match(ir: Any) -> Optional[Dict[str, Any]]:
    """A minimal gkReview dict the match IR selects; None when one
    cannot be constructed structurally (namespaceSelector needs
    namespace objects; opaque IRs are not guessed)."""
    if not isinstance(ir, dict):
        return None
    if "namespaceSelector" in ir:
        return None
    dead, _why = match_is_dead(ir)
    if dead:
        return None
    gk = _first_concrete_kind(ir)
    if gk is None:
        return None
    group, kind = gk
    scope = ir.get("scope")
    if scope not in (None, *_SCOPE_VALUES):
        return None
    ns = ""
    if scope != "Cluster":
        ns = "default"
        nss = ir.get("namespaces")
        if isinstance(nss, list):
            str_ns = [n for n in nss if isinstance(n, str)]
            if not str_ns:
                return None
            ns = str_ns[0]
        excl = ir.get("excludedNamespaces")
        if isinstance(excl, list) and ns in [
            e for e in excl if isinstance(e, str)
        ]:
            return None
    labels: Dict[str, Any] = {}
    sel = ir.get("labelSelector")
    if isinstance(sel, dict):
        ml = sel.get("matchLabels")
        if isinstance(ml, dict):
            if not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in ml.items()
            ):
                return None
            labels.update(ml)
        exprs = sel.get("matchExpressions")
        if isinstance(exprs, list) and exprs:
            for e in exprs:
                if not isinstance(e, dict) or "operator" not in e:
                    continue
                op, key = e.get("operator"), e.get("key")
                if not isinstance(key, str):
                    return None
                if op in ("In",):
                    vals = e.get("values")
                    if isinstance(vals, list) and any(
                        isinstance(v, str) for v in vals
                    ):
                        labels[key] = next(
                            v for v in vals if isinstance(v, str)
                        )
                    else:
                        return None
                elif op == "Exists":
                    labels.setdefault(key, "x")
                elif op in ("DoesNotExist", "NotIn"):
                    if key in labels:
                        return None
                else:
                    return None
    obj: Dict[str, Any] = {
        "apiVersion": f"{group}/v1" if group else "v1",
        "kind": kind,
        "metadata": {"name": "corpus-witness"},
    }
    if labels:
        obj["metadata"]["labels"] = dict(labels)
    if ns:
        obj["metadata"]["namespace"] = ns
    review: Dict[str, Any] = {
        "kind": {"group": group, "version": "v1", "kind": kind},
        "operation": "CREATE",
        "name": "corpus-witness",
        "object": obj,
    }
    if ns:
        review["namespace"] = ns
    return review


def _merge_witness(
    c_ir: Any, m_match: Dict[str, Any]
) -> Optional[Dict[str, Any]]:
    """Witness review selected by BOTH the constraint IR and the
    mutator match, or None. Strategy: build from the tighter merge of
    the two dicts; bail on any dimension both sides constrain
    differently (provably-disjoint or just not worth guessing)."""
    if not isinstance(c_ir, dict) or not isinstance(m_match, dict):
        return None
    merged: Dict[str, Any] = {}
    for dim in (
        "kinds", "scope", "namespaces", "excludedNamespaces",
        "labelSelector", "namespaceSelector",
    ):
        in_c, in_m = dim in c_ir, dim in m_match
        if in_c and in_m:
            if _canon(c_ir.get(dim)) != _canon(m_match.get(dim)):
                if dim == "namespaces":
                    a, b = c_ir.get(dim), m_match.get(dim)
                    if isinstance(a, list) and isinstance(b, list):
                        common = [
                            n for n in a
                            if isinstance(n, str) and n in b
                        ]
                        if common:
                            merged[dim] = common
                            continue
                elif dim == "excludedNamespaces":
                    a, b = c_ir.get(dim), m_match.get(dim)
                    if isinstance(a, list) and isinstance(b, list):
                        merged[dim] = a + b
                        continue
                return None
            merged[dim] = c_ir.get(dim)
        elif in_c:
            merged[dim] = c_ir.get(dim)
        elif in_m:
            merged[dim] = m_match.get(dim)
    return _witness_for_match(merged)


# ---------------------------------------------------------------------------
# the corpus pass


@dataclass
class _TemplateInfo:
    kind: str
    source: str
    template: Optional[Dict[str, Any]]  # raw doc (offline) or None
    report: Any  # VectorizabilityReport
    crd: Any  # templates.CRD or None when uninstantiable


def _constraint_key(c: Dict[str, Any]) -> str:
    name = ((c.get("metadata") or {}).get("name")) or "?"
    return f"{c.get('kind', '?')}/{name}"


def _params_schema(crd: Any) -> Optional[Dict[str, Any]]:
    schema = getattr(crd, "schema", None)
    if not isinstance(schema, dict):
        return None
    spec = (schema.get("properties") or {}).get("spec")
    if not isinstance(spec, dict):
        return None
    params = (spec.get("properties") or {}).get("parameters")
    return params if isinstance(params, dict) else None


def _unknown_keys(
    value: Any, schema: Optional[Dict[str, Any]], path: str
) -> List[str]:
    """Strict unknown-field walk: keys absent from a declared
    ``properties`` map (the permissive CRD validator only rejects them
    under an explicit additionalProperties: false)."""
    out: List[str] = []
    if not isinstance(schema, dict) or not isinstance(value, dict):
        return out
    props = schema.get("properties")
    addl = schema.get("additionalProperties")
    if isinstance(props, dict) and addl in (None, False):
        for k in sorted(value, key=str):
            if k not in props:
                out.append(f"{path}.{k}" if path else str(k))
            else:
                out.extend(
                    _unknown_keys(
                        value[k], props[k],
                        f"{path}.{k}" if path else str(k),
                    )
                )
    items = schema.get("items")
    if isinstance(items, dict) and isinstance(value, list):
        for i, v in enumerate(value):
            out.extend(_unknown_keys(v, items, f"{path}[{i}]"))
    return out


def _eval_violations(
    template_doc: Dict[str, Any],
    constraint: Dict[str, Any],
    review: Dict[str, Any],
) -> Optional[int]:
    """Violation count for one (template, constraint, review) through
    a throwaway stock-interpreter client; None when evaluation could
    not run (invalid template, engine error). Hermetic: never touches
    live serving state."""
    try:
        from ..constraint.client import Backend
        from ..constraint.driver import RegoDriver
        from ..constraint.target import AdmissionRequest, K8sValidationTarget

        client = Backend(RegoDriver()).new_client(K8sValidationTarget())
        client.add_template(template_doc)
        client.add_constraint(constraint)
        responses = client.review(AdmissionRequest(request=review))
        return sum(
            len(r.results) for r in responses.by_target.values()
        )
    except Exception:
        return None


def analyze_corpus(
    templates: Sequence[_TemplateInfo],
    constraints: Sequence[Tuple[str, Dict[str, Any]]],
    mutators: Sequence[Tuple[str, Any]],  # (source, Mutator object)
    providers: Dict[str, bool],  # name -> fail_open
    handler: Any = None,
    max_fight_pairs: int = 256,
) -> CorpusReport:
    """The whole-corpus pass. ``templates`` carry their analyzer
    report + CRD; ``mutators`` are typed Mutator objects; ``providers``
    maps registered names to their fail-open bit."""
    if handler is None:
        from ..constraint.target import K8sValidationTarget

        handler = K8sValidationTarget()

    report = CorpusReport()
    by_kind = {t.kind: t for t in templates}
    report.subjects = (
        len(templates) + len(constraints) + len(mutators) + len(providers)
    )
    # every linted subject gets a row (clean ones included) so the
    # baseline manifest pins the whole corpus, not just the flagged tail
    for t in templates:
        report.lint_for(f"template:{t.kind}", t.source)
    for src, c in constraints:
        report.lint_for(f"constraint:{_constraint_key(c)}", src)
    for m_src, m in mutators:
        report.lint_for(f"mutator:{getattr(m, 'id', '?')}", m_src)

    # -- pass 1: referential integrity --------------------------------------
    for t in templates:
        subject = f"template:{t.kind}"
        rep = t.report
        if rep is None:
            continue
        for prov in rep.external_providers():
            if prov not in providers:
                report.lint_for(subject, t.source).add(CorpusDiagnostic(
                    code="GK-C001",
                    subject=subject,
                    message=(
                        f"external_data names provider {prov!r} which is "
                        f"not registered"
                    ),
                ))
            elif rep.extdata_mode() == "err" and providers.get(prov):
                report.lint_for(subject, t.source).add(CorpusDiagnostic(
                    code="GK-C003",
                    subject=subject,
                    message=(
                        f"error-gated external_data consumes fail-open "
                        f"provider {prov!r}: provider errors resolve "
                        f"open, so the deny-on-error path never fires"
                    ),
                ))

    for src, c in constraints:
        key = _constraint_key(c)
        subject = f"constraint:{key}"
        kind = c.get("kind")
        t = by_kind.get(kind) if isinstance(kind, str) else None
        if t is None:
            report.lint_for(subject, src).add(CorpusDiagnostic(
                code="GK-C002",
                subject=subject,
                message=f"no live template for constraint kind {kind!r}",
            ))
            continue

        # -- pass 2: parameter type-check against the CRD schema ------------
        schema = _params_schema(t.crd)
        params = (c.get("spec") or {}).get("parameters")
        if schema is not None:
            from ..constraint.templates import validate_json_schema

            for err in validate_json_schema(
                params, schema, path="spec.parameters"
            ):
                report.lint_for(subject, src).add(CorpusDiagnostic(
                    code="GK-C004",
                    subject=subject,
                    message=err,
                    path="spec.parameters",
                ))
            for unknown in _unknown_keys(
                params, schema, "spec.parameters"
            ):
                report.lint_for(subject, src).add(CorpusDiagnostic(
                    code="GK-C005",
                    subject=subject,
                    message=(
                        f"parameter {unknown} is unknown to "
                        f"{t.kind}'s schema (silently ignored)"
                    ),
                    path=unknown,
                ))

    # -- pass 3: dead-match proofs + subsumption ----------------------------
    from ..constraint.match import match_needs_ns_selector

    irs: Dict[str, Any] = {}
    live_constraints = [
        (src, c) for src, c in constraints
        if isinstance(c.get("kind"), str) and c.get("kind") in by_kind
    ]
    for src, c in live_constraints:
        key = _constraint_key(c)
        subject = f"constraint:{key}"
        try:
            ir = handler.match_ir(c)
        except Exception:
            continue
        irs[key] = ir
        dead, proof = match_is_dead(ir)
        if dead:
            report.dead_keys.append(key)
            if not match_needs_ns_selector(ir):
                # no namespaceSelector -> no autoreject results either:
                # excluding the row cannot change any merged verdict
                report.prunable_keys.append(key)
            report.lint_for(subject, src).add(CorpusDiagnostic(
                code="GK-C006",
                subject=subject,
                message=f"match is provably unsatisfiable ({proof})",
                path="spec.match",
            ))

    from ..constraint.hooks import enforcement_action, constraint_parameters

    dead_set = set(report.dead_keys)
    for i, (src_b, b) in enumerate(live_constraints):
        key_b = _constraint_key(b)
        if key_b in dead_set or key_b not in irs:
            continue
        for j, (_src_a, a) in enumerate(live_constraints):
            if i == j:
                continue
            key_a = _constraint_key(a)
            if key_a in dead_set or key_a not in irs:
                continue
            if a.get("kind") != b.get("kind"):
                continue
            if _canon(constraint_parameters(a)) != _canon(
                constraint_parameters(b)
            ):
                continue
            if enforcement_action(a) != enforcement_action(b):
                continue
            if not match_subsumes(irs[key_a], irs[key_b]):
                continue
            if _canon(irs[key_a]) == _canon(irs[key_b]) and key_a > key_b:
                continue  # identical matches: only the later name warns
            subject = f"constraint:{key_b}"
            report.shadowed[key_b] = key_a
            report.lint_for(subject, src_b).add(CorpusDiagnostic(
                code="GK-C007",
                subject=subject,
                message=(
                    f"shadowed by {key_a}: same template, parameters "
                    f"and enforcementAction with a superset match"
                ),
                path="spec.match",
            ))
            break

    # -- pass 4: mutate<->validate interference -----------------------------
    pairs_tried = 0
    for m_src, m in mutators:
        m_match = getattr(m, "match", None)
        if not isinstance(m_match, dict):
            continue
        for src, c in live_constraints:
            key = _constraint_key(c)
            if key in dead_set or key not in irs:
                continue
            t = by_kind.get(c.get("kind"))
            if t is None or t.template is None or t.report is None:
                continue
            # only validators the analyzer can evaluate hermetically:
            # external_data calls would fetch during witness evaluation
            if t.report.external_calls or not t.report.compilable:
                continue
            if pairs_tried >= max_fight_pairs:
                break
            pairs_tried += 1
            witness = _merge_witness(irs[key], m_match)
            if witness is None:
                continue
            obj = witness.get("object")
            gvk = witness.get("kind") or {}
            try:
                if not m.applies_to(
                    gvk.get("group", ""), gvk.get("version", ""),
                    gvk.get("kind", ""),
                ):
                    continue
                mutated, changed = m.apply(copy.deepcopy(obj), witness)
            except Exception:
                continue
            if not changed:
                continue
            pre = _eval_violations(t.template, c, witness)
            if pre is None or pre > 0:
                continue
            post_review = dict(witness)
            post_review["object"] = mutated
            post = _eval_violations(t.template, c, post_review)
            if post is None or post == 0:
                continue
            mid = getattr(m, "id", "?")
            subject = f"mutator:{mid}"
            report.lint_for(subject, m_src).add(CorpusDiagnostic(
                code="GK-C008",
                subject=subject,
                message=(
                    f"admission fight with {key}: writing "
                    f"{getattr(m, 'location', '?')} turns a clean "
                    f"witness into a violation — every matching "
                    f"request 500s at the mutate/validate fixpoint"
                ),
                path=str(getattr(m, "location", "")),
            ))

    report.dead_keys.sort()
    report.prunable_keys.sort()
    return report


# ---------------------------------------------------------------------------
# corpus assembly (offline docs / live registries)


def corpus_from_docs(
    template_docs: Sequence[Tuple[str, Dict[str, Any]]],
    constraint_docs: Sequence[Tuple[str, Dict[str, Any]]],
    mutator_docs: Sequence[Tuple[str, Dict[str, Any]]],
    provider_docs: Sequence[Tuple[str, Dict[str, Any]]],
    max_fight_pairs: int = 256,
) -> CorpusReport:
    """Offline entry: raw YAML docs (the CLI collectors' output)."""
    from ..constraint.target import K8sValidationTarget
    from ..constraint.templates import ConstraintTemplate, create_crd
    from ..mutation.mutators import MutatorError, mutator_from_obj
    from .analyzer import analyze_template

    handler = K8sValidationTarget()
    templates: List[_TemplateInfo] = []
    for src, doc in template_docs:
        rep = analyze_template(doc)
        crd = None
        try:
            ct = ConstraintTemplate.from_dict(doc)
            crd = create_crd(ct, handler.match_schema())
        except Exception:
            pass
        templates.append(_TemplateInfo(
            kind=rep.kind, source=src, template=doc, report=rep, crd=crd,
        ))

    mutators: List[Tuple[str, Any]] = []
    for src, doc in mutator_docs:
        try:
            mutators.append((src, mutator_from_obj(doc)))
        except MutatorError:
            continue  # the mutators lint owns spec errors

    providers: Dict[str, bool] = {}
    for _src, doc in provider_docs:
        name = ((doc.get("metadata") or {}).get("name"))
        if not isinstance(name, str) or not name:
            continue
        policy = str(((doc.get("spec") or {}).get("failurePolicy") or ""))
        providers[name] = policy.lower() in (
            "ignore", "open", "fail-open", "",
        )

    return analyze_corpus(
        templates, list(constraint_docs), mutators, providers,
        handler=handler, max_fight_pairs=max_fight_pairs,
    )


def corpus_from_live(
    client: Any,
    mutation_system: Any = None,
    external_data: Any = None,
    max_fight_pairs: int = 256,
) -> CorpusReport:
    """Live entry: the same registries the serving planes hold."""
    templates: List[_TemplateInfo] = []
    handler = None
    with client._lock:
        entries = list(client._templates.values())
        constraint_map = {
            gk: dict(sub) for gk, sub in client._constraints.items()
        }
        for h in client.targets.values():
            handler = h
            break
    for e in entries:
        kind = e.crd.kind
        raw = getattr(e.template, "raw", None)
        templates.append(_TemplateInfo(
            kind=kind,
            source="live",
            # the retained source doc lets the fight pass re-ingest the
            # template into a throwaway interpreter client hermetically
            template=raw if isinstance(raw, dict) and raw else None,
            report=getattr(e.template, "vectorizability", None),
            crd=e.crd,
        ))
    constraints: List[Tuple[str, Dict[str, Any]]] = []
    for _gk, sub in sorted(constraint_map.items()):
        for _subpath, c in sorted(sub.items()):
            constraints.append(("live", c))

    mutators: List[Tuple[str, Any]] = []
    if mutation_system is not None:
        try:
            mutators = [("live", m) for m in mutation_system.ordered()]
        except Exception:
            mutators = []

    providers: Dict[str, bool] = {}
    if external_data is not None:
        try:
            for name in external_data.names():
                p = external_data.get(name)
                providers[name] = bool(getattr(p, "fail_open", True))
        except Exception:
            providers = {}

    return analyze_corpus(
        templates, constraints, mutators, providers,
        handler=handler, max_fight_pairs=max_fight_pairs,
    )


# ---------------------------------------------------------------------------
# serving-side plane


class CorpusPlane:
    """Debounced corpus recompute bound to the live registries.

    The report is recomputed on a background thread when the observed
    churn generation moves — NEVER in the request path. The partition
    planner asks for ``prunable_keys(target, gen)``; the answer is
    only non-empty when the cached report was computed at exactly the
    requested generation (a stale report prunes nothing — missing a
    pruning window is safe, pruning a live constraint is not)."""

    def __init__(
        self,
        client: Any,
        mutation_system: Any = None,
        external_data: Any = None,
        metrics: Any = None,
        debounce_s: float = 1.0,
        clock=None,
    ):
        import time as _time

        self.client = client
        self.mutation_system = mutation_system
        self.external_data = external_data
        self.metrics = metrics
        self.debounce_s = debounce_s
        self.clock = clock or _time.monotonic
        self._lock = threading.Lock()
        self._report: Optional[CorpusReport] = None
        self._computed_gen: Optional[Tuple[int, int]] = None
        self._last_recompute = -float("inf")
        self._pending: Optional[threading.Thread] = None
        self.recomputes = 0

    # -- generation observation ---------------------------------------------

    def _gen(self) -> Tuple[int, int]:
        cgen = 0
        gen_fn = getattr(self.client._driver, "constraint_generation", None)
        if gen_fn is not None:
            try:
                cgen = int(gen_fn())
            except Exception:
                cgen = 0
        mgen = 0
        if self.mutation_system is not None:
            try:
                mgen = int(self.mutation_system.generation)
            except Exception:
                mgen = 0
        return cgen, mgen

    # -- recompute ----------------------------------------------------------

    def refresh(self, force: bool = False) -> CorpusReport:
        """Synchronous recompute (CLI, tests, startup). Debounce does
        not apply; `force` additionally recomputes at an unchanged
        generation."""
        gen = self._gen()
        with self._lock:
            if (
                not force
                and self._report is not None
                and self._computed_gen == gen
            ):
                return self._report
        report = corpus_from_live(
            self.client, self.mutation_system, self.external_data,
        )
        with self._lock:
            self._report = report
            self._computed_gen = gen
            self._last_recompute = self.clock()
            self.recomputes += 1
        self._export(report)
        return report

    def maybe_recompute(self) -> bool:
        """Debounced background recompute when the generation moved;
        True when a recompute thread was started. Cheap enough for the
        planner's miss path — generation compare + time compare."""
        gen = self._gen()
        with self._lock:
            if self._report is not None and self._computed_gen == gen:
                return False
            if self._pending is not None and self._pending.is_alive():
                return False
            if self.clock() - self._last_recompute < self.debounce_s:
                return False
            t = threading.Thread(
                target=self._recompute_bg, name="corpus-analysis",
                daemon=True,
            )
            self._pending = t
        t.start()
        return True

    def _recompute_bg(self) -> None:
        try:
            self.refresh(force=True)
        except Exception:
            pass  # analysis must never take serving down

    def _export(self, report: CorpusReport) -> None:
        if self.metrics is None:
            return
        try:
            counts = report.counts()
            for code in CORPUS_CODES:
                self.metrics.gauge(
                    "corpus_diagnostics_total", counts.get(code, 0),
                    code=code,
                )
        except Exception:
            pass

    # -- planner / readyz feeds ----------------------------------------------

    def prunable_keys(self, target: str, gen: int) -> frozenset:
        """Constraint keys provably safe to exclude from dispatch rows
        at constraint generation `gen`; empty unless the cached report
        was computed at that exact generation (stale = prune nothing).
        `target` is accepted for planner symmetry — keys are already
        the per-target row ids."""
        with self._lock:
            report, cgen = self._report, self._computed_gen
        if report is None or cgen is None or cgen[0] != gen:
            self.maybe_recompute()
            return frozenset()
        return frozenset(report.prunable_keys)

    def shadowed_keys(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._report.shadowed) if self._report else {}

    def report(self) -> Optional[CorpusReport]:
        with self._lock:
            return self._report

    def snapshot(self) -> Dict[str, Any]:
        """/readyz `stats.analysis.corpus` view."""
        gen = self._gen()
        with self._lock:
            report, cgen = self._report, self._computed_gen
            recomputes = self.recomputes
        out: Dict[str, Any] = {
            "computed": report is not None,
            "stale": cgen != gen,
            "recomputes": recomputes,
        }
        if report is not None:
            out.update({
                "ok": report.ok,
                "subjects": report.subjects,
                "counts": report.counts(),
                "dead": len(report.dead_keys),
                "prunable": len(report.prunable_keys),
                "shadowed": len(report.shadowed),
            })
        return out
