"""Static vectorizability analysis over ConstraintTemplate Rego ASTs.

Runs at template-admission time (and offline via ``python -m
gatekeeper_tpu.analysis``) and predicts how the symbolic compiler
(`engine/symbolic.py`) will route the template, WITHOUT compiling it:

  * a **binding analysis** — the safety reorder (`rego/safety.py`)
    extended into a full bound-before-use checker with rule/line
    provenance (unsafe variables are unevaluable everywhere: INVALID);
  * a **feature audit** — every construct is checked against the
    symbolic compiler's actual capability set (builtin handler table,
    ref-walk shapes, comprehension kinds, iteration fanout) and mapped
    to a stable ``GK-Vxxx`` diagnostic code.

The verdict models `engine.programs.compile_program`'s retry chain
faithfully enough to be consulted *instead of* try/except routing:

  * constructs that abort even the screen-mode retry (with modifiers,
    ``every``, >2 nested array iterations, dynamic ref heads, fixed
    array indexing of review arrays, ...) are **hard** — the template
    is INTERPRETER;
  * constructs the screen retry absorbs (unsupported builtins over
    symbolic values, object comprehensions, inventory joins) are
    **soft** — the template still compiles, as a screen: PARTIAL_ROWS.
    Call and comprehension subtrees are themselves soft contexts (the
    screen-mode compiler catches failures there and degrades to opaque
    values), so hard findings inside them downgrade to PARTIAL_ROWS.

The analyzer is deliberately conservative in one direction only: a
VECTORIZED verdict is a *promise* that ``compile_program`` will not
raise ``CompileUnsupported`` (tests/test_analysis.py sweeps the promise
against the real compiler); PARTIAL_ROWS makes no exactness claim.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from ..rego import ast as A
from ..rego import safety
from ..rego.builtins import BUILTINS
from .report import (
    INTERPRETER,
    INVALID,
    PARTIAL_ROWS,
    VectorizabilityReport,
)

# builtins with symbolic handlers in engine/symbolic.py (Compiler
# ``_builtin_*`` methods plus the destructuring `split` special case):
# these accept review-derived arguments and stay on-device
SYMBOLIC_BUILTINS: Set[str] = {
    "count",
    "any",
    "all",
    "re_match",
    "regex.match",
    "startswith",
    "endswith",
    "contains",
    "lower",
    "upper",
    "trim",
    "trim_prefix",
    "sprintf",
    "concat",
    "is_number",
    "is_string",
    "is_array",
    "to_number",
    "split",
}


# -- abstract values --------------------------------------------------------


@dataclass(frozen=True)
class AVal:
    """Abstract value domain for the dataflow walk.

    domain:
      "const"   — literals / input.parameters / folded results
      "review"  — the review document or a sub-document/leaf of it
      "inv"     — data.inventory-derived (opaque to the compiler)
      "opaque"  — derived symbolic value (call results, set elements)
    depth: array-iteration levels opened along a review walk (the
      compiler's "#" levels; 3+ aborts compilation).
    key: value is a symbolic string usable as an object-join key
      (captured iteration keys, leaf scalars).
    """

    domain: str = "opaque"
    depth: int = 0
    key: bool = False


CONST = AVal("const")
OPAQUE = AVal("opaque")
INV = AVal("inv")


def _join(a: AVal, b: AVal) -> AVal:
    if "inv" in (a.domain, b.domain):
        return INV
    if a.domain == b.domain == "const":
        return CONST
    if "review" in (a.domain, b.domain):
        d = a if a.domain == "review" else b
        return AVal("review", depth=max(a.depth, b.depth), key=d.key)
    return AVal("opaque", depth=max(a.depth, b.depth))


# -- analyzer ---------------------------------------------------------------


@dataclass
class _Ctx:
    """Per-rule walk context."""

    env: Dict[str, AVal] = field(default_factory=dict)
    rule: str = ""
    # `:=`-assigned value terms by var name: lets the external_data
    # audit resolve `keys: images` back to the comprehension that
    # built `images` (extraction needs the defining expression)
    defs: Dict[str, Any] = field(default_factory=dict)
    rule_ast: Optional[A.Rule] = None


class Analyzer:
    def __init__(self, kind: str, modules: Sequence[A.Module]):
        self.kind = kind
        self.modules = list(modules)
        self.report = VectorizabilityReport(kind=kind)
        self.rules: Dict[str, List[A.Rule]] = {}
        for mod in self.modules:
            for rule in mod.rules:
                self.rules.setdefault(rule.head.name, []).append(rule)
        self._known = safety.module_known(
            self.modules[0] if self.modules else A.Module(),
            set(self.rules),
        )
        for mod in self.modules[1:]:
            self._known |= safety.module_known(mod, set(self.rules))
        # soft-context depth: >0 inside call/comprehension subtrees,
        # where screen-mode compilation absorbs failures
        self._soft = 0
        self._analyzed_rules: Set[int] = set()
        self._seen_diags: Set[Tuple] = set()
        # rule identity -> owning module: external_data call records
        # carry the module so key extraction can evaluate their keys
        # expression later (externaldata/extract.py)
        self._rule_module: Dict[int, A.Module] = {}
        for mod in self.modules:
            for rule in mod.rules:
                self._rule_module[id(rule)] = mod
        # the := target whose value term is currently being evaluated
        # (identity-matched): lets _eval_call know which var binds an
        # external_data response
        self._assign_target: Optional[str] = None
        self._assign_value: Any = None

    # -- diagnostics --------------------------------------------------------

    def _diag(
        self, code: str, message: str, rule: str, line: int,
        severity: str = "",
    ) -> None:
        if not severity and self._soft:
            # inside a call/comprehension the screen retry absorbs hard
            # failures: cap at PARTIAL_ROWS instead of the code default
            from .report import CODES, VERDICT_ORDER

            default_cap = CODES.get(code, ("", PARTIAL_ROWS))[1]
            if VERDICT_ORDER.index(default_cap) > VERDICT_ORDER.index(
                PARTIAL_ROWS
            ) and default_cap != INVALID:
                severity = PARTIAL_ROWS
        key = (code, message, rule, line, severity)
        if key in self._seen_diags:
            return
        self._seen_diags.add(key)
        self.report.add(
            code, message, rule=rule, line=line, severity=severity
        )

    # -- entry --------------------------------------------------------------

    def run(self) -> VectorizabilityReport:
        violations = self.rules.get("violation")
        if not violations:
            self._diag(
                "GK-V008", "no `violation` rule defined", "", 0,
                severity=INVALID,
            )
            return self.report
        for rule in violations:
            if rule.head.key is None:
                self._diag(
                    "GK-V008",
                    "`violation` must be a partial set rule "
                    "(violation[{...}])",
                    "violation",
                    rule.line,
                    severity=INVALID,
                )
            if rule.is_default or rule.else_rule is not None:
                self._diag(
                    "GK-V007",
                    "default/else `violation` rule is outside the "
                    "compilable subset",
                    "violation",
                    rule.line,
                )
        # binding analysis over every rule (helpers included: they are
        # all reachable from violation bodies in library templates, and
        # an unsafe helper is unevaluable on any engine)
        for mod in self.modules:
            for rule in mod.rules:
                self._check_bindings(rule)
        # feature audit from the entrypoint
        for rule in violations:
            self._audit_rule(rule)
        return self.report

    # -- binding analysis (GK-V005) -----------------------------------------

    def _check_bindings(self, rule: A.Rule) -> None:
        bound0: Set[str] = set()
        for formal in rule.head.args or []:
            if isinstance(formal, A.Var):
                bound0.add(formal.name)
        self._check_body_bindings(rule.body, bound0, rule)
        # rule head terms must be fully bound by the body
        bound = set(bound0)
        for e in rule.body:
            bound |= safety.all_vars(e, self._known)
        for part in (rule.head.key, rule.head.value):
            if part is None:
                continue
            missing = sorted(
                safety.needed_value(part, self._known) - bound
            )
            if missing:
                self._diag(
                    "GK-V005",
                    f"var(s) {', '.join(missing)} in rule head are "
                    "never bound in the body",
                    rule.head.name,
                    rule.line,
                    severity=INVALID,
                )
        if rule.else_rule is not None:
            self._check_bindings(rule.else_rule)

    def _check_body_bindings(
        self, body: List[A.Expr], bound0: Set[str], rule: A.Rule
    ) -> None:
        """Greedy schedulability fixpoint: any expression that can never
        be scheduled — no order of the body binds the vars it consumes —
        is a bound-before-use violation (OPA: 'var x is unsafe').

        Unlike `safety.reorder_body` (which must preserve evaluation
        order and so consults comprehension needs against a FIXED known
        set), outer-bound vars here fold into `known` between rounds:
        `comprehension_needed` over-approximates by counting locals
        blocked on outer vars, and treating bound vars as known is what
        discharges those."""
        remaining = list(body)
        bound = set(bound0)
        progress = True
        while remaining and progress:
            progress = False
            for i, e in enumerate(remaining):
                if safety.can_schedule(e, bound, self._known | bound):
                    bound |= safety.all_vars(e, self._known)
                    remaining.pop(i)
                    progress = True
                    break
        for e in remaining:  # permanently unschedulable
            missing = sorted(
                safety.expr_needed(e, self._known | bound) - bound
            )
            if missing:
                self._diag(
                    "GK-V005",
                    f"var(s) {', '.join(missing)} used before any "
                    "expression can bind them",
                    rule.head.name,
                    getattr(e, "line", 0) or rule.line,
                    severity=INVALID,
                )
            bound |= safety.all_vars(e, self._known)
        # recurse into comprehension bodies with the outer bound set so
        # internally-unsafe comprehensions get their own provenance
        for e in body:
            for comp in _comprehensions_in(e):
                self._check_body_bindings(comp.body, set(bound), rule)

    # -- feature audit ------------------------------------------------------

    def _audit_rule(self, rule: A.Rule, formals_from: str = "") -> None:
        """Audit one rule body (memoized by identity)."""
        if id(rule) in self._analyzed_rules:
            return
        self._analyzed_rules.add(id(rule))
        ctx = _Ctx(rule=rule.head.name, rule_ast=rule)
        for formal in rule.head.args or []:
            if isinstance(formal, A.Var):
                ctx.env[formal.name] = OPAQUE
        for expr in rule.body:
            self._audit_expr(expr, ctx)
        if rule.head.key is not None:
            self._eval_term(rule.head.key, ctx)
        if rule.head.value is not None:
            self._eval_term(rule.head.value, ctx)
        if rule.else_rule is not None:
            self._audit_rule(rule.else_rule)

    def _audit_expr(self, expr: A.Expr, ctx: _Ctx) -> None:
        if isinstance(expr, A.SomeDecl):
            return
        if isinstance(expr, A.WithExpr):
            self._diag(
                "GK-V007",
                "`with` modifier is outside the compilable subset",
                ctx.rule,
                expr.line,
            )
            self._audit_expr(expr.expr, ctx)
            return
        if isinstance(expr, A.Every):
            self._diag(
                "GK-V007",
                "`every` is outside the compilable subset",
                ctx.rule,
                expr.line,
            )
            return
        if isinstance(expr, A.NotExpr):
            self._audit_expr(expr.expr, ctx)
            return
        if isinstance(expr, A.Assign):
            self._audit_assign(expr.target, expr.value, ctx)
            return
        if isinstance(expr, A.Unify):
            lhs, rhs = expr.lhs, expr.rhs
            lv = isinstance(lhs, A.Var) and lhs.name not in ctx.env
            rv = isinstance(rhs, A.Var) and rhs.name not in ctx.env
            if lv and not rv:
                self._audit_assign(lhs, rhs, ctx)
            elif rv and not lv:
                self._audit_assign(rhs, lhs, ctx)
            else:
                self._eval_term(lhs, ctx)
                self._eval_term(rhs, ctx)
            return
        if isinstance(expr, A.TermExpr):
            self._eval_term(expr.term, ctx)
            return

    def _audit_assign(self, target: A.Term, value: A.Term, ctx: _Ctx):
        prev_t, prev_v = self._assign_target, self._assign_value
        if isinstance(target, A.Var):
            self._assign_target, self._assign_value = target.name, value
        try:
            val = self._eval_term(value, ctx)
        finally:
            self._assign_target, self._assign_value = prev_t, prev_v
        if isinstance(target, A.Var):
            ctx.env[target.name] = val
            ctx.defs[target.name] = value
            return
        if isinstance(target, A.Wildcard):
            return
        if isinstance(target, A.ArrayTerm):
            ok_split = (
                isinstance(value, A.Call)
                and value.name == "split"
                and len(value.args) == 2
            )
            for t in target.items:
                if not isinstance(t, (A.Var, A.Wildcard)):
                    self._diag(
                        "GK-V007",
                        "array destructuring target must be all "
                        "variables",
                        ctx.rule,
                        target.line,
                    )
                    return
            if not ok_split and val.domain == "review":
                self._diag(
                    "GK-V007",
                    "array destructuring of a review document is "
                    "outside the compilable subset (only `split` and "
                    "fixed lists destructure)",
                    ctx.rule,
                    target.line,
                )
            part = AVal("opaque", key=True)
            for t in target.items:
                if isinstance(t, A.Var):
                    ctx.env[t.name] = part
            return
        # object-pattern / nested destructuring
        self._diag(
            "GK-V007",
            "destructuring assignment target shape is outside the "
            "compilable subset",
            ctx.rule,
            getattr(target, "line", 0),
        )

    # -- terms --------------------------------------------------------------

    def _eval_term(self, term: A.Term, ctx: _Ctx) -> AVal:
        if isinstance(term, A.Scalar):
            return CONST
        if isinstance(term, A.Wildcard):
            return OPAQUE
        if isinstance(term, A.Var):
            if term.name in ctx.env:
                return ctx.env[term.name]
            if term.name in self.rules:
                return self._rule_value(term.name, ctx, term.line)
            return OPAQUE  # unbound: the binding analysis owns this
        if isinstance(term, A.Ref):
            return self._eval_ref(term, ctx)
        if isinstance(term, A.Call):
            return self._eval_call(term, ctx)
        if isinstance(term, A.BinOp):
            lv = self._eval_term(term.lhs, ctx)
            rv = self._eval_term(term.rhs, ctx)
            return _join(lv, rv)
        if isinstance(term, A.UnaryMinus):
            v = self._eval_term(term.operand, ctx)
            if v.domain != "const":
                self._diag(
                    "GK-V007",
                    "unary minus of a symbolic value is outside the "
                    "compilable subset",
                    ctx.rule,
                    term.line,
                )
            return CONST
        if isinstance(term, (A.ArrayTerm, A.SetTerm)):
            out = CONST
            for item in term.items:
                out = _join(out, self._eval_term(item, ctx))
            return replace(out, key=False)
        if isinstance(term, A.ObjectTerm):
            out = CONST
            for k, v in term.items:
                out = _join(out, self._eval_term(k, ctx))
                out = _join(out, self._eval_term(v, ctx))
            return replace(out, key=False)
        if isinstance(term, A.Comprehension):
            return self._eval_comprehension(term, ctx)
        return OPAQUE

    def _eval_comprehension(self, term: A.Comprehension, ctx: _Ctx) -> AVal:
        if term.kind == "object":
            self._diag(
                "GK-V002",
                "object comprehensions compile only as a screen "
                "(opaque value; conditions on it re-check on the "
                "interpreter)",
                ctx.rule,
                term.line,
            )
        # comprehension bodies are a soft context: the screen-mode
        # compiler catches failures here and degrades to opaque
        self._soft += 1
        try:
            sub = _Ctx(
                env=dict(ctx.env), rule=ctx.rule,
                defs=dict(ctx.defs), rule_ast=ctx.rule_ast,
            )
            for e in term.body:
                self._audit_expr(e, sub)
            head = self._eval_term(term.head, sub)
            if term.key is not None:
                head = _join(head, self._eval_term(term.key, sub))
        finally:
            self._soft -= 1
        return replace(head, key=False)

    # -- refs ---------------------------------------------------------------

    def _eval_ref(self, ref: A.Ref, ctx: _Ctx) -> AVal:
        if not isinstance(ref.head, A.Var):
            self._diag(
                "GK-V004",
                "computed ref head (expression indexed directly) is "
                "outside the compilable subset",
                ctx.rule,
                ref.line,
            )
            return OPAQUE
        name = ref.head.name
        if name == "input":
            if not ref.ops or not isinstance(ref.ops[0], A.Scalar):
                self._diag(
                    "GK-V004",
                    "dynamic access into `input` is outside the "
                    "compilable subset",
                    ctx.rule,
                    ref.line,
                )
                return OPAQUE
            first = ref.ops[0].value
            if first == "review":
                return self._walk(AVal("review"), ref.ops[1:], ctx, ref)
            if first == "parameters":
                return self._walk(CONST, ref.ops[1:], ctx, ref)
            self._diag(
                "GK-V004",
                f"`input.{first}` is not a compilable document root "
                "(only input.review / input.parameters)",
                ctx.rule,
                ref.line,
            )
            return OPAQUE
        if name == "data":
            if (
                ref.ops
                and isinstance(ref.ops[0], A.Scalar)
                and ref.ops[0].value == "inventory"
            ):
                self._diag(
                    "GK-V006",
                    "data.inventory join: compiles as a screen "
                    "(device pre-filter + interpreter re-check of "
                    "flagged rows)",
                    ctx.rule,
                    ref.line,
                )
                return self._walk(INV, ref.ops[1:], ctx, ref)
            # rewritten lib refs (data.libs.<Kind>.lib...) resolve to
            # mounted rules; anything else was allowlist-rejected
            tail = _ref_tail_rule(ref)
            if tail is not None and tail in self.rules:
                base = self._rule_value(tail, ctx, ref.line)
                return self._walk(base, [], ctx, ref)
            return OPAQUE
        if name in ctx.env:
            return self._walk(ctx.env[name], ref.ops, ctx, ref)
        if name in self.rules:
            base = self._rule_value(name, ctx, ref.line)
            return self._walk(base, ref.ops, ctx, ref, rule_ref=name)
        return OPAQUE  # unbound head: binding analysis owns it

    def _rule_value(self, name: str, ctx: _Ctx, line: int) -> AVal:
        """Referencing a rule as a value (complete rule / partial set)."""
        rules = self.rules[name]
        kind = rules[0].head.kind
        for rule in rules:
            # rule bodies referenced by ref are a HARD context (the
            # compiler evaluates them inline, uncaught)
            self._audit_rule(rule)
        if kind == "complete":
            if len(rules) > 1:
                self._diag(
                    "GK-V007",
                    f"rule `{name}` has multiple/default definitions; "
                    "computed complete-rule refs are outside the "
                    "compilable subset",
                    ctx.rule,
                    line,
                )
                return OPAQUE
            rule = rules[0]
            if rule.body and _touches_review(rule.body):
                self._diag(
                    "GK-V007",
                    f"complete rule `{name}` computes over the review "
                    "document; only concretely-resolvable rule bodies "
                    "compile",
                    ctx.rule,
                    line,
                )
            return OPAQUE if rule.body else CONST
        if kind == "func":
            self._diag(
                "GK-V007",
                f"function `{name}` referenced as a value",
                ctx.rule,
                line,
            )
        return OPAQUE

    def _walk(
        self,
        base: AVal,
        ops: Sequence[A.Term],
        ctx: _Ctx,
        ref: A.Ref,
        rule_ref: Optional[str] = None,
    ) -> AVal:
        cur = base
        for i, op in enumerate(ops):
            if cur.domain == "inv":
                # inventory walks stay opaque; unbound var segments
                # bind opaquely (mirrors SInventory._walk_one)
                if isinstance(op, A.Var) and op.name not in ctx.env:
                    ctx.env[op.name] = AVal("inv")
                continue
            if cur.domain == "const":
                if isinstance(op, (A.Scalar, A.Wildcard)):
                    continue
                if isinstance(op, A.Var):
                    if op.name not in ctx.env:
                        ctx.env[op.name] = AVal("const", key=True)
                    continue
                self._diag(
                    "GK-V004",
                    "computed key into a parameters/constant document",
                    ctx.rule,
                    ref.line,
                )
                return OPAQUE
            if cur.domain == "review":
                cur = self._walk_review(cur, op, ctx, ref, rule_ref, i)
                if cur is None:
                    return OPAQUE
                continue
            # opaque base: iterating/indexing an opaque value — the
            # compiler raises on SMsg/SDerived walks but returns [] for
            # most leaf walks; partial-set rule refs iterate fine.
            if isinstance(op, A.Var) and op.name not in ctx.env:
                ctx.env[op.name] = AVal("opaque", key=True)
            cur = OPAQUE
        return cur

    def _walk_review(
        self,
        cur: AVal,
        op: A.Term,
        ctx: _Ctx,
        ref: A.Ref,
        rule_ref: Optional[str],
        op_idx: int,
    ) -> Optional[AVal]:
        if isinstance(op, A.Scalar):
            if isinstance(op.value, str):
                return cur
            self._diag(
                "GK-V007",
                "fixed array index into the review document is "
                "outside the compilable subset (iterate with `[_]`)",
                ctx.rule,
                ref.line,
            )
            return None
        if isinstance(op, A.Wildcard) or (
            isinstance(op, A.Var) and op.name not in ctx.env
        ):
            depth = cur.depth + 1
            if depth >= 3:
                self._diag(
                    "GK-V003",
                    "3+ nested array iterations over the review "
                    "document exceed the device fanout axes "
                    "(g0 x g1 cross-join cap)",
                    ctx.rule,
                    ref.line,
                )
                return None
            if isinstance(op, A.Var):
                ctx.env[op.name] = AVal("review", depth=depth, key=True)
            return AVal("review", depth=depth, key=True)
        if isinstance(op, A.Var):  # bound key var
            kv = ctx.env[op.name]
            if kv.domain == "const":
                return cur
            if cur.depth > 0:
                self._diag(
                    "GK-V007",
                    "symbolic-key join under an open array iteration "
                    "is outside the compilable subset",
                    ctx.rule,
                    ref.line,
                )
                return None
            return AVal("review", depth=cur.depth, key=True)
        # computed key (call/binop/...): the ref-walk raises
        self._diag(
            "GK-V004",
            "computed key segment in a review document walk",
            ctx.rule,
            ref.line,
        )
        return None

    # -- calls --------------------------------------------------------------

    def _eval_call(self, call: A.Call, ctx: _Ctx) -> AVal:
        if call.name == "external_data":
            return self._eval_external_data(call, ctx)
        args = [self._eval_term(a, ctx) for a in call.args]
        name = call.name
        base = name.split(".")[-1] if "." in name else name
        if any(a.domain == "inv" for a in args):
            # calls over inventory values go opaque (screen); already
            # diagnosed at the data.inventory ref site
            return INV
        if base in self.rules and self.rules[base][0].head.kind == "func":
            sym = [a for a in args if a.domain != "const"]
            if len(sym) <= 1 and self._fn_tableizable(base):
                # pure scalar helper with at most one symbolic slot:
                # the compiler tableizes it per vocab entry via the
                # interpreter oracle (engine/symbolic._tableize_function)
                # — any builtin is allowed inside, it runs host-side
                out = AVal("opaque", key=True)
                for a in args:
                    out = _join(out, a)
                return replace(out, key=True)
            # general user function: body failures fall back to
            # tableization and then the screen retry — a soft context
            self._soft += 1
            try:
                for rule in self.rules[base]:
                    self._audit_rule(rule)
            finally:
                self._soft -= 1
            out = OPAQUE
            for a in args:
                out = _join(out, a)
            return replace(out, key=False)
        if name in SYMBOLIC_BUILTINS:
            sym = [a for a in args if a.domain != "const"]
            if name in ("re_match", "regex.match") and args and (
                args[0].domain != "const"
            ):
                self._diag(
                    "GK-V001",
                    "re_match with a non-constant pattern compiles "
                    "only as a screen",
                    ctx.rule,
                    call.line,
                )
            out = CONST if not sym else AVal("opaque", key=True)
            return out
        if name in BUILTINS:
            if any(a.domain != "const" for a in args):
                self._diag(
                    "GK-V001",
                    f"builtin `{name}` has no symbolic (vectorized) "
                    "lowering; applied to review-derived values it "
                    "compiles only as a screen",
                    ctx.rule,
                    call.line,
                )
            return CONST if all(
                a.domain == "const" for a in args
            ) else OPAQUE
        # unknown builtin: the interpreter will reject it too
        self._diag(
            "GK-V001",
            f"unknown builtin `{name}`",
            ctx.rule,
            call.line,
            severity=INTERPRETER,
        )
        return OPAQUE


    # -- external_data (GK-V009) --------------------------------------------

    def _eval_external_data(self, call: A.Call, ctx: _Ctx) -> AVal:
        """Record the call site and classify its batchability. The
        template compiles as a screen either way (the compiler treats
        the response as opaque); the classification decides how sharp
        the batch plane can be: extractable keys prefetch in one fetch
        per (provider, micro-batch), and an error-gated rule body lets
        clean-cache-hit rows skip the interpreter entirely."""
        from .report import ExternalDataCall

        for a in call.args:  # arg values still walk (diagnose refs)
            self._eval_term(a, ctx)
        spec = ExternalDataCall(rule=ctx.rule, line=call.line)
        detail = ""
        arg = call.args[0] if len(call.args) == 1 else None
        if not isinstance(arg, A.ObjectTerm):
            detail = "argument must be a literal object"
        else:
            fields: Dict[str, A.Term] = {}
            for k, v in arg.items:
                if isinstance(k, A.Scalar) and isinstance(k.value, str):
                    fields[k.value] = v
            prov = fields.get("provider")
            if isinstance(prov, A.Scalar) and isinstance(prov.value, str):
                spec.provider = prov.value
            else:
                detail = (
                    "provider must be a literal string (non-literal "
                    "providers cannot batch-prefetch)"
                )
            keys = fields.get("keys")
            resolved = self._resolve_keys_term(keys, ctx)
            if resolved is not None and self._keys_input_only(
                resolved, ctx, set()
            ):
                spec.keys_term = resolved
                rule_ast = ctx.rule_ast
                spec.module = (
                    self._rule_module.get(id(rule_ast))
                    if rule_ast is not None
                    else None
                )
                spec.extractable = spec.provider is not None
            elif not detail:
                detail = (
                    "keys expression is not input-derived; lookups "
                    "cannot batch-prefetch (per-call fetch at resolve "
                    "time)"
                )
        if (
            call is self._assign_value
            and self._assign_target is not None
            and ctx.rule_ast is not None
        ):
            spec.respvar = self._assign_target
            spec.error_gated = self._requires_errors(
                ctx.rule_ast.body, self._assign_target
            )
        self.report.external_calls.append(spec)
        msg = (
            "external_data: lookups ride the micro-batch (one fetch "
            "per provider per batch); compiles as a screen — "
            + (
                "clean cache-hit rows stay fused, cold-miss rows "
                "re-check on the interpreter"
                if spec.error_gated and spec.extractable
                else "matching rows re-check on the interpreter"
            )
        )
        if detail:
            msg += f" ({detail})"
        self._diag("GK-V009", msg, ctx.rule, call.line)
        return INV

    def _resolve_keys_term(
        self, term: Optional[A.Term], ctx: _Ctx, depth: int = 0
    ) -> Optional[A.Term]:
        """Follow `keys: somevar` through := definitions (bounded)."""
        if term is None or depth > 4:
            return None if term is None else term
        if isinstance(term, A.Var) and term.name in ctx.defs:
            return self._resolve_keys_term(
                ctx.defs[term.name], ctx, depth + 1
            )
        return term

    def _keys_input_only(
        self, term: A.Term, ctx: _Ctx, locals_: Set[str], depth: int = 0
    ) -> bool:
        """True when the keys expression depends only on input.review
        (plus literals and its own local bindings) — the condition for
        evaluating it standalone per review at prefetch time."""
        if depth > 8:
            return False
        if isinstance(term, (A.Scalar, A.Wildcard)):
            return True
        if isinstance(term, A.Var):
            if term.name in locals_:
                return True
            d = ctx.defs.get(term.name)
            if d is not None:
                return self._keys_input_only(d, ctx, locals_, depth + 1)
            return False
        if isinstance(term, (A.ArrayTerm, A.SetTerm)):
            return all(
                self._keys_input_only(t, ctx, locals_, depth + 1)
                for t in term.items
            )
        if isinstance(term, A.BinOp):
            return self._keys_input_only(
                term.lhs, ctx, locals_, depth + 1
            ) and self._keys_input_only(term.rhs, ctx, locals_, depth + 1)
        if isinstance(term, A.Call):
            # pure builtins over input-only args are fine (the
            # extraction evaluates them); helper functions may read
            # data/parameters, so they stay conservative
            if term.name == "external_data" or term.name not in BUILTINS:
                return False
            return all(
                self._keys_input_only(a, ctx, locals_, depth + 1)
                for a in term.args
            )
        if isinstance(term, A.Ref):
            if not isinstance(term.head, A.Var):
                return False
            h = term.head.name
            if h == "input":
                if not (
                    term.ops
                    and isinstance(term.ops[0], A.Scalar)
                    and term.ops[0].value == "review"
                ):
                    return False
            elif h not in locals_:
                d = ctx.defs.get(h)
                if d is None or not self._keys_input_only(
                    d, ctx, locals_, depth + 1
                ):
                    return False
            for op in term.ops:
                if isinstance(op, (A.Scalar, A.Wildcard)):
                    continue
                if isinstance(op, A.Var):
                    # an unbound var segment binds by iteration here
                    locals_.add(op.name)
                    continue
                return False
            return True
        if isinstance(term, A.Comprehension):
            sub = set(locals_)
            for e in term.body:
                if not self._comp_expr_input_only(e, ctx, sub, depth + 1):
                    return False
            if not self._keys_input_only(term.head, ctx, sub, depth + 1):
                return False
            return term.key is None or self._keys_input_only(
                term.key, ctx, sub, depth + 1
            )
        return False

    def _comp_expr_input_only(
        self, e: A.Expr, ctx: _Ctx, locals_: Set[str], depth: int
    ) -> bool:
        if isinstance(e, A.SomeDecl):
            locals_.update(e.names)
            return True
        if isinstance(e, A.Assign):
            ok = self._keys_input_only(e.value, ctx, locals_, depth)
            if isinstance(e.target, A.Var):
                locals_.add(e.target.name)
            elif isinstance(e.target, A.ArrayTerm):
                for t in e.target.items:
                    if isinstance(t, A.Var):
                        locals_.add(t.name)
            return ok
        if isinstance(e, A.Unify):
            for a, b in ((e.lhs, e.rhs), (e.rhs, e.lhs)):
                if isinstance(a, A.Var) and self._keys_input_only(
                    b, ctx, set(locals_), depth
                ):
                    self._keys_input_only(b, ctx, locals_, depth)
                    locals_.add(a.name)
                    return True
            return self._keys_input_only(
                e.lhs, ctx, locals_, depth
            ) and self._keys_input_only(e.rhs, ctx, locals_, depth)
        if isinstance(e, A.NotExpr):
            return self._comp_expr_input_only(e.expr, ctx, locals_, depth)
        if isinstance(e, A.TermExpr):
            return self._keys_input_only(e.term, ctx, locals_, depth)
        return False

    # -- error-gated proof ---------------------------------------------------

    def _requires_errors(self, body: List[A.Expr], respvar: str) -> bool:
        """True when some positive top-level body expression requires
        `respvar.errors` to be non-empty — then the rule can only fire
        when the provider returned an error entry, so the fused screen
        may soundly skip rows whose keys are all clean cache hits."""
        for e in body:
            terms: List[A.Term] = []
            if isinstance(e, A.TermExpr):
                terms = [e.term]
            elif isinstance(e, A.Assign):
                terms = [e.value]
            elif isinstance(e, A.Unify):
                terms = [e.lhs, e.rhs]
            for t in terms:
                if self._errors_requirement(t, respvar):
                    return True
        return False

    def _is_errors_ref(self, t: A.Term, respvar: str) -> bool:
        return (
            isinstance(t, A.Ref)
            and isinstance(t.head, A.Var)
            and t.head.name == respvar
            and bool(t.ops)
            and isinstance(t.ops[0], A.Scalar)
            and t.ops[0].value == "errors"
        )

    def _errors_requirement(self, t: A.Term, respvar: str) -> bool:
        # `resp.errors[_]` / `resp.errors[i][...]`: each body solution
        # demands an element, so firing implies errors is non-empty
        if self._is_errors_ref(t, respvar) and len(t.ops) >= 2:
            return True
        if not isinstance(t, A.BinOp):
            return False
        flip = {
            ">": "<", "<": ">", ">=": "<=", "<=": ">=",
            "!=": "!=", "==": "==",
        }
        for a, b, op in (
            (t.lhs, t.rhs, t.op),
            (t.rhs, t.lhs, flip.get(t.op, t.op)),
        ):
            num = b.value if isinstance(b, A.Scalar) else None
            if (
                isinstance(a, A.Call)
                and a.name == "count"
                and len(a.args) == 1
                and self._is_errors_ref(a.args[0], respvar)
                and isinstance(num, (int, float))
                and not isinstance(num, bool)
            ):
                if op == ">" and num >= 0:
                    return True
                if op in (">=", "==") and num >= 1:
                    return True
                if op == "!=" and num == 0:
                    return True
            if (
                self._is_errors_ref(a, respvar)
                and len(a.ops) == 1
                and op == "!="
                and isinstance(b, A.ArrayTerm)
                and not b.items
            ):
                return True
        return False

    # -- tableizability (mirrors symbolic._tableize_function's gates) -------

    def _fn_tableizable(self, name: str) -> bool:
        cached = getattr(self, "_tableizable_cache", None)
        if cached is None:
            cached = self._tableizable_cache = {}
        if name not in cached:
            cached[name] = self._fn_pure(name, set()) and (
                self._fn_args_unwalked(name)
            )
        return cached[name]

    def _fn_pure(self, name: str, seen: Set[str]) -> bool:
        """No input.review / data refs in the call graph (mirrors
        symbolic.Compiler._fn_is_pure; input.parameters is allowed)."""
        if name in seen:
            return True
        seen.add(name)
        impure: List[str] = []

        def visit(n: Any) -> None:
            import dataclasses as _dc

            if isinstance(n, A.Ref) and isinstance(n.head, A.Var):
                if n.head.name == "data":
                    impure.append("data")
                elif n.head.name == "input":
                    if not (
                        n.ops
                        and isinstance(n.ops[0], A.Scalar)
                        and n.ops[0].value == "parameters"
                    ):
                        impure.append("input")
                elif n.head.name in self.rules and not self._fn_pure(
                    n.head.name, seen
                ):
                    impure.append(n.head.name)
            if isinstance(n, A.Call):
                b = n.name.split(".")[-1] if "." in n.name else n.name
                if b in self.rules and not self._fn_pure(b, seen):
                    impure.append(b)
            if isinstance(n, A.Node):
                for f in _dc.fields(n):
                    visit(getattr(n, f.name))
            elif isinstance(n, (list, tuple)):
                for x in n:
                    visit(x)

        for rule in self.rules.get(name, []):
            visit(rule)
        return not impure

    def _fn_args_unwalked(self, name: str) -> bool:
        """The function never dereferences its formals (required for
        vid-keyed tableization: the oracle keys on the scalar value)."""
        for rule in self.rules.get(name, []):
            formals = {
                f.name
                for f in (rule.head.args or [])
                if isinstance(f, A.Var)
            }
            bad: List[str] = []

            def visit(n: Any) -> None:
                import dataclasses as _dc

                if (
                    isinstance(n, A.Ref)
                    and isinstance(n.head, A.Var)
                    and n.head.name in formals
                    and n.ops
                ):
                    bad.append(n.head.name)
                if isinstance(n, A.Node):
                    for f in _dc.fields(n):
                        visit(getattr(n, f.name))
                elif isinstance(n, (list, tuple)):
                    for x in n:
                        visit(x)

            visit(rule)
            if bad:
                return False
        return True


# -- helpers ----------------------------------------------------------------


def _comprehensions_in(node: Any) -> List[A.Comprehension]:
    out: List[A.Comprehension] = []

    def visit(n: Any) -> None:
        import dataclasses as _dc

        if isinstance(n, A.Comprehension):
            out.append(n)
            return  # nested comprehensions handled by recursion
        if isinstance(n, A.Node):
            for f in _dc.fields(n):
                visit(getattr(n, f.name))
        elif isinstance(n, (list, tuple)):
            for x in n:
                visit(x)

    visit(node)
    return out


def _touches_review(body: List[A.Expr]) -> bool:
    hits: List[str] = []

    def visit(n: Any) -> None:
        import dataclasses as _dc

        if isinstance(n, A.Ref) and isinstance(n.head, A.Var):
            if n.head.name in ("input", "data"):
                hits.append(n.head.name)
        if isinstance(n, A.Node):
            for f in _dc.fields(n):
                visit(getattr(n, f.name))
        elif isinstance(n, (list, tuple)):
            for x in n:
                visit(x)

    visit(body)
    return bool(hits)


def _ref_tail_rule(ref: A.Ref) -> Optional[str]:
    """Last scalar-string segment of a data.* ref (rewritten lib path)."""
    tail = None
    for op in ref.ops:
        if isinstance(op, A.Scalar) and isinstance(op.value, str):
            tail = op.value
        else:
            break
    return tail


# -- public API -------------------------------------------------------------


def analyze_modules(
    kind: str, modules: Sequence[A.Module]
) -> VectorizabilityReport:
    """Analyze a template's parsed+rewritten modules (what the Client
    mounts into the driver)."""
    return Analyzer(kind, modules).run()


def analyze_template(obj: Dict[str, Any]) -> VectorizabilityReport:
    """Analyze a raw ConstraintTemplate dict (YAML document): runs the
    same parse/validate/rewrite pipeline as Client.add_template, then
    the analyzer. Pipeline errors surface as INVALID diagnostics
    instead of exceptions, so offline lint runs never crash on one bad
    template."""
    from ..constraint.errors import InvalidTemplateError
    from ..constraint.templates import ConstraintTemplate
    from ..constraint import regocompile

    try:
        ct = ConstraintTemplate.from_dict(obj)
        ct.validate_names()
        spec = ct.targets[0]
        modules = regocompile.compile_template_modules(
            ct.kind, spec.target, spec.rego, spec.libs
        )
    except InvalidTemplateError as e:
        kind = ""
        try:
            kind = (
                ((obj.get("spec") or {}).get("crd") or {})
                .get("spec", {})
                .get("names", {})
                .get("kind", "")
            )
        except AttributeError:
            pass
        rep = VectorizabilityReport(kind=kind or "<invalid>")
        rep.add("GK-V008", str(e), severity=INVALID)
        return rep
    return analyze_modules(ct.kind, modules)
