"""Rego-subset → vectorized program compiler (symbolic partial evaluation).

Compiles ConstraintTemplate `violation` rules into Expr DAGs over the
token table, per (template, constraint-parameters) pair. The strategy is
partial evaluation: `input.parameters` is the constraint's concrete params
value, `input.review` is an abstract document backed by token patterns,
and rule bodies execute symbolically — concrete subterms fold at compile
time, review-dependent subterms emit vectorized ops.

Design decisions (see SURVEY.md §7 "hard parts"):
  * The program is a violation DETECTOR/COUNTER: it returns violations per
    resource. Messages are rendered host-side by re-evaluating only the
    ≤`--constraint-violations-limit` reported pairs with the interpreter,
    so message fidelity never constrains the kernel.
  * Document iteration is LAZY: `containers[_]` extends the abstract path
    with "#" and the array axis only materializes at leaf reads, with an
    occupancy guard per axis. Iterations fork into an array branch ("#")
    and an object branch ("*" token axis) — real data matches exactly one,
    so the other contributes zero.
  * Pure string work (regex, prefixes, to_number, helper fns like
    canonify_cpu) happens per distinct vocab entry on the host
    (tables.py), never on device.
  * Per-constraint constants land in a ConstPool (padded to power-of-two
    buckets), so constraints of the same template with the same control
    flow share one compiled program, called with different const tensors.
  * Anything outside the subset raises CompileUnsupported; the driver
    routes that template to the interpreter (hybrid routing, SURVEY.md §7).

Documented approximations (differential-tested to be unobservable on the
reference library with well-formed K8s objects):
  * Rego set-of-violations dedup across IDENTICAL {msg, details} objects
    is not replicated — counts assume distinct messages (library messages
    embed container/key names).
  * count() of token-derived sets counts tokens, not distinct values.
  * Device numeric comparisons are float32.
"""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..rego import ast as A
from ..flatten.encoder import K_NUM, K_STR
from ..flatten.vocab import Vocab
from .exprs import (
    ECapture,
    EConstSlot,
    EFullN,
    EGroup,
    EGroupPresent,
    EIsInConst,
    ELit,
    EMap,
    EReduce,
    EReduceAxis,
    ESelPattern,
    EStrTable,
    ETokCol,
    Expr,
    e_and,
    e_arith,
    e_cmp,
    e_not,
    e_or,
    e_where,
)
from ..flatten.encoder import esc_seg
from .patterns import PatternRegistry
from .tables import StrTables

NEG_INF = -(10.0**30)


# bump when oracle/interpreter evaluation semantics change: salts the
# persisted oracle-table memo keys (engine/tables.py _load/_save_persist)
ORACLE_MEMO_VERSION = 1


class Reason(str, enum.Enum):
    """Stable fused-path fallback taxonomy.

    Every `CompileUnsupported` raise site stamps one of these family
    codes, so consumers (the IR static-analysis plane's GK-P015
    diagnostic, fallback metrics, tests) classify *why* a template fell
    off the fused path by enum identity instead of string-matching
    human-oriented messages. Values are stable slugs: renaming a member
    is an API break; add new members instead."""

    AGGREGATE_ARG = "aggregate-arg"
    ARRAY_DEPTH = "array-depth"
    AXIS_SHAPE = "axis-shape"
    BINOP = "binop"
    BUILTIN_ARG_SHAPE = "builtin-arg-shape"
    COMPARISON = "comparison"
    COMPREHENSION = "comprehension"
    DATA_REF = "data-ref"
    DERIVED_VALUE = "derived-value"
    DESTRUCTURING = "destructuring"
    EXPR_FORM = "expr-form"
    EXTERNAL_DATA = "external-data"
    FIXED_INDEX = "fixed-index"
    FORKING = "forking"
    FUNCTION_CALL = "function-call"
    INPUT_REF = "input-ref"
    KEYED_LOOKUP = "keyed-lookup"
    OBJECT_ITERATION = "object-iteration"
    OTHER = "other"
    PARTIAL_SET = "partial-set"
    PROJECTION = "projection"
    RULE_REF = "rule-ref"
    TERM_FORM = "term-form"
    TRUTHINESS = "truthiness"
    UNSUPPORTED_BUILTIN = "unsupported-builtin"
    VIOLATION_RULE_FORM = "violation-rule-form"
    WALK_FORM = "walk-form"
    WITH_MODIFIER = "with-modifier"


class CompileUnsupported(Exception):
    """Template uses constructs outside the compilable subset.

    Carries template-kind / rule / line provenance, filled in as the
    exception unwinds through the clause compiler (`_compile_clause`
    stamps rule+line, `compile_violation_counts` stamps the kind), so
    fallback log lines and analyzer-mismatch reports cite WHERE
    compilation gave up, not just why. `code` is the stable `Reason`
    family the raise site belongs to (never derived from the message)."""

    def __init__(
        self,
        reason: str = "",
        kind: str = "",
        rule: str = "",
        line: int = 0,
        code: Optional[Reason] = None,
    ):
        self.reason = reason
        self.kind = kind
        self.rule = rule
        self.line = line
        self.code = code if code is not None else Reason.OTHER
        super().__init__(reason)

    def annotate(
        self, kind: str = "", rule: str = "", line: int = 0
    ) -> "CompileUnsupported":
        """Fill empty provenance fields (innermost context wins)."""
        if kind and not self.kind:
            self.kind = kind
        if rule and not self.rule:
            self.rule = rule
        if line and not self.line:
            self.line = line
        return self

    def __str__(self) -> str:
        ctx = []
        if self.kind:
            ctx.append(f"template={self.kind}")
        if self.rule:
            loc = self.rule + (f":{self.line}" if self.line else "")
            ctx.append(f"rule={loc}")
        if ctx:
            return f"{self.reason} [{' '.join(ctx)}]"
        return self.reason


class InventoryDependent(Exception):
    """A condition's truth depends on `data.inventory` content.

    Raised when a comparison/truthiness touches an inventory-derived
    value; caught at the statement level, where the conjunct is DROPPED
    — a sound over-approximation (weakening a conjunction can only add
    violations). Programs compiled this way are *screens*: the sparse
    pairs they flag are re-evaluated exactly by the interpreter with the
    real inventory (TpuDriver._eval_template), so audit/review results
    stay bit-exact while the dense non-matching bulk never leaves the
    device. This is how the reference's cross-join templates
    (uniqueingresshost / uniqueserviceselector,
    library/general/*/template.yaml; evaluated by the reference via the
    audit cross-join in regolib/src.go:45-62) ride the compiled path."""


@dataclass
class CompilerEnv:
    vocab: Vocab
    patterns: PatternRegistry
    tables: StrTables
    # oracle_fn(fn_name, scalar_value) -> (result, defined): interpreter-
    # backed evaluation of a pure template helper function, used to build
    # per-vocab-entry lookup tables for functions the symbolic compiler
    # can't inline (string canonicalizers like canonify_cpu)
    oracle_fn: Optional[Callable[[str, Any], Tuple[Any, bool]]] = None
    # namespace for oracle-built tables (unique per template+params)
    oracle_ns: str = ""
    # params-free namespace (unique per template only): tables for
    # helpers whose call graph never reads input.parameters register
    # here, so constraint params variants share one fill — the fill is
    # the expensive part (one interpreter call per vocab entry)
    oracle_ns_shared: str = ""
    # constraint kind, for CompileUnsupported provenance only
    template_kind: str = ""
    # external-data screen feature ("extdata:<kind>:<err|all>", set by
    # the driver when the template's external_data calls are
    # batch-extractable): external_data compiles as a screen whose
    # per-row bits the dispatch layer fills from the response cache —
    # in "err" mode (provably error-gated rules) clean cache-hit rows
    # are skipped; in "all" mode the feature only drives prefetch
    extdata_feature: Optional[str] = None


class ConstPool:
    """Per-constraint constants hoisted out of the program structure."""

    def __init__(self):
        self.values: Dict[str, np.ndarray] = {}
        self._n = 0

    def scalar(self, v: float) -> Expr:
        name = f"s{self._n}"
        self._n += 1
        self.values[name] = np.asarray(v, np.float32)
        return EConstSlot(name)

    def id_scalar(self, v: int) -> Expr:
        name = f"i{self._n}"
        self._n += 1
        self.values[name] = np.asarray(v, np.int32)
        return EConstSlot(name)

    def id_set(self, ids: Sequence[int]) -> str:
        """Padded [K] id array slot (for EIsInConst)."""
        name = f"set{self._n}"
        self._n += 1
        k = 1
        while k < max(len(ids), 1):
            k *= 2
        arr = np.full((k,), -1, np.int32)
        for i, v in enumerate(ids):
            arr[i] = v
        self.values[name] = arr
        return name


# ---------------------------------------------------------------------------
# Symbolic values


class SVal:
    pass


@dataclass
class SConst(SVal):
    value: Any


class SInput(SVal):
    """The bare `input` document (proc-mount passes it to a helper)."""


@dataclass
class SInventory(SVal):
    """Opaque value: walks and calls propagate it; any condition on it
    raises InventoryDependent (see that class). Produced by
    `data.inventory` refs always, and — in screen mode — by calls and
    comprehensions outside the compilable subset (a flatten_selector-
    style derived string whose only use is an inventory comparison needs
    no device value at all).

    `path` tracks the walked segments from the data.inventory root —
    escaped literal keys, "#" for literal array indices, "?" for
    var-iterated (unknown) segments; None once the value flowed through
    a call/comprehension and the path is unknowable. `root` identifies
    the inventory iteration the value descends from, so self-exclusion
    guards (`not identical(other, input.review)`) can be tied to the
    join they guard. Both exist solely so the invdup screen refinement
    can prove its soundness conditions (ADVICE r3 high: a cross-path
    join refined at the review leaf's own pattern under-approximates)."""

    path: Optional[Tuple[str, ...]] = None
    root: int = -1
    # derived-value provenance for the render-prune detection
    # (uniqueserviceselector's flatten_selector idiom):
    # ("rev", fn, review_prefix) for F(<review subdocument>),
    # ("inv", fn, walk_path) for F(<inventory-walked object>)
    call_tag: Any = None


@dataclass
class SNode(SVal):
    """Abstract review subdocument at a path prefix ("#" = array level,
    "*" = object-key iteration level)."""

    prefix: Tuple[str, ...]


def _axes_of(prefix: Tuple[str, ...]) -> Tuple[str, ...]:
    n = sum(1 for s in prefix if s == "#")
    if n == 0:
        return ()
    if n == 1:
        return ("g0",)
    if n == 2:
        # two array levels flatten onto one combined axis (idx0*G1 + idx1)
        return ("g01",)
    raise CompileUnsupported(">2 array levels", code=Reason.ARRAY_DEPTH)


@dataclass
class SScalar(SVal):
    """A leaf value read from the token table."""

    comp: "Compiler"
    pattern_idx: int  # -1 for derived scalars
    axes: Tuple[str, ...] = ()
    tok_space: bool = False
    sel_override: Optional[Expr] = None
    num_override: Optional[Expr] = None
    exists_override: Optional[Expr] = None
    # transformed string values (lower/trim/set-element bindings): ids of
    # known-string entries, bypassing the token columns
    vid_override: Optional[Expr] = None
    # render-signature override: derived values that stand in for a
    # message (value-position sprintf) keep SMsg-style cross-clause
    # dedup via _val_sig
    msg_sig: Optional[Tuple] = None

    @property
    def space(self) -> Tuple[str, ...]:
        return ("tok",) if self.tok_space else self.axes

    def sel(self) -> Expr:
        if self.sel_override is not None:
            return self.sel_override
        return ESelPattern(self.pattern_idx)

    def exists(self) -> Expr:
        if self.exists_override is not None:
            return self.exists_override
        if self.tok_space:
            return self.sel()
        if not self.axes:
            return EReduce(self.sel(), "any")
        return self._grouped(self.sel(), None, "any")

    def _grouped(self, mask, value, how, init=-1):
        if self.axes in (("g0",), ("g01",)):
            return EGroup(mask, value, self.axes[0], how=how, init=init)
        raise CompileUnsupported(f"axes {self.axes}", code=Reason.AXIS_SHAPE)

    def col(self, name: str, init=-1) -> Expr:
        if self.num_override is not None:
            raise CompileUnsupported("column of derived scalar", code=Reason.DERIVED_VALUE)
        if self.tok_space:
            return ETokCol(name)
        if not self.axes:
            # "maskfill" is an IR contract with analysis/ir.py: args are
            # [mask, value] and the result is a constant fill wherever
            # the mask is False, so a provably-False mask makes the node
            # pad-equivalent regardless of the value column.
            masked = EMap(
                lambda np_, m, v: np_.where(m, v, init),
                [self.sel(), ETokCol(name)],
                "maskfill",
            )
            return EReduce(masked, "max")
        return self._grouped(self.sel(), ETokCol(name), "max", init=init)

    def vid(self) -> Expr:
        if self.vid_override is not None:
            return self.vid_override
        return self.col("vid", -1)

    def num(self) -> Expr:
        if self.num_override is not None:
            return self.num_override
        return self.col("vnum", NEG_INF)

    def kindv(self) -> Expr:
        if self.vid_override is not None:
            return ELit(K_STR)  # transformed values are known strings
        return self.col("kind", -1)

    def truthy(self) -> Expr:
        # vid first: projected subfields carry BOTH overrides (vid for
        # identity, num for arithmetic) and `false` must stay non-truthy
        if self.vid_override is not None:
            return e_and(
                self.exists(),
                e_not(
                    e_cmp("==", self.vid_override, ELit(self.comp.false_id))
                ),
            )
        if self.num_override is not None:
            return self.exists()  # derived numbers: defined => truthy
        false_id = ELit(self.comp.false_id)
        if self.tok_space:
            return e_and(
                self.sel(), e_not(e_cmp("==", ETokCol("vid"), false_id))
            )
        return e_and(self.exists(), e_not(e_cmp("==", self.vid(), false_id)))


@dataclass
class SKey(SVal):
    """Captured object-key of a token-space iteration."""

    pattern_idx: int

    def ids(self) -> Expr:
        return ECapture(self.pattern_idx)


@dataclass
class SBool(SVal):
    expr: Expr


@dataclass
class SMsg(SVal):
    """Opaque always-defined value (sprintf output, head objects).

    `sig` is a structural signature of how the value renders (format
    string + argument source paths). Clauses whose heads carry EQUAL
    signatures render identical strings for the same (resource, element),
    so their violation objects collapse in Rego's result set — the
    compiler ORs such clauses instead of summing them.
    """

    sig: Any = None
    # single-symbolic-arg sprintf carries a LAZY transform recipe
    # (fmt, arg): comparisons materialize it into an id-transform table
    # on demand (apparmor's annotation-key join). Eager registration
    # exploded: several message-position sprintf tables mutually
    # transforming each other's products grow the vocab exponentially.
    recipe: Optional[Tuple[str, Any]] = None
    # render recipe for the compiled message path (engine/render.py):
    # ("sprintf", fmt, (SVal, ...)) or ("obj", ((const_key, SVal), ...))
    parts: Any = None

    def signature(self):
        return self.sig if self.sig is not None else ("opaque", id(self))


@dataclass
class STokenSet(SVal):
    """Set/array comprehension over a token selection.

    `axes` are OUTER array axes the elements are grouped under (e.g. the
    container axis for per-container capability sets); set operations
    reduce the token axis down to those axes via idx-grouping.
    """

    mask: Expr  # [N, L]
    elem_ids: Expr  # [N, L]
    axes: Tuple[str, ...] = ()

    def reduce_any(self, pred_mask: Optional[Expr]) -> Expr:
        m = e_and(self.mask, pred_mask) if pred_mask is not None else self.mask
        if self.axes == ():
            return EReduce(m, "any")
        if self.axes == ("g0",):
            return EGroup(m, None, "g0", how="any")
        raise CompileUnsupported("token-set axes", code=Reason.AXIS_SHAPE)

    def reduce_count(self) -> Expr:
        cnt = EMap(lambda np_, m: m.astype(np.int32), [self.mask], "toint")
        if self.axes == ():
            return EReduce(cnt, "sum")
        if self.axes == ("g0",):
            return EGroup(self.mask, cnt, "g0", how="sum")
        raise CompileUnsupported("token-set axes", code=Reason.AXIS_SHAPE)


@dataclass
class SElemProj(SVal):
    """Element projection of a SECOND array iterated in token space.

    When a clause's group axis is already owned by another array (the
    host-filesystem volumes x volumeMounts join), the second array's
    elements are represented by their subtree TOKENS: subfield reads
    gather the element's per-field values back onto each token
    (EGatherElem), so conditions on different fields of one element
    agree token-wise. Sound only under EXISTENTIAL reduction (function
    bodies, negations) — one element spans many tokens, so counting
    heads over projected conditions would over-count; the `proj` taint
    on State enforces the restriction."""

    root: Tuple[str, ...]  # ends with "#": the element's array marker
    rel: Tuple[str, ...] = ()  # walked segments below the element


@dataclass
class SDerived(SVal):
    """Per-resource derived number (e.g. a count)."""

    num: Expr
    defined: Expr
    # render recipe when the derived number stands in for a computable
    # value, e.g. ("constdiff", elems, STokenSet) for const-set minus
    # token-set (the `missing` idiom) — engine/render.py rebuilds the
    # actual set host-side from it
    render: Any = None


@dataclass
class SList(SVal):
    """Small fixed list of symbolic values (concrete-iteration
    comprehensions like allowedrepos' `satisfied` array).

    Each item carries an optional guard: the element is only present in
    the list when the guard holds (body conditions of the producing
    comprehension fork)."""

    items: List[Tuple[Optional[Expr], SVal]]


# ---------------------------------------------------------------------------


@dataclass
class State:
    env: Dict[str, SVal]
    cond: List[Expr] = field(default_factory=list)
    space: Tuple[str, ...] = ()
    # axis -> occupancy guard (array slot actually exists)
    guards: Dict[str, Expr] = field(default_factory=dict)
    # axis -> owning array prefix: two DIFFERENT arrays may not share a
    # group axis in one clause (their indices would silently mis-join)
    axis_owner: Dict[str, Tuple[str, ...]] = field(default_factory=dict)
    # element-projection taint (SElemProj): conds are per-TOKEN stand-ins
    # for per-element truth, valid only once existentially reduced
    # (_eval_not); a tainted state reaching a counting head aborts the
    # compile (programs retry with projection disabled)
    proj: bool = False


def _space_join(a: Tuple[str, ...], b: Tuple[str, ...]) -> Tuple[str, ...]:
    from .exprs import join_spaces

    j = join_spaces(a, b)
    if j is None:
        raise CompileUnsupported(f"space join {a} {b}", code=Reason.AXIS_SHAPE)
    return j


def _is_review_ref(term: A.Term, st: "State") -> bool:
    """Is this term the review document (`input.review` or a var bound
    to it)? Used when matching the self-exclusion guard idiom."""
    if (
        isinstance(term, A.Ref)
        and isinstance(term.head, A.Var)
        and term.head.name == "input"
        and len(term.ops) == 1
        and isinstance(term.ops[0], A.Scalar)
        and term.ops[0].value == "review"
    ):
        return True
    if isinstance(term, A.Var):
        v = st.env.get(term.name)
        return isinstance(v, SNode) and v.prefix == ()
    return False


def _self_identity_paths(
    rule: A.Rule,
) -> Optional[Tuple[Tuple[str, ...], ...]]:
    """If this function definition is provably TRUE whenever its first
    argument IS the second argument's `.object` (and the compared
    fields are defined), return the object paths whose definedness that
    proof needs; else None.

    Accepted shape — every body statement equates obj.<p...> with
    review.object.<p...> over the same all-scalar path (either operand
    order): when obj is review.object both sides are the same value, so
    each equality holds iff the path is defined."""
    head = rule.head
    if (
        head.kind != "func"
        or not head.args
        or len(head.args) != 2
        or rule.is_default
        or rule.else_rule is not None
        or not isinstance(head.args[0], A.Var)
        or not isinstance(head.args[1], A.Var)
        or not rule.body
    ):
        return None
    obj_name, rev_name = head.args[0].name, head.args[1].name
    paths: List[Tuple[str, ...]] = []
    for expr in rule.body:
        if (
            isinstance(expr, A.TermExpr)
            and isinstance(expr.term, A.BinOp)
            and expr.term.op == "=="
        ):
            lhs, rhs = expr.term.lhs, expr.term.rhs
        elif isinstance(expr, A.Unify):
            lhs, rhs = expr.lhs, expr.rhs
        else:
            return None
        p1 = _scalar_path(lhs, obj_name)
        p2 = _scalar_path(rhs, rev_name)
        if p1 is None or p2 is None:
            p1 = _scalar_path(rhs, obj_name)
            p2 = _scalar_path(lhs, rev_name)
        if p1 is None or p2 is None:
            return None
        if p2[:1] != ("object",) or p2[1:] != p1:
            return None
        paths.append(p1)
    return tuple(paths)


def _scalar_path(
    term: A.Term, base_name: str
) -> Optional[Tuple[str, ...]]:
    """`base.<a>.<b>...` with all-scalar-string ops -> ("a", "b", ...)."""
    if (
        not isinstance(term, A.Ref)
        or not isinstance(term.head, A.Var)
        or term.head.name != base_name
    ):
        return None
    segs: List[str] = []
    for op in term.ops:
        if isinstance(op, A.Scalar) and isinstance(op.value, str):
            segs.append(op.value)
        else:
            return None
    return tuple(segs)


class Compiler:
    """Compiles one template's violation rules for one concrete params."""

    def __init__(
        self,
        env: CompilerEnv,
        modules: Sequence[A.Module],
        params: Any,
        screen_mode: bool = False,
        elem_projection: bool = True,
    ):
        # screen mode: calls/comprehensions outside the compilable
        # subset become opaque SInventory values instead of aborting —
        # the program over-approximates and flagged pairs re-check via
        # the interpreter (compile_program's fallback retry)
        self.screen_mode = screen_mode
        # element projection (SElemProj): compile second-array joins in
        # token space; off in the middle retry of compile_program's
        # chain (a projection that cannot reduce existentially aborts)
        self.elem_projection = elem_projection
        self.cenv = env
        self.vocab = env.vocab
        self.patterns = env.patterns
        self.tables = env.tables
        self.params = params
        self.pool = ConstPool()
        self.false_id = env.vocab.val_id(False)
        self.rules: Dict[str, List[A.Rule]] = {}
        for mod in modules:
            for rule in mod.rules:
                self.rules.setdefault(rule.head.name, []).append(rule)
        self._fn_depth = 0
        self.signature: List[Any] = []  # structural program signature
        self.uses_inventory = False  # compiled as a screen (see
        # InventoryDependent): flagged pairs re-check via interpreter
        # opaque: some CONDITION was dropped (inventory content or an
        # uncompilable call/comprehension became an opaque value), so
        # branch conds over-approximate for EVERY row and the compiled
        # render is off. Safety FLAGS alone do not set this — a flagged
        # program is exact on unflagged rows (the flag routes the rest).
        self.opaque = False
        self._no_inv_catch = 0  # >0 inside negation bodies
        # row-level safety flags: [N]-space bools OR'd into the clause
        # being compiled when a construct is handled under a shape
        # assumption (e.g. object-key iteration at a node that COULD
        # hold an array) — rows breaking the assumption route to the
        # interpreter instead of silently evaluating wrong
        self._force_flags: List[Expr] = []
        # (leaf pattern id, mirror pattern id, inventory root id) of
        # review-side leaves equality-joined against inventory content
        # in the clause being compiled (screen refinement; _apply_binop)
        self._clause_joins: List[Tuple[int, int, int]] = []
        # (inventory root id, guard pattern ids) for detected
        # self-exclusion guards (`not identical(obj, input.review)`)
        self._clause_guards: List[Tuple[int, Tuple[int, ...]]] = []
        self._inv_root_n = 0  # fresh ids for inventory iterations
        # extdata features recorded by external_data calls in the
        # clause being compiled (ANDed in like _clause_joins)
        self._clause_extfeats: List[str] = []
        self.row_features: List[str] = []  # features programs consume
        # outputs of compile_violation_counts for the compiled-render
        # path (engine/render.py): grouped violation branches with their
        # un-flagged conditions + render plans, and the program's safety
        # flags (a flagged row renders via the interpreter)
        self.out_branches: List[Any] = []
        self.out_flags: List[Expr] = []
        # render-prune detection (derived-key inventory joins): per-
        # clause records + per-clause inventory-root usage; assembled
        # into prune_plan when exactly one clause touches inventory and
        # its every deref is the one recorded join
        self._clause_prunes: List[Tuple[str, Tuple[str, ...], str]] = []
        self._prune_records: List[Tuple[int, Tuple]] = []
        self._clause_inv_roots: List[Tuple[int, int]] = []
        self._clause_n = 0
        self.prune_plan: Optional[Dict[str, Any]] = None

    def _pattern(self, segs: Tuple[str, ...]) -> int:
        idx = self.patterns.register(segs)
        self.signature.append(("pat", segs))
        return idx

    # -- entry --------------------------------------------------------------

    def compile_violation_counts(self) -> Expr:
        try:
            return self._compile_violation_counts()
        except CompileUnsupported as e:
            raise e.annotate(kind=self.cenv.template_kind)

    def _compile_violation_counts(self) -> Expr:
        clauses = self.rules.get("violation")
        if not clauses:
            raise CompileUnsupported("no violation rule", code=Reason.VIOLATION_RULE_FORM)
        branches: List[Tuple[Any, Tuple[str, ...], Expr, Optional[Expr], Any]]
        branches = []
        for rule in clauses:
            if rule.is_default or rule.else_rule is not None:
                raise CompileUnsupported("default/else violation rule", code=Reason.VIOLATION_RULE_FORM)
            try:
                branches.extend(self._compile_clause(rule))
            except CompileUnsupported as e:
                raise e.annotate(rule=rule.head.name, line=rule.line)
        if not branches:
            return EFullN(0)
        # Rego's violation document is a SET: clauses rendering the same
        # {msg, details} object for the same (resource, element) collapse
        # (e.g. containerlimits' two "has no resource limits" clauses).
        # Branches with EQUAL head signatures on the same space are OR'd;
        # everything else sums.
        grouped: Dict[Any, List[Any]] = {}
        order: List[Any] = []
        for sig, space, cond, cond_exact, plan in branches:
            key = (sig, space)
            ent = grouped.get(key)
            if ent is not None:
                ent[0] = e_or(ent[0], cond)
                # equal sigs render identically (the dedup contract the
                # count layer already relies on): OR the exact conds and
                # keep the first available plan
                if ent[1] is None or cond_exact is None:
                    ent[1] = None
                else:
                    ent[1] = e_or(ent[1], cond_exact)
                if ent[2] is None:
                    ent[2] = plan
            else:
                grouped[key] = [cond, cond_exact, plan]
                order.append(key)
        from .render import Branch

        self.out_branches = []
        counts: List[Expr] = []
        for key in order:
            cond, cond_exact, plan = grouped[key]
            if cond_exact is not None:
                self.out_branches.append(
                    Branch(space=key[1], cond=cond_exact, plan=plan)
                )
            cnt = EMap(lambda np_, c: c.astype(np.int32), [cond], "toint")
            while cnt.space:
                cnt = EReduceAxis(cnt, cnt.space[-1], "sum")
            counts.append(cnt)
        total = counts[0]
        for c in counts[1:]:
            total = e_arith("+", total, c)
        self._assemble_prune_plan()
        return total

    def _assemble_prune_plan(self) -> None:
        """Valid iff exactly one clause touches the inventory, it walked
        exactly one root, and that root's sole use is the recorded
        derived-key join — then every object the clause can match is in
        the join key's candidate set, so the render may prune."""
        if not self._prune_records:
            return
        inv_clauses = [c for c, n in self._clause_inv_roots if n > 0]
        if len(inv_clauses) != 1:
            return
        clause = inv_clauses[0]
        recs = {r for c, r in self._prune_records if c == clause}
        if len(recs) != 1 or any(
            c != clause for c, _ in self._prune_records
        ):
            return
        root_count = next(
            n for c, n in self._clause_inv_roots if c == clause
        )
        if root_count != 1:
            return
        rec = next(iter(recs))
        if len(rec) == 4:  # path-form ("path", rel, review_pattern, tree)
            _, rel, psegs, tree = rec
            self.prune_plan = {
                "path": rel,
                "review_pattern": psegs,
                "tree": tree,
            }
        else:  # fn-form (fn, review_prefix, tree)
            fn, prefix, tree = rec
            self.prune_plan = {
                "fn": fn,
                "review_prefix": prefix,
                "tree": tree,
            }

    def _inv_rel_path(self, inv: "SInventory") -> Optional[Tuple[str, ...]]:
        """The deref path of `inv` relative to a walked inventory OBJECT
        (namespace tree: depth-4 walk; cluster tree: depth-3), or None
        when the walk doesn't address an object root or the value flowed
        through a call (path unknowable)."""
        if inv.path is None:
            return None
        if inv.path[0] == "namespace" and len(inv.path) > 5:
            return inv.path[5:]
        if inv.path[0] == "cluster" and len(inv.path) > 4:
            return inv.path[4:]
        return None

    def _compile_clause(
        self, rule: A.Rule
    ) -> List[Tuple[Any, Tuple[str, ...], Expr, Optional[Expr], Any]]:
        flags_base = len(self._force_flags)
        joins_base = len(self._clause_joins)
        extfeats_base = len(self._clause_extfeats)
        guards_base = len(self._clause_guards)
        prunes_base = len(self._clause_prunes)
        roots_base = self._inv_root_n
        self._clause_n += 1
        clause_idx = self._clause_n
        finals = self._eval_body(rule.body, State(env={}))
        for rec in self._clause_prunes[prunes_base:]:
            self._prune_records.append((clause_idx, rec))
        del self._clause_prunes[prunes_base:]
        self._clause_inv_roots.append(
            (clause_idx, self._inv_root_n - roots_base)
        )
        # safety flags raised during this clause's evaluation OR into
        # every branch: flagged rows always route to the interpreter
        clause_flags = self._force_flags[flags_base:]
        del self._force_flags[flags_base:]
        # inventory join refinements AND into the clause: a row can only
        # violate if SOME recorded join key is duplicated cluster-wide
        # (the dispatch layer supplies the per-row bits; absent bits
        # default True so the screen degrades to coarse, never unsound)
        clause_joins = sorted(set(self._clause_joins[joins_base:]))
        del self._clause_joins[joins_base:]
        guards_map: Dict[int, Tuple[int, ...]] = {}
        for root, gpids in self._clause_guards[guards_base:]:
            guards_map.setdefault(root, gpids)
        del self._clause_guards[guards_base:]
        join_refine: Optional[Expr] = None
        if clause_joins:
            from .exprs import ERowFeature

            for leaf_pid, mirror_pid, root in clause_joins:
                # feature encoding consumed by the dispatch layer
                # (TpuDriver._row_feature_bits):
                # invdup:<leaf>:<mirror>:<self-excluded 0|1>:<g+g+...>
                gpids = guards_map.get(root)
                se = 1 if gpids else 0
                gstr = "+".join(str(g) for g in (gpids or ()))
                feat_name = f"invdup:{leaf_pid}:{mirror_pid}:{se}:{gstr}"
                if feat_name not in self.row_features:
                    self.row_features.append(feat_name)
                    self.signature.append(("rowfeat", feat_name))
                f = ERowFeature(feat_name)
                # ALL dropped equalities are conjuncts: clause truth
                # implies every joined key is matched by another object,
                # so ANDing the bits stays sound and is sharpest
                join_refine = f if join_refine is None else e_and(
                    join_refine, f
                )
        clause_extfeats = sorted(set(self._clause_extfeats[extfeats_base:]))
        del self._clause_extfeats[extfeats_base:]
        if clause_extfeats:
            from .exprs import ERowFeature

            # external-data screen refinement: in "err" mode a clause
            # through an error-gated external_data call can only fire
            # when some row key is NOT a clean cache hit — AND the
            # dispatch-supplied bit in (absent bits default True, so
            # the screen degrades coarse, never unsound); "all"-mode
            # bits are all-ones and exist to drive batch prefetch
            for feat_name in clause_extfeats:
                if feat_name not in self.row_features:
                    self.row_features.append(feat_name)
                    self.signature.append(("rowfeat", feat_name))
                f = ERowFeature(feat_name)
                join_refine = f if join_refine is None else e_and(
                    join_refine, f
                )
        self.out_flags.extend(clause_flags)
        if any(st.proj for st in finals):
            # element-projected conditions reached the counting head:
            # one element spans many tokens, so the count would inflate.
            # Abort; compile_program retries with projection disabled.
            raise CompileUnsupported("unreduced element projection", code=Reason.PROJECTION)
        outs: List[Tuple[Any, Tuple[str, ...], Expr, Optional[Expr], Any]] = []
        for st in finals:
            # the head must evaluate too (undefined heads drop violations);
            # its render-signature drives cross-clause set dedup
            try:
                head_forks = self._eval_term(rule.head.key, st)
            except InventoryDependent:
                # head value depends on opaque content: keep the branch
                # with a unique (no-dedup) signature — over-counting is
                # fine for a screen, the interpreter renders exact sets
                cond = self._conj(st)
                if join_refine is not None:
                    cond = e_and(cond, join_refine)
                exact = cond
                cond = self._with_flags(cond, clause_flags)
                outs.append(
                    (
                        ("inv-head", id(rule), len(outs)),
                        cond.space,
                        cond,
                        exact,
                        None,
                    )
                )
                continue
            for hv, hs in head_forks:
                cond = self._conj(hs)
                if join_refine is not None:
                    cond = e_and(cond, join_refine)
                exact = cond
                cond = self._with_flags(cond, clause_flags)
                plan = None
                if not self.screen_mode:
                    from .render import build_plan

                    plan = build_plan(self, hv)
                outs.append(
                    (_freeze_sig(_val_sig(hv)), cond.space, cond, exact, plan)
                )
        if not outs and clause_flags:
            # the clause compiled to statically-nothing but carries
            # safety flags: flagged rows must still route
            flag = clause_flags[0]
            for f in clause_flags[1:]:
                flag = e_or(flag, f)
            outs.append((("flag-only", id(rule)), flag.space, flag, None, None))
        return outs

    def _with_flags(self, cond: Expr, flags: List[Expr]) -> Expr:
        for f in flags:
            cond = e_or(cond, f)
        return cond

    def _conj(self, st: State) -> Expr:
        # anchor to [N] so fully-concrete bodies still count per resource
        out: Expr = EFullN(True)
        for c in list(st.cond) + [g for g in st.guards.values()]:
            out = e_and(out, c)
        return out

    # -- body ---------------------------------------------------------------

    def _eval_body(self, body: List[A.Expr], state: State) -> List[State]:
        states = [state]
        for expr in body:
            nxt: List[State] = []
            for st in states:
                nxt.extend(self._eval_expr(expr, st))
            if not nxt:
                return []
            states = nxt
            if len(states) > 64:
                raise CompileUnsupported("fork explosion", code=Reason.FORKING)
        return states

    def _eval_expr(self, expr: A.Expr, st: State) -> List[State]:
        try:
            return self._eval_expr_inner(expr, st)
        except InventoryDependent:
            # the conjunct's truth depends on inventory content: DROP it
            # (treat as satisfiable) — sound over-approximation in both
            # polarities since the WHOLE statement (including any `not`)
            # is what drops (inside a negation body the exception
            # re-raises so `not P(inv)` never resolves to inner-defined/
            # undefined, which would under-approximate); the interpreter
            # re-checks flagged pairs with the real inventory
            if self._no_inv_catch:
                raise
            return [st]

    def _eval_expr_inner(self, expr: A.Expr, st: State) -> List[State]:
        if isinstance(expr, A.SomeDecl):
            return [st]
        if isinstance(expr, A.Assign):
            return self._eval_assign(expr.target, expr.value, st)
        if isinstance(expr, A.Unify):
            return self._eval_unify(expr.lhs, expr.rhs, st)
        if isinstance(expr, A.TermExpr):
            return self._eval_cond_term(expr.term, st)
        if isinstance(expr, A.NotExpr):
            return self._eval_not(expr.expr, st)
        if isinstance(expr, A.WithExpr):
            raise CompileUnsupported("with modifier", code=Reason.WITH_MODIFIER)
        raise CompileUnsupported(f"expr {type(expr).__name__}", code=Reason.EXPR_FORM)

    def _node_exists_cond(self, node: SNode) -> Optional[Expr]:
        """Definedness of an abstract node (any token beneath it)."""
        if "*" in node.prefix:
            raise CompileUnsupported("existence under object iteration", code=Reason.OBJECT_ITERATION)
        pat = self._pattern(node.prefix + ("**",))
        axes = _axes_of(node.prefix)
        sel = ESelPattern(pat)
        if not axes:
            return EReduce(sel, "any")
        if axes in (("g0",), ("g01",)):
            return EGroup(sel, None, axes[0], how="any")
        raise CompileUnsupported("existence axes", code=Reason.AXIS_SHAPE)

    def _eval_assign(self, target, value, st: State) -> List[State]:
        if isinstance(target, A.Wildcard):
            return self._eval_cond_term(value, st)
        if isinstance(target, A.ArrayTerm):
            return self._eval_destructure(target, value, st)
        if not isinstance(target, A.Var):
            raise CompileUnsupported("destructuring assignment", code=Reason.DESTRUCTURING)
        out = []
        for val, st2 in self._eval_term(value, st):
            if isinstance(val, SNode) and not val.prefix[-1:] == ("#",):
                # `x := path` fails when the path is undefined — the
                # binding itself requires existence (observable through
                # later negations, e.g. containerlimits' parse clauses).
                # Iteration elements (prefix ending in "#") are already
                # guaranteed by the axis occupancy guard.
                st2 = replace(
                    st2, cond=st2.cond + [self._node_exists_cond(val)]
                )
            env = dict(st2.env)
            env[target.name] = val
            out.append(replace(st2, env=env))
        return out

    def _eval_destructure(self, target: A.ArrayTerm, value, st: State):
        """`[prefix, name] := split(key, "/")`-style array destructuring.

        Supported value shapes: `split(sym, const_sep)` — each part
        becomes an id-transform table (defined only when the split
        yields exactly len(target) parts, matching Rego's unification
        failure on length mismatch) — and SList/SConst sequences of
        matching length."""
        n = len(target.items)
        vars_ = []
        for t in target.items:
            if isinstance(t, (A.Var, A.Wildcard)):
                vars_.append(t)
            else:
                raise CompileUnsupported("destructure target shape", code=Reason.DESTRUCTURING)
        if (
            isinstance(value, A.Call)
            and value.name == "split"
            and len(value.args) == 2
        ):
            out = []
            for sep_v, st1 in self._eval_term(value.args[1], st):
                if not isinstance(sep_v, SConst) or not isinstance(
                    sep_v.value, str
                ):
                    raise CompileUnsupported("split separator shape", code=Reason.BUILTIN_ARG_SHAPE)
                sep = sep_v.value
                for tgt_v, st2 in self._eval_term(value.args[0], st1):
                    tgt_v = self._leafify(tgt_v)
                    if isinstance(tgt_v, SConst):
                        if not isinstance(tgt_v.value, str):
                            continue
                        parts = tgt_v.value.split(sep)
                        if len(parts) != n:
                            continue
                        env = dict(st2.env)
                        for t, p in zip(vars_, parts):
                            if isinstance(t, A.Var):
                                env[t.name] = SConst(p)
                        out.append(replace(st2, env=env))
                        continue
                    env = dict(st2.env)
                    conds: List[Expr] = []
                    for i, t in enumerate(vars_):
                        def mk(sep=sep, i=i, n=n):
                            def fn(s):
                                parts = s.split(sep)
                                if len(parts) != n:
                                    raise ValueError("part count")
                                return parts[i]

                            return fn

                        forks = self._str_transform(
                            tgt_v, st2, f"split:{sep}:{i}of{n}", mk()
                        )
                        if not forks:
                            return []
                        part, _ = forks[0]
                        conds.append(part.exists())
                        if isinstance(t, A.Var):
                            env[t.name] = part
                    out.append(
                        replace(st2, env=env, cond=st2.cond + conds)
                    )
            return out
        forks = self._eval_term(value, st)
        out = []
        for val, st2 in forks:
            items = None
            if isinstance(val, SList) and len(val.items) == n:
                items = [v for _, v in val.items]
            elif isinstance(val, SConst) and isinstance(val.value, list) and (
                len(val.value) == n
            ):
                items = [SConst(x) for x in val.value]
            if items is None:
                raise CompileUnsupported("destructure value shape", code=Reason.DESTRUCTURING)
            env = dict(st2.env)
            for t, v in zip(vars_, items):
                if isinstance(t, A.Var):
                    env[t.name] = v
            out.append(replace(st2, env=env))
        return out

    def _eval_unify(self, lhs, rhs, st: State) -> List[State]:
        lvar = isinstance(lhs, A.Var) and lhs.name not in st.env
        rvar = isinstance(rhs, A.Var) and rhs.name not in st.env
        if lvar and not rvar:
            return self._eval_assign(lhs, rhs, st)
        if rvar and not lvar:
            return self._eval_assign(rhs, lhs, st)
        if isinstance(lhs, A.Wildcard):
            return self._eval_cond_term(rhs, st)
        if isinstance(rhs, A.Wildcard):
            return self._eval_cond_term(lhs, st)
        return self._eval_cond_term(A.BinOp(op="==", lhs=lhs, rhs=rhs), st)

    def _inv_barrier(self):
        """Context manager: InventoryDependent raised inside must escape
        to the ENCLOSING construct instead of dropping an inner conjunct.
        Dropping is only sound where a weaker condition can only ADD
        violations — the top-level clause conjunction. Inside negation
        bodies, comprehension bodies, function bodies, and referenced
        rule bodies, a dropped conjunct weakens a VALUE that may flow
        into non-monotone uses (count(xs) == 0, not f(x), equality), so
        the whole enclosing statement/call must drop (or the compile
        falls back / retries as a coarser screen) instead."""
        import contextlib

        @contextlib.contextmanager
        def barrier():
            self._no_inv_catch += 1
            try:
                yield
            finally:
                self._no_inv_catch -= 1

        return barrier()

    def _eval_not(self, inner: A.Expr, st: State) -> List[State]:
        sub = State(env=dict(st.env), space=st.space, guards=dict(st.guards), axis_owner=dict(st.axis_owner))
        with self._inv_barrier():
            try:
                finals = self._eval_body([inner], sub)
            except InventoryDependent:
                # the whole `not` conjunct is about to drop; if it is a
                # CLAUSE-LEVEL self-exclusion guard, record it for the
                # invdup refinement before the exception propagates.
                # Depth 1 = only this `not`'s own barrier is active; a
                # deeper nesting (comprehension/function body) inverts
                # or launders polarity, so the guard cannot be trusted
                if self._no_inv_catch == 1:
                    self._note_self_exclusion(inner, st)
                raise
        if not finals:
            return [st]  # statically undefined -> `not` succeeds
        if (
            any(f.proj for f in finals)
            and "tok" in st.space
            and not st.proj
        ):
            # the negation cannot existentially close the projection's
            # token axis (the outer space already holds an UNRELATED
            # token iteration) — mixing their token conds would misjoin
            raise CompileUnsupported("projection under open token axis", code=Reason.PROJECTION)
        exprs = []
        statically_true = False
        for f in finals:
            conds = list(f.cond)
            # inner guards beyond the outer ones participate in the inner
            # truth value (an out-of-range element does not exist)
            for ax, g in f.guards.items():
                if st.guards.get(ax) is not g:
                    conds.append(g)
            if not conds:
                statically_true = True
                break
            cond = conds[0]
            for c in conds[1:]:
                cond = e_and(cond, c)
            # reduce axes opened inside the negation (e.g. the token axis
            # of an annotations[key] join) back to the outer space
            for ax in cond.space:
                if ax not in st.space:
                    cond = EReduceAxis(cond, ax, "any")
            if any(ax not in cond.space for ax in st.space):
                # outer axes missing from inner cond: broadcasting in the
                # final AND handles it
                pass
            exprs.append(cond)
        if statically_true:
            return []  # inner always defined -> `not` fails
        combined = exprs[0]
        for e in exprs[1:]:
            combined = e_or(combined, e)
        return [replace(st, cond=st.cond + [e_not(combined)])]

    # -- terms --------------------------------------------------------------

    def _eval_term(self, term: A.Term, st: State) -> List[Tuple[SVal, State]]:
        if isinstance(term, A.Scalar):
            return [(SConst(term.value), st)]
        if isinstance(term, A.Var):
            if term.name in st.env:
                return [(st.env[term.name], st)]
            if term.name == "input":
                return [(SInput(), st)]
            if term.name in self.rules:
                return self._eval_rule_ref(term.name, [], st)
            raise CompileUnsupported(f"unbound var {term.name}", code=Reason.TERM_FORM)
        if isinstance(term, A.Wildcard):
            raise CompileUnsupported("wildcard term", code=Reason.TERM_FORM)
        if isinstance(term, A.Ref):
            return self._eval_ref(term, st)
        if isinstance(term, A.Call):
            return self._eval_call(term, st)
        if isinstance(term, A.BinOp):
            return self._eval_binop(term, st)
        if isinstance(term, A.Comprehension):
            if self.screen_mode:
                try:
                    return self._eval_comprehension(term, st)
                except (CompileUnsupported, InventoryDependent):
                    self.uses_inventory = True
                    self.opaque = True
                    return [(SInventory(), st)]
            return self._eval_comprehension(term, st)
        if isinstance(term, A.ArrayTerm):
            return self._eval_seq_literal(term.items, st, "array")
        if isinstance(term, A.SetTerm):
            return self._eval_seq_literal(term.items, st, "set")
        if isinstance(term, A.ObjectTerm):
            return self._eval_obj_literal(term, st)
        if isinstance(term, A.UnaryMinus):
            forks = self._eval_term(term.operand, st)
            out = []
            for v, s in forks:
                if isinstance(v, SConst) and isinstance(v.value, (int, float)):
                    out.append((SConst(-v.value), s))
                else:
                    raise CompileUnsupported("symbolic unary minus", code=Reason.TERM_FORM)
            return out
        raise CompileUnsupported(f"term {type(term).__name__}", code=Reason.TERM_FORM)

    def _eval_seq_literal(self, items, st: State, kind: str):
        vals, cur = [], st
        symbolic = False
        for item in items:
            forks = self._eval_term(item, cur)
            if not forks:
                return []  # undefined element -> literal undefined
            if len(forks) != 1:
                raise CompileUnsupported("forking literal element", code=Reason.FORKING)
            v, cur = forks[0]
            if not isinstance(v, SConst):
                symbolic = True
            vals.append(v)
        if symbolic:
            return [(SList([(None, v) for v in vals]), cur)]
        pyvals = [v.value for v in vals]
        if kind == "set":
            return [(SConst(set(_hashable(x) for x in pyvals)), cur)]
        return [(SConst(pyvals), cur)]

    def _eval_obj_literal(self, term: A.ObjectTerm, st: State):
        cur = st
        concrete: Dict[Any, Any] = {}
        symbolic = False
        for k, v in term.items:
            kf = self._eval_term(k, cur)
            if len(kf) != 1:
                raise CompileUnsupported("forking object key", code=Reason.FORKING)
            kv, cur = kf[0]
            vf = self._eval_term(v, cur)
            if len(vf) != 1:
                raise CompileUnsupported("forking object value", code=Reason.FORKING)
            vv, cur = vf[0]
            if isinstance(kv, SConst) and isinstance(vv, SConst):
                concrete[_hashable(kv.value)] = vv.value
            else:
                symbolic = True
        if symbolic:
            sig_items = []
            part_items: Optional[List[Tuple[Any, Any]]] = []
            for k, v in term.items:
                kf = self._eval_term(k, st)
                kv = kf[0][0] if kf else None
                vf = self._eval_term(v, st)
                vv = vf[0][0] if vf else None
                sig_items.append((_val_sig(kv), _val_sig(vv)))
                if part_items is not None and isinstance(kv, SConst):
                    part_items.append((kv.value, vv))
                else:
                    part_items = None  # symbolic key: no render recipe
            parts = (
                ("obj", tuple(part_items)) if part_items is not None else None
            )
            return [(SMsg(sig=("obj", tuple(sig_items)), parts=parts), cur)]
        return [(SConst(concrete), cur)]

    # -- refs ---------------------------------------------------------------

    def _eval_ref(self, ref: A.Ref, st: State):
        if not isinstance(ref.head, A.Var):
            raise CompileUnsupported("computed ref head", code=Reason.INPUT_REF)
        name = ref.head.name
        if name == "input":
            if not ref.ops or not isinstance(ref.ops[0], A.Scalar):
                raise CompileUnsupported("opaque input access", code=Reason.INPUT_REF)
            first = ref.ops[0].value
            if first == "parameters":
                return self._walk(SConst(self.params), ref.ops[1:], st)
            if first == "review":
                return self._walk(SNode(prefix=()), ref.ops[1:], st)
            raise CompileUnsupported(f"input.{first}", code=Reason.INPUT_REF)
        if name in st.env:
            return self._walk(st.env[name], ref.ops, st)
        if name in self.rules:
            return self._eval_rule_ref(name, ref.ops, st)
        if name == "data":
            if (
                ref.ops
                and isinstance(ref.ops[0], A.Scalar)
                and ref.ops[0].value == "inventory"
            ):
                # inventory joins compile as screens: the value is opaque
                # and conditions on it drop (InventoryDependent); walking
                # with unbound vars binds them opaquely too
                self.uses_inventory = True
                self.opaque = True
                self._inv_root_n += 1
                return self._walk(
                    SInventory(path=(), root=self._inv_root_n),
                    ref.ops[1:],
                    st,
                )
            raise CompileUnsupported("data ref outside inventory", code=Reason.DATA_REF)
        raise CompileUnsupported(f"unknown ref head {name}", code=Reason.INPUT_REF)

    def _walk(self, val: SVal, ops: List[A.Term], st: State):
        forks: List[Tuple[SVal, State]] = [(val, st)]
        for op in ops:
            nxt: List[Tuple[SVal, State]] = []
            for v, s in forks:
                nxt.extend(self._walk_one(v, op, s))
            forks = nxt
            if not forks:
                return []
        return forks

    def _walk_one(self, val: SVal, op: A.Term, st: State):
        if isinstance(val, SInventory):
            # any step stays opaque; unbound var keys (ns/name/apiversion
            # iteration) bind opaquely. The walked segment is tracked on
            # the result so inventory joins can prove their counting
            # pattern mirrors the partner's real path (esc-literal / "#"
            # for literal array indices / "?" where the segment is
            # unknowable at compile time).
            seg: Optional[str] = None
            if isinstance(op, A.Scalar):
                if isinstance(op.value, str):
                    seg = esc_seg(op.value)
                elif isinstance(op.value, (int, float)) and not isinstance(
                    op.value, bool
                ):
                    seg = "#"
            elif isinstance(op, A.Wildcard):
                seg = "?"
            elif isinstance(op, A.Var):
                bound = st.env.get(op.name)
                if bound is None:
                    env = dict(st.env)
                    env[op.name] = SInventory()
                    st = replace(st, env=env)
                    seg = "?"
                elif isinstance(bound, SConst) and isinstance(
                    bound.value, str
                ):
                    seg = esc_seg(bound.value)
                else:
                    seg = "?"
            path = (
                None
                if (val.path is None or seg is None)
                else val.path + (seg,)
            )
            return [(SInventory(path=path, root=val.root), st)]
        if isinstance(val, SInput):
            if isinstance(op, A.Scalar) and op.value == "parameters":
                return [(SConst(self.params), st)]
            if isinstance(op, A.Scalar) and op.value == "review":
                return [(SNode(prefix=()), st)]
            raise CompileUnsupported("opaque input walk", code=Reason.INPUT_REF)
        if isinstance(val, SConst):
            return self._walk_const(val.value, op, st)
        if isinstance(val, SNode):
            return self._walk_node(val, op, st)
        if isinstance(val, (SScalar, SKey, SMsg, SDerived)):
            # indexing into a leaf: undefined in Rego (object-branch values
            # walked further also land here and contribute nothing). But an
            # object-ITERATION element (tok_space over prefix.*.**) may hold
            # structure on some rows — those rows' deeper walks are real in
            # Rego, so raise a row-level safety flag routing exactly the
            # rows that have matching deeper tokens to the interpreter
            # (found via the mixed-structure partner differential test:
            # spec.rules as an object map where the template iterates it).
            if (
                isinstance(val, SScalar)
                and val.tok_space
                and val.pattern_idx >= 0
            ):
                segs = self.patterns.segs(val.pattern_idx)
                if segs and segs[-1] == "**":
                    if isinstance(op, A.Scalar) and isinstance(
                        op.value, str
                    ):
                        flag_segs = segs[:-1] + (esc_seg(op.value), "**")
                    else:
                        # var/wildcard iteration, numeric/bool indexing:
                        # any one deeper segment voids the leaf read
                        flag_segs = segs[:-1] + ("?", "**")
                    flag_pat = self._pattern(flag_segs)
                    self._force_flags.append(
                        EReduce(ESelPattern(flag_pat), "any")
                    )
                    self.uses_inventory = True
            return []
        if isinstance(val, SElemProj):
            return self._walk_elem_proj(val, op, st)
        if isinstance(val, STokenSet):
            if isinstance(op, (A.Var, A.Wildcard)) and not (
                isinstance(op, A.Var) and op.name in st.env
            ):
                if val.axes:
                    raise CompileUnsupported("iterating per-axis token set", code=Reason.WALK_FORM)
                elem = SScalar(
                    self,
                    pattern_idx=-1,
                    axes=(),
                    tok_space=True,
                    sel_override=val.mask,
                    vid_override=val.elem_ids,
                    exists_override=val.mask,
                )
                st2 = replace(st, space=_space_join(st.space, ("tok",)))
                st2 = replace(st2, cond=st2.cond + [val.mask])
                return [(elem, st2)]
            raise CompileUnsupported("walking a comprehension result", code=Reason.WALK_FORM)
        raise CompileUnsupported(f"walk {type(val).__name__}", code=Reason.WALK_FORM)

    def _walk_const(self, value: Any, op: A.Term, st: State):
        if isinstance(op, A.Scalar):
            key = op.value
            if isinstance(value, dict):
                return [(SConst(value[key]), st)] if key in value else []
            if isinstance(value, list):
                if isinstance(key, (int, float)) and int(key) == key:
                    i = int(key)
                    return [(SConst(value[i]), st)] if 0 <= i < len(value) else []
                return []
            if isinstance(value, (set, frozenset)):
                return [(SConst(key), st)] if _hashable(key) in value else []
            return []
        if isinstance(op, A.Var) and op.name in st.env:
            kv = st.env[op.name]
            if isinstance(kv, SConst):
                return self._walk_const(value, A.Scalar(kv.value), st)
            return self._lookup_symbolic(value, kv, st)
        if isinstance(op, (A.Wildcard, A.Var)):
            bind = op.name if isinstance(op, A.Var) else None
            if isinstance(value, dict):
                items = list(value.items())
            elif isinstance(value, list):
                items = list(enumerate(value))
            elif isinstance(value, (set, frozenset)):
                items = [(v, v) for v in value]
            else:
                return []
            out = []
            for k, v in items:
                env = dict(st.env)
                if bind:
                    env[bind] = SConst(k)
                out.append((SConst(v), replace(st, env=env)))
            return out
        raise CompileUnsupported("const walk op", code=Reason.WALK_FORM)

    def _lookup_symbolic(self, container: Any, key: SVal, st: State):
        """concrete_container[symbolic_key] — membership/lookup condition."""
        if isinstance(container, (set, frozenset, dict, list)):
            if isinstance(container, dict):
                keys = list(container.keys())
            elif isinstance(container, list):
                keys = list(range(len(container)))
            else:
                keys = list(container)
            str_keys = [k for k in keys if isinstance(k, str)]
            if len(str_keys) != len(keys):
                raise CompileUnsupported("non-string symbolic lookup keys", code=Reason.KEYED_LOOKUP)
            ids = [self.vocab.str_id(k) for k in str_keys]
            slot = self.pool.id_set(ids)
            self.signature.append(("idset", len(self.pool.values[slot])))
            if isinstance(key, SKey):
                cond = EIsInConst(key.ids(), slot)
            elif isinstance(key, SScalar) and key.num_override is None:
                cond = e_and(key.exists(), EIsInConst(key.vid(), slot))
            else:
                raise CompileUnsupported("symbolic lookup key shape", code=Reason.KEYED_LOOKUP)
            # the VALUE is only usable when all container values are equal
            # or the result is used as a condition; return an opaque truthy
            # value guarded by membership (values in these templates are
            # `true` markers or the keys themselves)
            st2 = replace(st, cond=st.cond + [cond])
            vals = set(
                _hashable(v)
                for v in (
                    container.values()
                    if isinstance(container, dict)
                    else container
                )
            )
            if len(vals) == 1:
                return [(SConst(next(iter(vals))), st2)]
            return [(SMsg(), st2)]
        return []

    def _walk_node(self, node: SNode, op: A.Term, st: State):
        if isinstance(op, A.Scalar):
            if not isinstance(op.value, str):
                return self._iterate_indexed(node, op, st)
            if "*" in node.prefix:
                raise CompileUnsupported("field access under object iteration", code=Reason.OBJECT_ITERATION)
            return [(SNode(node.prefix + (esc_seg(op.value),)), st)]
        if isinstance(op, A.Var) and op.name in st.env:
            kv = st.env[op.name]
            if isinstance(kv, SConst):
                if isinstance(kv.value, str):
                    return [(SNode(node.prefix + (esc_seg(kv.value),)), st)]
                if kv.value is _ARRAY_INDEX:
                    raise CompileUnsupported("array index used as key", code=Reason.KEYED_LOOKUP)
                return []
            if isinstance(kv, (SKey, SScalar)):
                return self._iterate_keyed_bound(node, kv, st)
            raise CompileUnsupported("bound node key shape", code=Reason.KEYED_LOOKUP)
        if isinstance(op, (A.Wildcard, A.Var)):
            return self._iterate_node(node, op, st)
        raise CompileUnsupported("node walk op", code=Reason.WALK_FORM)

    def _iterate_indexed(self, node: SNode, op: A.Scalar, st: State):
        """containers[0] — fixed array index."""
        idx = op.value
        if not (isinstance(idx, (int, float)) and int(idx) == idx):
            return []
        raise CompileUnsupported("fixed array index", code=Reason.FIXED_INDEX)

    def _iterate_keyed_bound(self, node: SNode, key: SVal, st: State):
        """node[k] with k already bound to a symbolic key — equality join
        between the capture and the bound key (labels[key] pattern)."""
        if "*" in node.prefix or "#" in node.prefix:
            raise CompileUnsupported("keyed join under iteration", code=Reason.KEYED_LOOKUP)
        pat = self._pattern(node.prefix + ("*", "**"))
        scalar = SScalar(self, pat, axes=(), tok_space=True)
        if isinstance(key, SKey):
            cond = e_cmp("==", ECapture(pat), key.ids())
        elif isinstance(key, SScalar) and key.num_override is None:
            cond = e_and(key.exists(), e_cmp("==", ECapture(pat), key.vid()))
        else:
            raise CompileUnsupported("keyed join key shape", code=Reason.KEYED_LOOKUP)
        st2 = replace(
            st,
            cond=st.cond + [e_and(scalar.sel(), cond)],
            space=_space_join(st.space, ("tok",)),
        )
        return [(scalar, st2)]

    def _iterate_node(self, node: SNode, op: A.Term, st: State):
        bind = op.name if isinstance(op, A.Var) else None
        forks: List[Tuple[SVal, State]] = []
        # array branch: extend with "#" (lazy axis)
        axis_conflict = False
        if (
            node.prefix.count("#") < 2
            and "*" not in node.prefix
            and "tok" not in st.space
        ):
            child = SNode(node.prefix + ("#",))
            axes = _axes_of(child.prefix)
            axis = axes[-1]
            owner = st.axis_owner.get(axis)
            if owner is not None and owner != node.prefix:
                # a second array cannot share the open group axis — but
                # the node may be an OBJECT (annotations under the
                # containers axis, the seccomp/apparmor join): skip the
                # array interpretation and let the object branch handle
                # it, with a row-level safety flag for rows where the
                # node actually holds an array (those route to the
                # interpreter instead of evaluating wrong)
                axis_conflict = True
            else:
                guard_pat = self._pattern(child.prefix + ("**",))
                guard = EGroupPresent(ESelPattern(guard_pat), axis)
                guards = dict(st.guards)
                guards[axis] = guard
                owners = dict(st.axis_owner)
                owners[axis] = node.prefix
                env = dict(st.env)
                if bind:
                    # the numeric index value: comparisons against it are
                    # statically false (no library template uses it)
                    env[bind] = SConst(_ARRAY_INDEX)
                st2 = replace(
                    st,
                    env=env,
                    space=_space_join(st.space, axes),
                    guards=guards,
                    axis_owner=owners,
                )
                forks.append((child, st2))
        # object branch: token axis over keys; allowed under an open array
        # axis too (joins land on the rank-3 ("tok","g0") space)
        if st.space in ((), ("g0",)):
            pat = self._pattern(node.prefix + ("*", "**"))
            scalar = SScalar(self, pat, axes=(), tok_space=True)
            env = dict(st.env)
            if bind:
                env[bind] = SKey(pat)
            st2 = replace(
                st,
                env=env,
                space=_space_join(st.space, ("tok",)),
                cond=st.cond + [scalar.truthy()],
            )
            forks.append((scalar, st2))
            if axis_conflict:
                if self.elem_projection:
                    # ARRAY handling without a free group axis: iterate
                    # in token space via element projection. The object
                    # and projection forks select DISJOINT tokens
                    # ("*" never matches "#"), so emitting both is exact
                    # whichever shape a row actually holds — no safety
                    # flag, no interpreter routing.
                    forks.append(self._elem_proj_fork(node, bind, st))
                else:
                    # projection disabled (retry path): rows where the
                    # node IS an array must route (Rego would bind
                    # indices there; the object branch sees nothing —
                    # an under-approximation without this flag)
                    arr_pat = self._pattern(node.prefix + ("#", "**"))
                    self._force_flags.append(
                        EReduce(ESelPattern(arr_pat), "any")
                    )
                    self.uses_inventory = True
        if not forks:
            if "tok" in st.space:
                # we're inside the phantom object-branch of an earlier
                # iteration (real data there is an array, matched by the
                # sibling fork): this fork contributes nothing
                return []
            raise CompileUnsupported("iteration not representable", code=Reason.WALK_FORM)
        return forks

    def _elem_proj_fork(
        self, node: SNode, bind: Optional[str], st: State
    ) -> Tuple[SVal, State]:
        root = node.prefix + ("#",)
        elem_any = self._pattern(root + ("**",))
        val = SElemProj(root=root, rel=())
        env = dict(st.env)
        if bind:
            env[bind] = val
        st2 = replace(
            st,
            env=env,
            space=_space_join(st.space, ("tok",)),
            cond=st.cond + [ESelPattern(elem_any)],
            proj=True,
        )
        return (val, st2)

    def _walk_elem_proj(self, val: SElemProj, op: A.Term, st: State):
        if isinstance(op, A.Scalar):
            if not isinstance(op.value, str):
                raise CompileUnsupported("indexed walk under projection", code=Reason.PROJECTION)
            return [
                (replace(val, rel=val.rel + (esc_seg(op.value),)), st)
            ]
        if isinstance(op, (A.Wildcard, A.Var)) and not (
            isinstance(op, A.Var) and op.name in st.env
        ):
            # nested array under the projected element (volumeMounts[_])
            root2 = val.root + val.rel + ("#",)
            if root2.count("#") > 2:
                raise CompileUnsupported(">2 array levels in projection", code=Reason.ARRAY_DEPTH)
            elem_any = self._pattern(root2 + ("**",))
            child = SElemProj(root=root2, rel=())
            env = dict(st.env)
            if isinstance(op, A.Var):
                env[op.name] = child
            st2 = replace(
                st,
                env=env,
                space=_space_join(st.space, ("tok",)),
                cond=st.cond + [ESelPattern(elem_any)],
                proj=True,
            )
            return [(child, st2)]
        raise CompileUnsupported("projection walk op", code=Reason.PROJECTION)

    def _elem_proj_scalar(self, v: SElemProj) -> SScalar:
        """Projected subfield read: the element's per-field value
        gathered onto each of the element's tokens (see SElemProj)."""
        from .exprs import EGatherElem

        if not v.rel:
            raise CompileUnsupported("whole projected element as value", code=Reason.PROJECTION)
        ax = "g0" if v.root.count("#") == 1 else "g01"
        pat_f = self._pattern(v.root + v.rel)
        elem_any = self._pattern(v.root + ("**",))
        grp_sel = ESelPattern(pat_f)
        vid_tok = EGatherElem(
            EGroup(grp_sel, ETokCol("vid"), ax, how="max", init=-1),
            default=-1,
        )
        ex_tok = e_and(
            ESelPattern(elem_any),
            EGatherElem(
                EGroup(grp_sel, None, ax, how="any"), default=False
            ),
        )
        num_tok = EGatherElem(
            EGroup(grp_sel, ETokCol("vnum"), ax, how="max", init=NEG_INF),
            default=NEG_INF,
        )
        return SScalar(
            self,
            pattern_idx=pat_f,
            axes=(),
            tok_space=True,
            sel_override=ex_tok,
            vid_override=vid_tok,
            num_override=num_tok,
            exists_override=ex_tok,
        )

    def _elem_proj_truthy(self, v: SElemProj) -> Expr:
        """Projected-subfield truthiness (`mount.readOnly`,
        has_field-style object checks): the element has ANY token at or
        under the subfield path and its exact leaf is not `false` —
        _node_truthy's semantics, element-gathered onto tokens."""
        from .exprs import EGatherElem

        if not v.rel:
            raise CompileUnsupported("bare projected element truthiness", code=Reason.PROJECTION)
        ax = "g0" if v.root.count("#") == 1 else "g01"
        deep = self._pattern(v.root + v.rel + ("**",))
        exact = self._pattern(v.root + v.rel)
        elem_any = self._pattern(v.root + ("**",))
        deep_any = EGatherElem(
            EGroup(ESelPattern(deep), None, ax, how="any"), default=False
        )
        false_leaf = e_and(
            ESelPattern(exact),
            e_cmp("==", ETokCol("vid"), ELit(self.false_id)),
        )
        has_false = EGatherElem(
            EGroup(false_leaf, None, ax, how="any"), default=False
        )
        return e_and(
            ESelPattern(elem_any), e_and(deep_any, e_not(has_false))
        )

    def _node_leaf(self, node: SNode) -> SScalar:
        if "*" in node.prefix:
            raise CompileUnsupported("leaf under object iteration", code=Reason.OBJECT_ITERATION)
        pat = self._pattern(node.prefix)
        return SScalar(self, pat, axes=_axes_of(node.prefix))

    def _eval_rule_ref(self, name: str, ops: List[A.Term], st: State):
        rules = self.rules[name]
        kind = rules[0].head.kind
        if kind == "set":
            if not ops:
                raise CompileUnsupported("bare partial-set ref as value", code=Reason.RULE_REF)
            out: List[Tuple[SVal, State]] = []
            for rule in rules:
                for v, s in self._iterate_partial_set(rule, ops[0], st):
                    out.extend(self._walk(v, ops[1:], s))
            return out
        if kind == "complete":
            if len(rules) == 1 and not rules[0].is_default:
                rule = rules[0]
                if not rule.body:
                    forks = self._eval_term(rule.head.value, st)
                else:
                    # computed complete rule (requiredprobes' probe_type_set):
                    # compile only when the body resolves concretely
                    sub = State(env={})
                    with self._inv_barrier():
                        finals = self._eval_body(rule.body, sub)
                    if len(finals) != 1 or finals[0].cond or finals[0].space:
                        raise CompileUnsupported("computed complete rule", code=Reason.RULE_REF)
                    forks = self._eval_term(rule.head.value, finals[0])
                    forks = [(v, st) for v, _ in forks]
                out = []
                for v, s in forks:
                    out.extend(self._walk(v, ops, s))
                return out
            raise CompileUnsupported("computed complete rule ref", code=Reason.RULE_REF)
        raise CompileUnsupported(f"rule ref {kind}", code=Reason.RULE_REF)

    def _iterate_partial_set(self, rule: A.Rule, op: A.Term, st: State):
        """Iterate/match a same-module partial set rule.

        Object-literal operands (the containerlimits
        `general_violation[{"msg": msg, "field": "containers"}]` pattern)
        unify field-by-field with an object-literal head key: caller-side
        constants PRE-BIND the head's variables before the body runs,
        caller-side unbound variables bind from the head afterwards.
        """
        pre_env: Dict[str, SVal] = {}
        post_binds: List[Tuple[str, A.Term]] = []
        if isinstance(op, A.ObjectTerm):
            if not isinstance(rule.head.key, A.ObjectTerm):
                return []
            head_map = {}
            for hk, hval in rule.head.key.items:
                if not isinstance(hk, A.Scalar):
                    raise CompileUnsupported("computed head key field", code=Reason.PARTIAL_SET)
                head_map[hk.value] = hval
            # interpreter object-pattern semantics are SUBSET match:
            # every caller field must exist in the head element, extra
            # head fields are ignored (interp.py:_bind_pattern). A
            # caller field the head lacks can never unify.
            caller_keys = {
                k.value for k, _ in op.items if isinstance(k, A.Scalar)
            }
            if len(caller_keys) != len(op.items):
                raise CompileUnsupported("computed pattern field key", code=Reason.PARTIAL_SET)
            if not caller_keys <= set(head_map):
                return []  # caller field missing from head: no match
            for k, v in op.items:
                hterm = head_map[k.value]
                if isinstance(v, A.Var) and v.name not in st.env:
                    post_binds.append((v.name, hterm))
                    continue
                if isinstance(v, A.Wildcard):
                    continue
                vf = self._eval_term(v, st)
                if len(vf) != 1 or not isinstance(vf[0][0], SConst):
                    raise CompileUnsupported("non-const pattern field", code=Reason.PARTIAL_SET)
                cv = vf[0][0]
                if isinstance(hterm, A.Var):
                    pre_env[hterm.name] = cv
                elif isinstance(hterm, A.Scalar):
                    if hterm.value != cv.value:
                        return []  # statically mismatched clause
                else:
                    raise CompileUnsupported("head field shape", code=Reason.PARTIAL_SET)
        elif not isinstance(op, (A.Var, A.Wildcard)):
            raise CompileUnsupported("partial-set operand shape", code=Reason.PARTIAL_SET)

        sub = State(env=pre_env, space=st.space, guards=dict(st.guards), axis_owner=dict(st.axis_owner))
        with self._inv_barrier():
            finals = self._eval_body(rule.body, sub)
        out = []
        for f in finals:
            for hv, hs in self._eval_term(rule.head.key, f):
                merged = replace(
                    st,
                    cond=st.cond + hs.cond,
                    space=hs.space,
                    guards=hs.guards,
                    axis_owner=hs.axis_owner,
                    proj=st.proj or hs.proj,
                )
                env = dict(merged.env)
                if isinstance(op, A.Var) and op.name not in st.env:
                    env[op.name] = hv
                for var_name, hterm in post_binds:
                    bf = self._eval_term(hterm, hs)
                    if len(bf) != 1:
                        raise CompileUnsupported("forking head field", code=Reason.FORKING)
                    env[var_name] = bf[0][0]
                merged = replace(merged, env=env)
                out.append((hv, merged))
        return out

    # -- calls --------------------------------------------------------------

    def _eval_call(self, call: A.Call, st: State):
        arg_forks: List[Tuple[List[SVal], State]] = [([], st)]
        for arg in call.args:
            nxt = []
            for vals, s in arg_forks:
                for v, s2 in self._eval_term(arg, s):
                    if isinstance(v, SNode):
                        # call operands are evaluated before the call:
                        # undefined args make the whole call undefined
                        s2 = replace(
                            s2,
                            cond=s2.cond + [self._node_exists_cond(v)],
                        )
                    nxt.append((vals + [v], s2))
            arg_forks = nxt
        out: List[Tuple[SVal, State]] = []
        for vals, s in arg_forks:
            out.extend(self._apply_call(call.name, vals, s))
        return out

    def _apply_call(self, name: str, args: List[SVal], st: State):
        # derived-value provenance for render pruning: an opaque result
        # of a pure 1-arg template helper remembers WHOSE value it is —
        # F(<review subdoc>) or F(<inventory-walked object>). Applied to
        # every opaque outcome (the inline may "succeed" opaquely when
        # its comprehension screens out, or abort outright).
        base = name.split(".")[-1] if "." in name else name
        tag = None
        if len(args) == 1 and base in self.rules:
            if isinstance(args[0], SNode):
                tag = ("rev", base, args[0].prefix)
            elif (
                isinstance(args[0], SInventory)
                and args[0].path is not None
            ):
                tag = ("inv", base, args[0].path)

        def tagged(outs):
            if tag is None:
                return outs
            return [
                (
                    replace(v, call_tag=tag)
                    if isinstance(v, SInventory) and v.call_tag is None
                    else v,
                    s,
                )
                for v, s in outs
            ]

        if name == "external_data":
            # out-of-band lookup: never exactly compilable (the answer
            # lives outside the review), but in screen mode the response
            # is opaque and the clause gains the extdata row feature —
            # the dispatch layer fills it from the batch-prefetched
            # response cache, so clean cache-hit rows stay fused and
            # only cold-miss/error rows take the interpreter rung
            if not self.screen_mode:
                raise CompileUnsupported("external_data (compiles as a batch-prefetched screen)", code=Reason.EXTERNAL_DATA)
            self.uses_inventory = True
            self.opaque = True
            feat = self.cenv.extdata_feature
            if feat:
                self._clause_extfeats.append(feat)
            return [(SInventory(), st)]
        if any(isinstance(a, SInventory) for a in args):
            # calls over inventory values (identical(), flatten_selector,
            # re_match on an iterated apiversion, sprintf into the msg)
            # produce opaque values; conditions on them drop later
            return tagged([(SInventory(), st)])
        if self.screen_mode:
            try:
                return tagged(self._apply_call_inner(name, args, st))
            except (CompileUnsupported, InventoryDependent):
                # InventoryDependent escaping a function body (via the
                # _inv_barrier) means the call's value depends on
                # inventory content: opaque, conditions on it drop
                self.uses_inventory = True
                self.opaque = True
                return tagged([(SInventory(), st)])
        return self._apply_call_inner(name, args, st)

    def _apply_call_inner(self, name: str, args: List[SVal], st: State):
        if name in self.rules:
            return self._inline_function(name, args, st)
        handler = getattr(self, f"_builtin_{name.replace('.', '_')}", None)
        if handler is not None:
            return handler(args, st)
        if all(isinstance(a, SConst) for a in args):
            from ..rego.builtins import BUILTINS, BuiltinError
            from ..rego.values import freeze, thaw

            if name in BUILTINS:
                arity, fn = BUILTINS[name]
                if arity != len(args):
                    raise CompileUnsupported(f"{name} arity", code=Reason.FUNCTION_CALL)
                try:
                    v = fn(*[freeze(a.value) for a in args])
                except BuiltinError:
                    return []
                return [(SConst(thaw(v)), st)]
        raise CompileUnsupported(f"builtin {name} symbolic", code=Reason.UNSUPPORTED_BUILTIN)

    def _inline_function(self, name: str, args: List[SVal], st: State):
        if self._fn_depth > 8:
            raise CompileUnsupported("inline depth", code=Reason.FUNCTION_CALL)
        rules = self.rules[name]
        if rules[0].head.kind != "func":
            raise CompileUnsupported(f"{name} not a function", code=Reason.FUNCTION_CALL)
        try:
            return self._inline_function_body(name, rules, args, st)
        except CompileUnsupported:
            # fall back to per-vocab-entry tableization for pure scalar
            # helpers (canonify_cpu & co)
            tabled = self._tableize_function(name, args, st)
            if tabled is not None:
                return tabled
            raise

    def _inline_function_body(
        self, name: str, rules: List[A.Rule], args: List[SVal], st: State
    ):
        try:
            return self._inline_function_rules(name, rules, args, st)
        except CompileUnsupported as e:
            raise e.annotate(rule=name, line=rules[0].line)

    def _inline_function_rules(
        self, name: str, rules: List[A.Rule], args: List[SVal], st: State
    ):
        self._fn_depth += 1
        try:
            out: List[Tuple[SVal, State]] = []
            for rule in rules:
                formals = rule.head.args or []
                if len(formals) != len(args):
                    continue
                sub = State(env={}, space=st.space, guards=dict(st.guards), axis_owner=dict(st.axis_owner))
                ok = True
                for formal, actual in zip(formals, args):
                    if isinstance(formal, A.Var):
                        sub.env[formal.name] = actual
                    elif isinstance(formal, A.Wildcard):
                        continue
                    elif isinstance(formal, A.Scalar):
                        if isinstance(actual, SConst):
                            if actual.value != formal.value:
                                ok = False
                                break
                        else:
                            cond, okk = self._sym_eq(
                                actual, SConst(formal.value)
                            )
                            if not okk:
                                raise CompileUnsupported("formal pattern", code=Reason.FUNCTION_CALL)
                            sub.cond.append(cond)
                    else:
                        raise CompileUnsupported("formal pattern shape", code=Reason.FUNCTION_CALL)
                if not ok:
                    continue
                with self._inv_barrier():
                    finals = self._eval_body(rule.body, sub)
                for f in finals:
                    vf = (
                        self._eval_term(rule.head.value, f)
                        if rule.head.value is not None
                        else [(SConst(True), f)]
                    )
                    for hv, hs in vf:
                        merged = replace(
                            st,
                            cond=st.cond + hs.cond,
                            space=hs.space,
                            guards=hs.guards,
                            proj=st.proj or hs.proj,
                        )
                        out.append((hv, merged))
            return out
        finally:
            self._fn_depth -= 1

    def _tableize_function(self, name: str, args: List[SVal], st: State):
        """Pure helper with exactly ONE symbolic scalar argument (the
        rest constants) -> per-vocab-entry value table. The constants
        fold into the table identity, so e.g. host-filesystem's
        `path_matches(<const prefix>, volume.hostPath.path)` becomes
        one boolean table over distinct path strings per prefix."""
        if self.cenv.oracle_fn is None or not args:
            return None
        sym_idx = -1
        consts: List[Any] = []
        for i, a in enumerate(args):
            if isinstance(a, SConst):
                if not _jsonable(a.value):
                    return None
                consts.append(a.value)
                continue
            if sym_idx >= 0:
                return None  # at most one symbolic slot
            sym_idx = i
            consts.append(None)
        if sym_idx < 0:
            return None
        arg = self._leafify(args[sym_idx])
        if not isinstance(arg, (SScalar, SKey)):
            return None
        if isinstance(arg, SScalar) and arg.num_override is not None:
            return None
        if not self._fn_is_pure(name, set()):
            return None
        if not self._fn_arg_scalar(name, sym_idx=sym_idx):
            return None
        oracle = self.cenv.oracle_fn
        ns = self.cenv.oracle_ns
        reads_params = self._fn_reads_params(name, set())
        if self.cenv.oracle_ns_shared and not reads_params:
            ns = self.cenv.oracle_ns_shared
        # content hash over the whole module rule set: any template edit
        # invalidates the persisted oracle memo (conservatively)
        if not hasattr(self, "_rules_hash"):
            import hashlib

            self._rules_hash = hashlib.sha256(
                repr(sorted((k, repr(v)) for k, v in self.rules.items()))
                .encode()
            ).hexdigest()
        # ORACLE_MEMO_VERSION salts the key so oracle/interpreter
        # implementation changes invalidate persisted memos
        persist_key = f"v{ORACLE_MEMO_VERSION}|{self._rules_hash}|{name}"
        if reads_params:
            persist_key += f"|{json.dumps(self.params, sort_keys=True, default=str)}"
        table_id = f"fn:{ns}:{name}"
        call_extra = None
        if len(args) > 1:
            # fold the constant arguments into the table identity: one
            # table per (function, const combination)
            cjson = json.dumps(consts, sort_keys=True, default=str)
            import hashlib as _hl

            chash = _hl.sha256(cjson.encode()).hexdigest()[:16]
            table_id += f":{sym_idx}:{chash}"
            persist_key += f"|{sym_idx}|{cjson}"
            call_extra = (sym_idx, consts)
        tname = self.tables.register(
            table_id,
            lambda v, _n=name, _o=oracle, _e=call_extra: _numeric_oracle(
                _o, _n, v, extra=_e
            ),
            dtype="float64",
            persist_key=persist_key,
        )
        self.signature.append(("table", tname))
        if isinstance(arg, SScalar):
            ids = arg.vid()
            base_def = arg.exists()
        else:
            ids = arg.ids()
            base_def = e_cmp("!=", arg.ids(), ELit(-1))
        num = EStrTable(tname, ids, default=0.0)
        dfn = e_and(base_def, EStrTable(tname + "!def", ids, default=False))
        return [(SDerived(num=num, defined=dfn), st)]

    def _fn_arg_scalar(self, name: str, sym_idx: int = 0) -> bool:
        """True if the function never walks into its SYMBOLIC formal
        (required for vid-keyed tableization; const formals pass whole
        frozen values to the oracle, so walking them is fine)."""
        for rule in self.rules.get(name, []):
            head_args = rule.head.args or []
            formals = {
                f.name
                for i, f in enumerate(head_args)
                if isinstance(f, A.Var) and i == sym_idx
            }
            bad = []

            def visit(node):
                if (
                    isinstance(node, A.Ref)
                    and isinstance(node.head, A.Var)
                    and node.head.name in formals
                    and node.ops
                ):
                    bad.append(node.head.name)

            import dataclasses as _dc

            def walk(n):
                if isinstance(n, A.Node):
                    visit(n)
                    for f in _dc.fields(n):
                        walk(getattr(n, f.name))
                elif isinstance(n, (list, tuple)):
                    for x in n:
                        walk(x)

            walk(rule)
            if bad:
                return False
        return True

    def _fn_reads_params(self, name: str, seen: set) -> bool:
        """True if the function's call graph touches input.parameters
        (then its table must stay per-params)."""
        if name in seen:
            return False
        seen.add(name)
        reads = []

        def visit(node):
            if (
                isinstance(node, A.Ref)
                and isinstance(node.head, A.Var)
                and node.head.name == "input"
            ):
                reads.append("input")
            if isinstance(node, A.Call):
                base = (
                    node.name.split(".")[-1] if "." in node.name
                    else node.name
                )
                if base in self.rules and self._fn_reads_params(base, seen):
                    reads.append(base)
            if isinstance(node, A.Ref) and isinstance(node.head, A.Var):
                if node.head.name in self.rules and self._fn_reads_params(
                    node.head.name, seen
                ):
                    reads.append(node.head.name)

        import dataclasses as _dc

        def walk(n):
            if isinstance(n, A.Node):
                visit(n)
                for f in _dc.fields(n):
                    walk(getattr(n, f.name))
            elif isinstance(n, (list, tuple)):
                for x in n:
                    walk(x)

        for rule in self.rules.get(name, []):
            walk(rule)
        return bool(reads)

    def _fn_is_pure(self, name: str, seen: set) -> bool:
        """No input.review / data refs anywhere in the call graph
        (input.parameters is concrete and allowed)."""
        if name in seen:
            return True
        seen.add(name)
        from ..constraint.regocompile import walk_module as _walk_rules

        impure = []

        def visit(node):
            if isinstance(node, A.Ref) and isinstance(node.head, A.Var):
                if node.head.name == "data":
                    impure.append("data")
                if node.head.name in self.rules and not self._fn_is_pure(
                    node.head.name, seen
                ):
                    impure.append(node.head.name)
                if node.head.name == "input":
                    if (
                        node.ops
                        and isinstance(node.ops[0], A.Scalar)
                        and node.ops[0].value == "parameters"
                    ):
                        return
                    impure.append("input")
            if isinstance(node, A.Call):
                base = node.name.split(".")[-1] if "." in node.name else node.name
                if base in self.rules and not self._fn_is_pure(base, seen):
                    impure.append(base)

        import dataclasses as _dc

        def walk(n):
            if isinstance(n, A.Node):
                visit(n)
                for f in _dc.fields(n):
                    walk(getattr(n, f.name))
            elif isinstance(n, (list, tuple)):
                for x in n:
                    walk(x)

        for rule in self.rules.get(name, []):
            walk(rule)
        return not impure

    # -- binops -------------------------------------------------------------

    def _eval_binop(self, term: A.BinOp, st: State):
        out = []
        for lv, s1 in self._eval_term(term.lhs, st):
            for rv, s2 in self._eval_term(term.rhs, s1):
                r = self._apply_binop(term.op, lv, rv, s2)
                if r is not None:
                    out.append(r)
        return out

    def _apply_binop(self, op: str, lv: SVal, rv: SVal, st: State):
        if isinstance(lv, SInventory) or isinstance(rv, SInventory):
            # equality joins between a review-side leaf and inventory
            # content record the leaf's pattern: the dispatch layer then
            # supplies a per-row "join key duplicated in the inventory"
            # feature that SHARPENS the screen (rows whose keys are
            # unique cluster-wide cannot violate a uniqueness join and
            # need no interpreter re-check).
            # The _no_inv_catch guard is load-bearing for soundness: it
            # restricts recording to TOP-LEVEL clause conjuncts. Inside
            # negations the join is anti-monotone, and inside function/
            # rule/comprehension bodies the equality may sit in ONE of
            # several definitions — ANDing the refinement into the whole
            # clause would wrongly screen forks that can violate without
            # the join (those constructs run under the _inv_barrier).
            if op == "==" and self._no_inv_catch == 0:
                # derived-key join (flatten_selector idiom): BOTH sides
                # opaque results of the same pure helper F, one over a
                # review subdocument, one over a full-tree inventory
                # walk. The clause then implies F(other) == F(review
                # side), so the interpreter render may soundly restrict
                # the inventory to a host-built F-key index's candidates
                # (VERDICT r3 #4: uniqueserviceselector at scale).
                if (
                    isinstance(lv, SInventory)
                    and isinstance(rv, SInventory)
                ):
                    tags = {}
                    for t in (lv.call_tag, rv.call_tag):
                        if t is not None:
                            tags[t[0]] = t
                    if (
                        len(tags) == 2
                        and tags["rev"][1] == tags["inv"][1]
                        and self._fn_is_pure(tags["rev"][1], set())
                    ):
                        walk = tags["inv"][2]
                        tree = walk[0] if walk else None
                        depth_ok = (
                            tree == "namespace" and len(walk) == 5
                            or tree == "cluster" and len(walk) == 4
                        ) and all(s == "?" for s in walk[1:])
                        if depth_ok:
                            self._clause_prunes.append(
                                (tags["rev"][1], tags["rev"][2], tree)
                            )
                inv = lv if isinstance(lv, SInventory) else rv
                other = rv if isinstance(lv, SInventory) else lv
                try:
                    leaf = self._leafify(other)
                except CompileUnsupported:
                    leaf = None
                if (
                    isinstance(leaf, SScalar)
                    and leaf.pattern_idx >= 0
                    and leaf.num_override is None
                    and leaf.vid_override is None
                    and isinstance(inv, SInventory)
                ):
                    mirror = self._mirror_pattern_for(
                        inv, leaf.pattern_idx
                    )
                    if mirror is not None:
                        self._clause_joins.append(
                            (leaf.pattern_idx, mirror, inv.root)
                        )
                    # path-key join (uniqueingresshost idiom): a review
                    # leaf equality-joined against a PATH deref of the
                    # walked object (`other.spec.rules[_].host == host`).
                    # Record a path-form prune: the render may restrict
                    # the inventory to objects carrying one of the
                    # review's key values at that relative path — the
                    # top-level equality conjunct guarantees every
                    # violating partner shares a key (VERDICT r4 weak
                    # #5; reference
                    # library/general/uniqueingresshost/src.rego).
                    rel = self._inv_rel_path(inv)
                    psegs = self.patterns.segs(leaf.pattern_idx)
                    if rel is not None and "**" not in psegs:
                        self._clause_prunes.append(
                            ("path", rel, psegs, inv.path[0])
                        )
            raise InventoryDependent()
        if isinstance(lv, SConst) and isinstance(rv, SConst):
            return self._const_binop(op, lv, rv, st)
        if op in ("==", "!=", "<", "<=", ">", ">="):
            return self._sym_cmp(op, lv, rv, st)
        if op in ("+", "-", "*", "/", "%"):
            if op == "-" and isinstance(lv, (SConst, STokenSet)) and (
                isinstance(rv, (SConst, STokenSet))
            ):
                maybe = self._set_difference(lv, rv, st)
                if maybe is not None:
                    return maybe
            return self._sym_arith(op, lv, rv, st)
        if op in ("&", "|"):
            raise CompileUnsupported("symbolic set intersection/union", code=Reason.BINOP)
        raise CompileUnsupported(f"binop {op}", code=Reason.BINOP)

    def _mirror_pattern_for(
        self, inv: "SInventory", leaf_pid: int
    ) -> Optional[int]:
        """The partner-side counting pattern for an inventory equality
        join, or None when the refinement must be skipped (ADVICE r3
        high: refining a cross-path join at the review leaf's own
        pattern under-approximates and misses violations).

        Sound iff every concrete partner token path consistent with the
        walk matches the returned pattern AND the leaf's own pattern is
        a sub-pattern of it (so the row self-counts, keeping the
        duplicate threshold meaningful). That holds when the walk
        addresses an object root (data.inventory.namespace[.][.][.][.]
        or .cluster[.][.][.]) and the remaining segments positionally
        mirror the leaf pattern: equal literals, or "?" (var-iterated —
        "?" matches ANY one segment, so it covers both the partner's
        real structure and the leaf's "#"/"*" position)."""
        if inv.path is None or not inv.path:
            return None
        if inv.path[0] == "namespace" and len(inv.path) >= 5:
            obj = inv.path[5:]
        elif inv.path[0] == "cluster" and len(inv.path) >= 4:
            obj = inv.path[4:]
        else:
            return None
        psegs = self.patterns.segs(leaf_pid)
        # partners are inventory objects encoded as synthesized reviews,
        # so their tokens live under the "object" root; a leaf outside
        # it (e.g. oldObject) cannot self-count — skip. A "**" leaf
        # matches variable depth, which no fixed-length mirror covers
        # (the row would not self-count at depths the mirror misses).
        if not psegs or psegs[0] != "object" or "**" in psegs:
            return None
        body = psegs[1:]
        if len(body) != len(obj):
            return None
        mirror: List[str] = ["object"]
        for p, m in zip(body, obj):
            if m == "?":
                mirror.append("?")
            elif p == m and p not in ("*", "?", "**"):
                mirror.append(m)
            else:
                return None
        if tuple(mirror) == tuple(psegs):
            return leaf_pid
        return self._pattern(tuple(mirror))

    def _note_self_exclusion(self, inner: A.Expr, st: State) -> None:
        """Detect the uniqueness-template self-exclusion idiom
        `not identical(<inventory obj>, input.review)` (reference:
        library/general/uniqueingresshost/src.rego identical/2) while
        its InventoryDependent escapes the negation barrier.

        Without a proven self-exclusion an object can join with ITSELF
        (it is part of the synced inventory), so "key carried by >=2
        distinct rows" no longer bounds violations and the refinement
        threshold must drop to 1. Records (inventory root, guard
        pattern ids) — the guard paths are the identity fields the
        proof needs DEFINED on the row (an object missing one, e.g.
        metadata.namespace on a cluster-scoped kind, makes identical()
        undefined and the exclusion void for that row)."""
        if not isinstance(inner, A.TermExpr) or not isinstance(
            inner.term, A.Call
        ):
            return
        call = inner.term
        if call.name not in self.rules or len(call.args) != 2:
            return
        a0 = call.args[0]
        if not isinstance(a0, A.Var):
            return
        inv = st.env.get(a0.name)
        if not isinstance(inv, SInventory) or inv.path is None:
            return
        rootlen = (
            5 if inv.path[:1] == ("namespace",)
            else 4 if inv.path[:1] == ("cluster",)
            else -1
        )
        if rootlen < 0 or len(inv.path) != rootlen:
            return
        if not _is_review_ref(call.args[1], st):
            return
        for rule in self.rules[call.name]:
            gpaths = _self_identity_paths(rule)
            if gpaths is not None:
                gpids = tuple(
                    self._pattern(
                        ("object",) + tuple(esc_seg(s) for s in gp)
                    )
                    for gp in gpaths
                )
                self._clause_guards.append((inv.root, gpids))
                return

    def _const_binop(self, op: str, lv: SConst, rv: SConst, st: State):
        from ..rego.values import freeze, rego_cmp

        if lv.value is _ARRAY_INDEX or rv.value is _ARRAY_INDEX:
            # array-index binding compared to a concrete value: unknown
            # number vs (usually) string — only == / != are decidable when
            # the other side is not a number
            other = rv.value if lv.value is _ARRAY_INDEX else lv.value
            if op == "==" and not isinstance(other, (int, float)):
                return (SConst(False), st)
            if op == "!=" and not isinstance(other, (int, float)):
                return (SConst(True), st)
            raise CompileUnsupported("comparison with array index", code=Reason.COMPARISON)
        if op in ("==", "!=", "<", "<=", ">", ">="):
            c = rego_cmp(freeze(lv.value), freeze(rv.value))
            res = {
                "==": c == 0,
                "!=": c != 0,
                "<": c < 0,
                "<=": c <= 0,
                ">": c > 0,
                ">=": c >= 0,
            }[op]
            return (SConst(res), st)
        a, b = lv.value, rv.value
        if isinstance(a, (set, frozenset)) and isinstance(b, (set, frozenset)):
            res = {"-": a - b, "&": a & b, "|": a | b}.get(op)
            if res is None:
                raise CompileUnsupported("const set op", code=Reason.BINOP)
            return (SConst(res), st)
        if (
            isinstance(a, (int, float))
            and isinstance(b, (int, float))
            and not isinstance(a, bool)
            and not isinstance(b, bool)
        ):
            if op in ("/", "%") and b == 0:
                return None
            res = {
                "+": a + b,
                "-": a - b,
                "*": a * b,
                "/": a / b if b != 0 else None,
                "%": a % b if b != 0 else None,
            }[op]
            return (SConst(res), st)
        raise CompileUnsupported("const binop types", code=Reason.BINOP)

    def _set_difference(self, lv: SVal, rv: SVal, st: State):
        """Set difference where at least one side is token-derived."""
        if isinstance(lv, SConst) and isinstance(rv, STokenSet):
            if not isinstance(lv.value, (set, frozenset)):
                return None
            elems = [v for v in lv.value]
            if not all(_is_scalar_const(v) for v in elems):
                raise CompileUnsupported("const set of composites", code=Reason.BINOP)
            # count(missing) = #elems whose id never appears in the token set
            self.signature.append(("constdiff", len(elems)))
            if not elems:
                return (SDerived(num=EFullN(0), defined=ELit(True)), st)
            terms = []
            for v in elems:
                vid = self.vocab.val_id(_norm_num(v))
                slot = self.pool.id_scalar(vid)
                present = rv.reduce_any(e_cmp("==", rv.elem_ids, slot))
                terms.append(
                    EMap(
                        lambda np_, p: (~p).astype(np.int32), [present], "miss"
                    )
                )
            cnt = terms[0]
            for t in terms[1:]:
                cnt = e_arith("+", cnt, t)
            return (
                SDerived(
                    num=cnt,
                    defined=ELit(True),
                    render=("constdiff", tuple(elems), rv),
                ),
                st,
            )
        if isinstance(lv, STokenSet) and isinstance(rv, SConst):
            if not isinstance(rv.value, (set, frozenset)):
                return None
            elems = [v for v in rv.value if _is_scalar_const(v)]
            ids = [self.vocab.val_id(_norm_num(v)) for v in elems]
            slot = self.pool.id_set(ids)
            self.signature.append(("idset", len(self.pool.values[slot])))
            mask = e_and(lv.mask, e_not(EIsInConst(lv.elem_ids, slot)))
            return (STokenSet(mask, lv.elem_ids, lv.axes), st)
        if isinstance(lv, STokenSet) and isinstance(rv, STokenSet):
            raise CompileUnsupported("token-set minus token-set", code=Reason.BINOP)
        return None

    def _sym_arith(self, op: str, lv: SVal, rv: SVal, st: State):
        ln, rn = self._as_num(lv), self._as_num(rv)
        if ln is None or rn is None:
            raise CompileUnsupported("non-numeric arithmetic", code=Reason.BINOP)
        val = e_arith(op, ln[0], rn[0])
        defined = e_and(ln[1], rn[1])
        if op in ("/", "%"):
            defined = e_and(defined, e_cmp("!=", rn[0], ELit(0.0)))
        return (SDerived(num=val, defined=defined), st)

    def _as_num(self, v: SVal):
        v = self._leafify(v)
        if isinstance(v, SConst):
            if isinstance(v.value, bool) or not isinstance(
                v.value, (int, float)
            ):
                return None
            slot = self.pool.scalar(float(v.value))
            self.signature.append(("num",))
            return (slot, ELit(True))
        if isinstance(v, SDerived):
            return (v.num, v.defined)
        if isinstance(v, SScalar):
            if v.num_override is not None:
                return (v.num_override, v.exists())
            return (
                v.num(),
                e_and(v.exists(), e_cmp("==", v.kindv(), ELit(K_NUM))),
            )
        return None

    def _materialize_msg(self, v: SVal) -> SVal:
        """SMsg with a transform recipe -> derived SScalar (comparison
        position forces the lazy sprintf into an id-transform table)."""
        if isinstance(v, SMsg) and v.recipe is not None:
            fv, arg = v.recipe
            forks = self._str_transform(
                arg,
                State(env={}),
                f"sprintf:{fv}",
                lambda s, _f=fv: _f.replace("%v", s, 1),
            )
            if forks:
                part = forks[0][0]
                if isinstance(part, SScalar):
                    return replace(part, msg_sig=v.sig)
                return part
        return v

    def _sym_eq(self, lv: SVal, rv: SVal) -> Tuple[Expr, bool]:
        lv, rv = self._leafify(lv), self._leafify(rv)
        lv, rv = self._materialize_msg(lv), self._materialize_msg(rv)
        if isinstance(lv, SConst) and not isinstance(rv, SConst):
            lv, rv = rv, lv
        if isinstance(rv, SConst):
            cv = rv.value
            if isinstance(lv, SDerived):
                if isinstance(cv, bool) or not isinstance(cv, (int, float)):
                    return ELit(False), True
                slot = self.pool.scalar(float(cv))
                self.signature.append(("num",))
                return e_and(lv.defined, e_cmp("==", lv.num, slot)), True
            if isinstance(lv, SScalar):
                if lv.num_override is not None:
                    if isinstance(cv, bool) or not isinstance(
                        cv, (int, float)
                    ):
                        return ELit(False), True
                    slot = self.pool.scalar(float(cv))
                    self.signature.append(("num",))
                    return (
                        e_and(
                            lv.exists(),
                            e_cmp("==", lv.num_override, slot),
                        ),
                        True,
                    )
                if _is_scalar_const(cv):
                    slot = self.pool.id_scalar(
                        self.vocab.val_id(_norm_num(cv))
                    )
                    self.signature.append(("id",))
                    return (
                        e_and(lv.exists(), e_cmp("==", lv.vid(), slot)),
                        True,
                    )
                return ELit(False), True
            if isinstance(lv, SKey):
                if isinstance(cv, str):
                    slot = self.pool.id_scalar(self.vocab.str_id(cv))
                    self.signature.append(("id",))
                    return e_cmp("==", lv.ids(), slot), True
                return ELit(False), True
            raise CompileUnsupported("eq const shape", code=Reason.COMPARISON)
        if isinstance(lv, SKey) and isinstance(rv, SScalar):
            lv, rv = rv, lv
        if isinstance(lv, SScalar) and isinstance(rv, SKey):
            return (
                e_and(
                    e_and(lv.exists(), e_cmp("==", lv.kindv(), ELit(K_STR))),
                    e_cmp("==", lv.vid(), rv.ids()),
                ),
                True,
            )
        if isinstance(lv, SKey) and isinstance(rv, SKey):
            return e_cmp("==", lv.ids(), rv.ids()), True
        if isinstance(lv, SScalar) and isinstance(rv, SScalar):
            # vid identity is exact whenever both sides HAVE a vid: a
            # num_override alone marks a derived number (no vid), but
            # projected subfields carry BOTH overrides and must compare
            # by typed id, not by lossy vnum
            l_vid = lv.num_override is None or lv.vid_override is not None
            r_vid = rv.num_override is None or rv.vid_override is not None
            if l_vid and r_vid:
                return (
                    e_and(
                        e_and(lv.exists(), rv.exists()),
                        e_cmp("==", lv.vid(), rv.vid()),
                    ),
                    True,
                )
        ln, rn = self._as_num(lv), self._as_num(rv)
        if ln and rn:
            return (
                e_and(e_and(ln[1], rn[1]), e_cmp("==", ln[0], rn[0])),
                True,
            )
        return ELit(False), False

    def _sym_cmp(self, op: str, lv: SVal, rv: SVal, st: State):
        lv, rv = self._leafify(lv), self._leafify(rv)
        if op in ("==", "!="):
            cond, ok = self._sym_eq(lv, rv)
            if not ok:
                raise CompileUnsupported("eq shapes", code=Reason.COMPARISON)
            if op == "!=":
                defs = []
                for v in (lv, rv):
                    if isinstance(v, SScalar):
                        defs.append(v.exists())
                    elif isinstance(v, SDerived):
                        defs.append(v.defined)
                cond = e_not(cond)
                for d in defs:
                    cond = e_and(cond, d)
            return (SBool(cond), st)
        ln, rn = self._as_num(lv), self._as_num(rv)
        if ln and rn:
            return (SBool(e_and(e_and(ln[1], rn[1]), e_cmp(op, ln[0], rn[0]))), st)
        if (
            isinstance(lv, SScalar)
            and lv.num_override is None
            and isinstance(rv, SConst)
            and isinstance(rv.value, str)
        ):
            tname = self.tables.register(
                f"cmp{op}:{rv.value}",
                lambda s, _c=rv.value, _o=op: (
                    {"<": s < _c, "<=": s <= _c, ">": s > _c, ">=": s >= _c}[
                        _o
                    ],
                    True,
                ),
                dtype=bool,
            )
            self.signature.append(("table", tname))
            cond = e_and(
                e_and(
                    lv.exists(), e_cmp("==", lv.kindv(), ELit(K_STR))
                ),
                EStrTable(tname, lv.vid()),
            )
            return (SBool(cond), st)
        raise CompileUnsupported(f"cmp {op} shapes", code=Reason.COMPARISON)

    # -- conditions ---------------------------------------------------------

    def _eval_cond_term(self, term: A.Term, st: State) -> List[State]:
        out = []
        for v, s in self._eval_term(term, st):
            c = self._truthiness(v, s)
            if c is None:
                continue
            if c is True:
                out.append(s)
            else:
                out.append(replace(s, cond=s.cond + [c]))
        return out

    def _truthiness(self, v: SVal, st: State):
        if isinstance(v, SInventory):
            raise InventoryDependent()
        if isinstance(v, SConst):
            return True if v.value is not False else None
        if isinstance(v, SBool):
            return v.expr
        if isinstance(v, SDerived):
            return v.defined
        if isinstance(v, SScalar):
            return v.truthy()
        if isinstance(v, SNode):
            return self._node_truthy(v)
        if isinstance(v, SElemProj):
            return self._elem_proj_truthy(v)
        if isinstance(v, (SMsg, SKey, STokenSet, SList)):
            return True
        raise CompileUnsupported(f"truthiness {type(v).__name__}", code=Reason.TRUTHINESS)

    def _node_truthy(self, node: SNode) -> Expr:
        """Node exists and is not the literal false."""
        if "*" in node.prefix:
            raise CompileUnsupported("node truthy under object iteration", code=Reason.OBJECT_ITERATION)
        deep = self._pattern(node.prefix + ("**",))
        axes = _axes_of(node.prefix)
        exact = self._pattern(node.prefix)
        false_id = ELit(self.false_id)
        sel_deep = ESelPattern(deep)
        sel_exact = ESelPattern(exact)
        is_false_leaf = e_and(
            sel_exact, e_cmp("==", ETokCol("vid"), false_id)
        )
        good = e_and(sel_deep, e_not(is_false_leaf))
        if not axes:
            return EReduce(good, "any")
        if axes in (("g0",), ("g01",)):
            return EGroup(good, None, axes[0], how="any")
        raise CompileUnsupported("node truthy axes", code=Reason.AXIS_SHAPE)

    # -- comprehensions ------------------------------------------------------

    def _eval_comprehension(self, term: A.Comprehension, st: State):
        """Set/array comprehension.

        The body evaluates in the OUTER state (bindings like `container`
        stay live); axes already open outside remain the set's grouping
        axes, axes/token-selections opened inside become the set's element
        dimension.
        """
        if term.kind == "object":
            raise CompileUnsupported("object comprehension", code=Reason.COMPREHENSION)
        sub = State(env=dict(st.env), space=st.space, guards=dict(st.guards), axis_owner=dict(st.axis_owner))
        with self._inv_barrier():
            finals = self._eval_body(term.body, sub)
        if not finals:
            if term.kind == "set":
                return [(SConst(set()), st)]
            return [(SConst([]), st)]
        # concrete-iteration comprehension (possibly with symbolic heads
        # and per-fork guards, e.g. allowedrepos' [good | repo =
        # params.repos[_]; good = startswith(container.image, repo)])
        if all(
            f.space == st.space and f.guards == st.guards for f in finals
        ):
            vals: List[Tuple[Optional[Expr], SVal]] = []
            for f in finals:
                guard: Optional[Expr] = None
                extra = [c for c in f.cond if c not in st.cond]
                for c in extra:
                    guard = c if guard is None else e_and(guard, c)
                for hv, hs in self._eval_term(term.head, f):
                    vals.append((guard, hv))
            if all(g is None and isinstance(v, SConst) for g, v in vals):
                elems = [v.value for _, v in vals]
                if term.kind == "set":
                    return [(SConst(set(_hashable(e) for e in elems)), st)]
                return [(SConst(elems), st)]
            if all(isinstance(v, (SConst, SBool)) for _, v in vals):
                return [(SList(vals), st)]
        outer_axes = tuple(a for a in st.space if a in ("g0", "g1"))
        pieces: List[Tuple[Expr, Expr]] = []  # (mask, elem_ids)
        for f in finals:
            hf = self._eval_term(term.head, f)
            for hv, hs in hf:
                if isinstance(hv, SNode):
                    hv = self._node_leaf(hv)
                if isinstance(hv, SConst) and hv.value is _ARRAY_INDEX:
                    # array-iteration indices as elements: numeric indices
                    # never collide with interned string/value ids, so this
                    # branch's contribution to set algebra is empty
                    continue
                if hs.proj and not st.proj:
                    # projected conds are per-token stand-ins; a set
                    # comprehension would materialize per-token
                    # duplicates (count() over it would inflate)
                    raise CompileUnsupported("element projection in comprehension", code=Reason.COMPREHENSION)
                inner_conds = list(hs.cond)
                if isinstance(hv, SKey):
                    mask: Expr = ESelPattern(hv.pattern_idx)
                    elem: Expr = hv.ids()
                elif isinstance(hv, SScalar) and hv.tok_space:
                    mask = hv.sel()
                    elem = ETokCol("vid")
                elif (
                    isinstance(hv, SScalar)
                    and hv.num_override is None
                    and hv.pattern_idx >= 0
                ):
                    # valid shapes: elements one or two array levels below
                    # the outer binding — idx0-grouping covers both since
                    # the first array level IS the outer axis
                    ok = (
                        not outer_axes
                        or (
                            outer_axes == ("g0",)
                            and hv.axes in (("g0",), ("g01",))
                        )
                    )
                    if not ok:
                        raise CompileUnsupported("comprehension axis mismatch", code=Reason.COMPREHENSION)
                    mask = hv.sel()
                    elem = ETokCol("vid")
                else:
                    raise CompileUnsupported("comprehension head shape", code=Reason.COMPREHENSION)
                for c in inner_conds:
                    if c.space not in ((), ("tok",)):
                        raise CompileUnsupported("comprehension cond space", code=Reason.COMPREHENSION)
                    mask = e_and(mask, c)
                pieces.append((mask, elem))
        if not pieces:
            return [(SConst(set() if term.kind == "set" else []), st)]
        if len(pieces) == 1:
            return [(STokenSet(pieces[0][0], pieces[0][1], outer_axes), st)]
        # union of branches: token selections over the same [N, L] space
        # are disjoint per token, so elem ids can be merged positionally
        mask = pieces[0][0]
        for m, _ in pieces[1:]:
            mask = e_or(mask, m)
        elem = pieces[0][1]
        for m, e in pieces[1:]:
            elem = e_where(m, e, elem)
        return [(STokenSet(mask, elem, outer_axes), st)]

    # -- builtins ------------------------------------------------------------

    def _builtin_count(self, args: List[SVal], st: State):
        (v,) = args
        if isinstance(v, SConst):
            try:
                return [(SConst(len(v.value)), st)]
            except TypeError:
                return []
        if isinstance(v, STokenSet):
            return [(SDerived(num=v.reduce_count(), defined=ELit(True)), st)]
        if isinstance(v, SDerived):
            return [(v, st)]  # const-diff counts are already numbers
        if isinstance(v, SList):
            if all(g is None for g, _ in v.items):
                return [(SConst(len(v.items)), st)]
            terms = []
            for g, _ in v.items:
                if g is None:
                    terms.append(EFullN(1))
                else:
                    terms.append(
                        EMap(lambda np_, c: c.astype(np.int32), [g], "toint")
                    )
            cnt = terms[0]
            for t in terms[1:]:
                cnt = e_arith("+", cnt, t)
            return [(SDerived(num=cnt, defined=ELit(True)), st)]
        if isinstance(v, SNode):
            # count of an abstract node: number of ARRAY elements (distinct
            # indices present). Exact for arrays — the library's only
            # count-of-document usage (tls lists etc.); object/string counts
            # are not compiled.
            if "*" in v.prefix:
                raise CompileUnsupported("count under object iteration", code=Reason.OBJECT_ITERATION)
            child = v.prefix + ("#", "**")
            axes = _axes_of(child)
            pat = self._pattern(child)
            present = EGroupPresent(ESelPattern(pat), axes[-1])
            if len(axes) > 1:
                raise CompileUnsupported("count of nested array", code=Reason.AGGREGATE_ARG)
            cnt = EReduce(
                EMap(
                    lambda np_, p: p.astype(np.int32), [present], "toint"
                ),
                "sum",
            )
            # defined only when the node IS an array (has elements or is
            # the empty-array token) or... count of undefined is undefined;
            # count of {} / "" is 0. Approximation: defined iff node exists.
            deep = self._pattern(v.prefix + ("**",))
            exists = EReduce(ESelPattern(deep), "any")
            return [(SDerived(num=cnt, defined=exists), st)]
        raise CompileUnsupported("count arg", code=Reason.AGGREGATE_ARG)

    def _builtin_any(self, args: List[SVal], st: State):
        (v,) = args
        if isinstance(v, SConst):
            try:
                return [(SConst(any(x is True for x in v.value)), st)]
            except TypeError:
                return []
        if isinstance(v, SList):
            exprs = []
            for guard, item in v.items:
                if isinstance(item, SConst):
                    if item.value is True:
                        if guard is None:
                            return [(SConst(True), st)]
                        exprs.append(guard)
                elif isinstance(item, SBool):
                    e = item.expr if guard is None else e_and(guard, item.expr)
                    exprs.append(e)
            if not exprs:
                return [(SConst(False), st)]
            out = exprs[0]
            for e in exprs[1:]:
                out = e_or(out, e)
            return [(SBool(out), st)]
        if isinstance(v, STokenSet):
            # any over a token-set of booleans: true iff the set contains
            # the literal true
            true_slot = self.pool.id_scalar(self.vocab.val_id(True))
            self.signature.append(("id",))
            return [
                (
                    SBool(
                        v.reduce_any(e_cmp("==", v.elem_ids, true_slot))
                    ),
                    st,
                )
            ]
        raise CompileUnsupported("any arg", code=Reason.AGGREGATE_ARG)

    def _builtin_all(self, args: List[SVal], st: State):
        (v,) = args
        if isinstance(v, SConst):
            try:
                return [(SConst(all(x is True for x in v.value)), st)]
            except TypeError:
                return []
        if isinstance(v, SList):
            exprs = []
            for guard, item in v.items:
                if isinstance(item, SConst):
                    if item.value is not True:
                        if guard is None:
                            return [(SConst(False), st)]
                        exprs.append(e_not(guard))
                elif isinstance(item, SBool):
                    e = item.expr if guard is None else e_or(e_not(guard), item.expr)
                    exprs.append(e)
            if not exprs:
                return [(SConst(True), st)]
            out = exprs[0]
            for e in exprs[1:]:
                out = e_and(out, e)
            return [(SBool(out), st)]
        raise CompileUnsupported("all arg", code=Reason.AGGREGATE_ARG)

    def _builtin_re_match(self, args, st: State):
        pat, target = args
        if not isinstance(pat, SConst) or not isinstance(pat.value, str):
            raise CompileUnsupported("symbolic regex pattern", code=Reason.BUILTIN_ARG_SHAPE)
        if isinstance(target, SConst):
            import re as _re

            if not isinstance(target.value, str):
                return []
            try:
                return [
                    (
                        SConst(
                            _re.search(pat.value, target.value) is not None
                        ),
                        st,
                    )
                ]
            except _re.error:
                return []
        tname = self.tables.regex(pat.value)
        self.signature.append(("table", tname))
        ids, defined = self._string_ids(target)
        return [(SBool(e_and(defined, EStrTable(tname, ids))), st)]

    def _builtin_startswith(self, args, st):
        return self._strpred(args, st, self.tables.prefix, lambda s, p: s.startswith(p))

    def _builtin_endswith(self, args, st):
        return self._strpred(args, st, self.tables.suffix, lambda s, p: s.endswith(p))

    def _builtin_contains(self, args, st):
        return self._strpred(args, st, self.tables.contains, lambda s, p: p in s)

    def _strpred(self, args, st, mk, concrete):
        target, pat = args
        if not isinstance(pat, SConst) or not isinstance(pat.value, str):
            raise CompileUnsupported("symbolic string-pred arg", code=Reason.BUILTIN_ARG_SHAPE)
        if isinstance(target, SConst):
            if not isinstance(target.value, str):
                return []
            return [(SConst(concrete(target.value, pat.value)), st)]
        tname = mk(pat.value)
        self.signature.append(("table", tname))
        ids, defined = self._string_ids(target)
        return [(SBool(e_and(defined, EStrTable(tname, ids))), st)]

    def _str_transform(self, v, st, name, fn):
        v = self._leafify(v)
        if isinstance(v, SConst):
            if not isinstance(v.value, str):
                return []
            return [(SConst(fn(v.value)), st)]
        ids, defined = self._string_ids(v)
        tname = self.tables.str_transform(name, fn)
        self.signature.append(("table", tname))
        out_ids = EStrTable(tname, ids, default=-1)
        space = ids.space
        return [
            (
                SScalar(
                    self,
                    pattern_idx=-1,
                    axes=space if space != ("tok",) else (),
                    tok_space=space == ("tok",),
                    vid_override=out_ids,
                    exists_override=defined,
                ),
                st,
            )
        ]

    def _builtin_lower(self, args, st):
        return self._str_transform(args[0], st, "lower", lambda x: x.lower())

    def _builtin_upper(self, args, st):
        return self._str_transform(args[0], st, "upper", lambda x: x.upper())

    def _builtin_trim(self, args, st):
        target, cutset = args
        if not isinstance(cutset, SConst) or not isinstance(cutset.value, str):
            raise CompileUnsupported("symbolic trim cutset", code=Reason.BUILTIN_ARG_SHAPE)
        c = cutset.value
        return self._str_transform(
            target, st, f"trim:{c}", lambda x, _c=c: x.strip(_c)
        )

    def _builtin_trim_prefix(self, args, st):
        target, pre = args
        if not isinstance(pre, SConst) or not isinstance(pre.value, str):
            raise CompileUnsupported("symbolic trim_prefix arg", code=Reason.BUILTIN_ARG_SHAPE)
        c = pre.value
        return self._str_transform(
            target,
            st,
            f"trimpre:{c}",
            lambda x, _c=c: x[len(_c):] if x.startswith(_c) else x,
        )

    def _builtin_sprintf(self, args, st):
        fmt, arglist = args
        if isinstance(fmt, SConst) and isinstance(arglist, (SConst, SList)):
            items = (
                [v for _, v in arglist.items]
                if isinstance(arglist, SList)
                else [SConst(v) for v in arglist.value]
                if isinstance(arglist.value, list)
                else None
            )
            if items is not None:
                sig = (
                    "sprintf",
                    fmt.value,
                    tuple(_val_sig(v) for v in items),
                )
                # value-position form: a single symbolic string argument
                # with one %v verb compiles to an id transform so the
                # result can join/compare (apparmor's annotation-key
                # construction); the msg_sig keeps head-dedup semantics
                arg0 = None
                if len(items) == 1:
                    try:
                        arg0 = self._leafify(items[0])
                    except CompileUnsupported:
                        arg0 = None
                if (
                    arg0 is not None
                    and isinstance(fmt.value, str)
                    and fmt.value.count("%") == 1
                    and "%v" in fmt.value
                    and isinstance(arg0, (SScalar, SKey))
                    and not (
                        isinstance(arg0, SScalar)
                        and arg0.num_override is not None
                    )
                ):
                    # lazily materializable (see SMsg.recipe)
                    return [
                        (
                            SMsg(
                                sig=sig,
                                recipe=(fmt.value, arg0),
                                parts=("sprintf", fmt.value, tuple(items)),
                            ),
                            st,
                        )
                    ]
                return [
                    (
                        SMsg(
                            sig=sig,
                            parts=("sprintf", fmt.value, tuple(items)),
                        ),
                        st,
                    )
                ]
        return [(SMsg(), st)]

    def _builtin_concat(self, args, st):
        if all(isinstance(a, SConst) for a in args):
            sep, items = args
            try:
                return [(SConst(sep.value.join(items.value)), st)]
            except Exception:
                return []
        return [(SMsg(), st)]

    def _builtin_is_number(self, args, st):
        (v,) = args
        v = self._leafify(v)
        if isinstance(v, SConst):
            return [
                (
                    SConst(
                        isinstance(v.value, (int, float))
                        and not isinstance(v.value, bool)
                    ),
                    st,
                )
            ]
        if isinstance(v, SDerived):
            return [(SBool(v.defined), st)]
        if isinstance(v, SScalar):
            if v.num_override is not None:
                return [(SBool(v.exists()), st)]
            return [
                (
                    SBool(
                        e_and(
                            v.exists(), e_cmp("==", v.kindv(), ELit(K_NUM))
                        )
                    ),
                    st,
                )
            ]
        raise CompileUnsupported("is_number arg", code=Reason.BUILTIN_ARG_SHAPE)

    def _builtin_is_string(self, args, st):
        (v,) = args
        v = self._leafify(v)
        if isinstance(v, SConst):
            return [(SConst(isinstance(v.value, str)), st)]
        if isinstance(v, SDerived):
            return [(SBool(ELit(False)), st)]
        if isinstance(v, SScalar):
            if v.num_override is not None:
                return [(SBool(ELit(False)), st)]
            return [
                (
                    SBool(
                        e_and(
                            v.exists(), e_cmp("==", v.kindv(), ELit(K_STR))
                        )
                    ),
                    st,
                )
            ]
        raise CompileUnsupported("is_string arg", code=Reason.BUILTIN_ARG_SHAPE)

    def _builtin_is_array(self, args, st):
        (v,) = args
        if isinstance(v, SConst):
            return [(SConst(isinstance(v.value, list)), st)]
        if isinstance(v, SNode):
            # an array node has element tokens or the empty-array token
            if "*" in v.prefix:
                raise CompileUnsupported("is_array under object iteration", code=Reason.OBJECT_ITERATION)
            elem_pat = self._pattern(v.prefix + ("#", "**"))
            exact = self._pattern(v.prefix)
            axes = _axes_of(v.prefix)
            from ..flatten.encoder import K_EMPTY_ARR

            empty_arr = e_and(
                ESelPattern(exact),
                e_cmp("==", ETokCol("kind"), ELit(K_EMPTY_ARR)),
            )
            arrish = e_or(ESelPattern(elem_pat), empty_arr)
            if not axes:
                return [(SBool(EReduce(arrish, "any")), st)]
            if axes in (("g0",), ("g01",)):
                return [
                    (SBool(EGroup(arrish, None, axes[0], how="any")), st)
                ]
            raise CompileUnsupported("is_array axes", code=Reason.AXIS_SHAPE)
        if isinstance(v, (SScalar, SKey, SDerived)):
            return [(SConst(False), st)] if not isinstance(v, SScalar) else [
                (SBool(ELit(False)), st)
            ]
        raise CompileUnsupported("is_array arg", code=Reason.BUILTIN_ARG_SHAPE)

    def _builtin_to_number(self, args, st):
        (v,) = args
        if isinstance(v, SDerived):
            # to_number of a number is the number itself
            return [(v, st)]
        v = self._leafify(v)
        if isinstance(v, SConst):
            try:
                if isinstance(v.value, bool):
                    return []
                return [(SConst(float(v.value)), st)]
            except (TypeError, ValueError):
                return []
        if isinstance(v, SScalar) and v.num_override is None:
            tname = self.tables.register("to_number", _to_number_host)
            self.signature.append(("table", tname))
            ids = v.vid()
            parsed = EStrTable(tname, ids, default=0.0)
            parsed_def = EStrTable(tname + "!def", ids, default=False)
            kind_num = e_cmp("==", v.kindv(), ELit(K_NUM))
            val = e_where(kind_num, v.num(), parsed)
            kind_str = e_cmp("==", v.kindv(), ELit(K_STR))
            dfn = e_and(
                v.exists(),
                e_or(kind_num, e_and(kind_str, parsed_def)),
            )
            return [(SDerived(num=val, defined=dfn), st)]
        raise CompileUnsupported("to_number arg", code=Reason.BUILTIN_ARG_SHAPE)

    def _leafify(self, v: SVal) -> SVal:
        """Materialize an abstract node as a leaf read where a scalar is
        consumed (builtin args, comparisons)."""
        if isinstance(v, SNode):
            return self._node_leaf(v)
        if isinstance(v, SElemProj):
            return self._elem_proj_scalar(v)
        return v

    def _string_ids(self, v: SVal) -> Tuple[Expr, Expr]:
        v = self._leafify(v)
        if isinstance(v, SScalar):
            if v.num_override is not None:
                raise CompileUnsupported("derived used as string", code=Reason.DERIVED_VALUE)
            return v.vid(), e_and(
                v.exists(), e_cmp("==", v.kindv(), ELit(K_STR))
            )
        if isinstance(v, SKey):
            return v.ids(), e_cmp("!=", v.ids(), ELit(-1))
        raise CompileUnsupported("string operand", code=Reason.BUILTIN_ARG_SHAPE)


def _freeze_sig(sig):
    """Signatures must be hashable dict keys."""
    try:
        hash(sig)
        return sig
    except TypeError:
        return ("unhashable", id(sig))


def _val_sig(v):
    """Render-signature of a symbolic value (see SMsg.sig)."""
    if isinstance(v, SConst):
        return ("c", _hashable(v.value))
    if isinstance(v, SMsg):
        return v.signature()
    if isinstance(v, SScalar):
        if v.msg_sig is not None:
            return v.msg_sig
        if v.pattern_idx >= 0 and v.num_override is None:
            return ("p", v.pattern_idx, v.tok_space)
        return ("deriv", id(v))
    if isinstance(v, SNode):
        return ("n", v.prefix)
    if isinstance(v, SKey):
        return ("k", v.pattern_idx)
    if isinstance(v, SList):
        return ("l", tuple(_val_sig(x) for _, x in v.items))
    return ("opaque", id(v))


class _ArrayIndexSentinel:
    """Binding value of an array-iteration index variable."""

    def __repr__(self):
        return "<array-index>"


_ARRAY_INDEX = _ArrayIndexSentinel()


def _hashable(v):
    if isinstance(v, (list, dict, set)):
        return json.dumps(v, sort_keys=True, default=str)
    return v


def _is_scalar_const(v) -> bool:
    return v is None or isinstance(v, (str, int, float, bool))


def _norm_num(v):
    if isinstance(v, float) and not isinstance(v, bool) and v.is_integer():
        return int(v)
    return v


def _to_number_host(v):
    """Rego to_number semantics per vocab entry: strings parse, numbers
    pass, booleans map to 1/0, null to 0."""
    if v is None:
        return 0.0, True
    if isinstance(v, bool):
        return (1.0 if v else 0.0), True
    if isinstance(v, (int, float)):
        return float(v), True
    try:
        return float(v), True
    except (TypeError, ValueError):
        return 0.0, False


def _jsonable(v) -> bool:
    try:
        json.dumps(v, sort_keys=True)
        return True
    except (TypeError, ValueError):
        return False


def _numeric_oracle(oracle, name: str, value, extra=None):
    """Adapter: oracle result must be numeric to live in a float table.
    `extra` = (sym_idx, consts): multi-arg call with the per-vocab value
    substituted at sym_idx."""
    try:
        if extra is not None:
            res, defined = oracle(name, value, extra=extra)
        else:
            res, defined = oracle(name, value)
    except Exception:
        return 0.0, False
    if not defined:
        return 0.0, False
    if isinstance(res, bool):
        return (1.0 if res else 0.0), True
    if isinstance(res, (int, float)):
        return float(res), True
    return 0.0, False
