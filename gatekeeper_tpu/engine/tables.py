"""Vocab-derived string tables: per-distinct-string predicate/transform caches.

The device never touches strings. Any string computation a template needs —
regex checks, prefix/suffix tests, quantity canonicalization, arbitrary
pure string->scalar helper functions (e.g. k8scontainerlimits'
canonify_cpu) — is evaluated once per distinct vocab entry on the host and
shipped as a [vocab_size] table the kernel gathers with the token's value
id. Resource batches share vocab entries heavily, so this amortizes the
string work the reference's interpreter redoes per object per query.

Tables are registered by name with a callback `fn(raw_string) ->
(value, defined)`; sync() extends all tables as the vocab grows.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..flatten.vocab import Vocab


@dataclass
class _Table:
    fn: Callable[[str], Tuple[Any, bool]]
    dtype: Any
    values: np.ndarray
    defined: np.ndarray


class StrTables:
    def __init__(self, vocab: Vocab):
        self.vocab = vocab
        self._tables: Dict[str, _Table] = {}
        self.generation = 0

    def register(
        self,
        name: str,
        fn: Callable[[Any], Tuple[Any, bool]],
        dtype=np.float32,
    ) -> str:
        """Idempotent by name. fn receives the decoded scalar VALUE of each
        vocab entry — a str for "s:" entries, the parsed JSON scalar
        (number/bool/null) for "j:" entries; path entries are skipped."""
        if name not in self._tables:
            self._tables[name] = _Table(
                fn=fn,
                dtype=dtype,
                values=np.zeros((0,), dtype),
                defined=np.zeros((0,), bool),
            )
            self._fill(self._tables[name])
            self.generation += 1
        return name

    def _fill(self, t: _Table) -> None:
        n = len(self.vocab)
        start = t.values.shape[0]
        if start >= n:
            return
        vals = np.zeros((n,), t.dtype)
        defined = np.zeros((n,), bool)
        vals[:start] = t.values
        defined[:start] = t.defined
        for i in range(start, n):
            val = _decode_entry(self.vocab.string(i))
            if val is _SKIP:
                continue
            try:
                v, d = t.fn(val)
            except Exception:
                v, d = 0, False
            if d:
                vals[i] = v
                defined[i] = True
        t.values = vals
        t.defined = defined

    def sync(self) -> None:
        """Extend tables to cover the vocab; loops to a fixed point since
        id-valued transforms (lower/trim) intern NEW strings during fill."""
        changed = False
        while True:
            n = len(self.vocab)
            done = all(
                t.values.shape[0] >= n for t in self._tables.values()
            )
            if done and len(self.vocab) == n:
                break
            for t in self._tables.values():
                self._fill(t)
            changed = True
            if len(self.vocab) == n:
                break
        if changed:
            self.generation += 1

    def arrays(self) -> Dict[str, np.ndarray]:
        """name -> values table, name+"!def" -> defined table."""
        out: Dict[str, np.ndarray] = {}
        for name, t in self._tables.items():
            out[name] = t.values
            out[name + "!def"] = t.defined
        return out

    # -- common predicate helpers ------------------------------------------
    # string builtins on non-string values are builtin errors in Rego
    # (-> undefined), so non-str entries stay defined=False

    def regex(self, pattern: str) -> str:
        import re as _re

        try:
            rx = _re.compile(pattern)
        except _re.error:
            rx = None

        def fn(s):
            if rx is None or not isinstance(s, str):
                return False, False
            return rx.search(s) is not None, True

        return self.register(f"re:{pattern}", fn, dtype=bool)

    def prefix(self, p: str) -> str:
        return self.register(
            f"pre:{p}",
            lambda s: (s.startswith(p), True) if isinstance(s, str) else (False, False),
            dtype=bool,
        )

    def suffix(self, p: str) -> str:
        return self.register(
            f"suf:{p}",
            lambda s: (s.endswith(p), True) if isinstance(s, str) else (False, False),
            dtype=bool,
        )

    def contains(self, p: str) -> str:
        return self.register(
            f"has:{p}",
            lambda s: (p in s, True) if isinstance(s, str) else (False, False),
            dtype=bool,
        )


    def str_transform(self, name: str, fn: Callable[[str], str]) -> str:
        """id -> id table: interned result of a pure string transform."""
        vocab = self.vocab

        def table_fn(s):
            if not isinstance(s, str):
                return -1, False
            return vocab.str_id(fn(s)), True

        return self.register(f"xf:{name}", table_fn, dtype=np.int32)


_SKIP = object()


def _decode_entry(s: str):
    if s.startswith("s:"):
        return s[2:]
    if s.startswith("j:"):
        import json

        try:
            return json.loads(s[2:])
        except ValueError:
            return _SKIP
    return _SKIP
