"""Vocab-derived string tables: per-distinct-string predicate/transform caches.

The device never touches strings. Any string computation a template needs —
regex checks, prefix/suffix tests, quantity canonicalization, arbitrary
pure string->scalar helper functions (e.g. k8scontainerlimits'
canonify_cpu) — is evaluated once per distinct vocab entry on the host and
shipped as a [vocab_size] table the kernel gathers with the token's value
id. Resource batches share vocab entries heavily, so this amortizes the
string work the reference's interpreter redoes per object per query.

Tables are registered by name with a callback `fn(raw_string) ->
(value, defined)`; sync() extends all tables as the vocab grows.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..flatten.vocab import Vocab


@dataclass
class _Table:
    fn: Callable[[str], Tuple[Any, bool]]
    dtype: Any
    values: np.ndarray
    defined: np.ndarray
    # content-addressed persistence (oracle tables): entries computed by
    # the interpreter oracle memoize across processes — SURVEY §5's
    # "compiled rule tensors are a cache keyed on template hash"
    persist_key: Optional[str] = None
    persist_store: Optional[Dict[str, Tuple[Any, bool]]] = None
    persist_new: int = 0
    # transform tables (str_transform): True enables the composition-
    # depth cutoff in _fill — without it, mutually prefixing transforms
    # grow the vocab exponentially under sync()'s fixed point
    is_transform: bool = False
    # the raw str->str transform (transforms only): fill_overlay interns
    # outputs into the OVERLAY, which the table_fn closure (bound to the
    # base vocab) cannot do
    raw_xf: Optional[Callable[[str], str]] = None


class StrTables:
    def __init__(self, vocab: Vocab):
        self.vocab = vocab
        self._tables: Dict[str, _Table] = {}
        self.generation = 0
        # transform-composition depth per vocab id: organically interned
        # entries (corpus values, captures, params) have depth 0; a
        # transform output's depth is input+1. Transforms skip inputs at
        # depth >= XF_MAX_DEPTH, bounding the sync() fixed point while
        # still transforming every organic string AND one level of
        # cross-table composition (tabA[tabB[vid]] chains). Only strings
        # whose content coincides with a depth>=2 composed product can
        # see an undefined transform — documented corner.
        self._xf_depth: Dict[int, int] = {}
        self._fill_depth = 0  # depth of the entry currently being filled

    def register(
        self,
        name: str,
        fn: Callable[[Any], Tuple[Any, bool]],
        dtype=np.float32,
        persist_key: Optional[str] = None,
        is_transform: bool = False,
    ) -> str:
        """Idempotent by name. fn receives the decoded scalar VALUE of each
        vocab entry — a str for "s:" entries, the parsed JSON scalar
        (number/bool/null) for "j:" entries; path entries are skipped.

        `persist_key`: content hash enabling a cross-process disk
        memo of fn results (for expensive interpreter-oracle fns)."""
        if name not in self._tables:
            t = _Table(
                fn=fn,
                dtype=dtype,
                values=np.zeros((0,), dtype),
                defined=np.zeros((0,), bool),
                persist_key=persist_key,
                is_transform=is_transform,
            )
            if persist_key is not None:
                t.persist_store = _load_persist(persist_key)
            self._tables[name] = t
            self._fill(t)
            self.generation += 1
        return name

    def _fill(self, t: _Table) -> None:
        n = len(self.vocab)
        start = t.values.shape[0]
        if start >= n:
            return
        vals = np.zeros((n,), t.dtype)
        defined = np.zeros((n,), bool)
        vals[:start] = t.values
        defined[:start] = t.defined
        store = t.persist_store
        for i in range(start, n):
            if t.is_transform:
                d = self._xf_depth.get(i, 0)
                if d >= XF_MAX_DEPTH:
                    continue  # composition-depth cutoff (see __init__)
                self._fill_depth = d
            raw = self.vocab.string(i)
            val = _decode_entry(raw)
            if val is _SKIP:
                continue
            if store is not None:
                hit = store.get(raw)
                if hit is not None:
                    v, d = hit
                    if d:
                        vals[i] = v
                        defined[i] = True
                    continue
            try:
                v, d = t.fn(val)
            except Exception:
                v, d = 0, False
            if d:
                vals[i] = v
                defined[i] = True
            if store is not None:
                store[raw] = (v if d else 0, d)
                t.persist_new += 1
        t.values = vals
        t.defined = defined
        if t.persist_key is not None and t.persist_new >= 1024:
            _save_persist(t.persist_key, t.persist_store)
            t.persist_new = 0

    def sync(self) -> None:
        """Extend tables to cover the vocab; loops to a fixed point since
        id-valued transforms (lower/trim) intern NEW strings during fill."""
        changed = False
        while True:
            n = len(self.vocab)
            done = all(
                t.values.shape[0] >= n for t in self._tables.values()
            )
            if done and len(self.vocab) == n:
                break
            for t in self._tables.values():
                self._fill(t)
            changed = True
            if len(self.vocab) == n:
                break
        if changed:
            self.generation += 1
        # flush pending memo entries even when this sync had nothing to
        # extend (register()'s immediate fill may have produced them)
        for t in self._tables.values():
            if t.persist_key is not None and t.persist_new:
                _save_persist(t.persist_key, t.persist_store)
                t.persist_new = 0

    def arrays(self) -> Dict[str, np.ndarray]:
        """name -> values table, name+"!def" -> defined table."""
        out: Dict[str, np.ndarray] = {}
        for name, t in self._tables.items():
            out[name] = t.values
            out[name + "!def"] = t.defined
        return out

    def fill_overlay(
        self, overlay, start: int, end: int
    ) -> Dict[str, np.ndarray]:
        """Per-table rows for overlay entries [start, end): the ephemeral
        counterpart of _fill, never touching the base tables or vocab.
        Transform outputs intern into the OVERLAY (raw_xf); the caller
        loops while the overlay keeps growing. Depth bookkeeping mirrors
        _fill: overlay-born transform products get depth input+1 and are
        cut off at XF_MAX_DEPTH."""
        names = list(self._tables)
        cols: Dict[str, Tuple[list, list]] = {n: ([], []) for n in names}
        depth = getattr(overlay, "_ov_xf_depth", None)
        if depth is None:
            depth = overlay._ov_xf_depth = {}
        for i in range(start, end):
            raw = overlay.string(i)
            val = _decode_entry(raw)
            for n in names:
                t = self._tables[n]
                v, d = 0, False
                if val is not _SKIP:
                    if t.is_transform:
                        de = depth.get(i, 0)
                        if de < XF_MAX_DEPTH and isinstance(val, str):
                            try:
                                out_s = t.raw_xf(val)
                            except Exception:
                                out_s = None
                            if out_s is not None:
                                oid = overlay.str_id(out_s)
                                nd = de + 1
                                if nd < depth.get(oid, 99):
                                    depth[oid] = nd
                                v, d = oid, True
                    else:
                        try:
                            v, d = t.fn(val)
                        except Exception:
                            v, d = 0, False
                vals, defs = cols[n]
                vals.append(v if d else 0)
                defs.append(d)
        out: Dict[str, np.ndarray] = {}
        for n in names:
            t = self._tables[n]
            vals, defs = cols[n]
            out[n] = np.asarray(vals, t.dtype)
            out[n + "!def"] = np.asarray(defs, bool)
        return out

    # -- common predicate helpers ------------------------------------------
    # string builtins on non-string values are builtin errors in Rego
    # (-> undefined), so non-str entries stay defined=False

    def regex(self, pattern: str) -> str:
        import re as _re

        try:
            rx = _re.compile(pattern)
        except _re.error:
            rx = None

        def fn(s):
            if rx is None or not isinstance(s, str):
                return False, False
            return rx.search(s) is not None, True

        return self.register(f"re:{pattern}", fn, dtype=bool)

    def prefix(self, p: str) -> str:
        return self.register(
            f"pre:{p}",
            lambda s: (s.startswith(p), True) if isinstance(s, str) else (False, False),
            dtype=bool,
        )

    def suffix(self, p: str) -> str:
        return self.register(
            f"suf:{p}",
            lambda s: (s.endswith(p), True) if isinstance(s, str) else (False, False),
            dtype=bool,
        )

    def contains(self, p: str) -> str:
        return self.register(
            f"has:{p}",
            lambda s: (p in s, True) if isinstance(s, str) else (False, False),
            dtype=bool,
        )


    def str_transform(self, name: str, fn: Callable[[str], str]) -> str:
        """id -> id table: interned result of a pure string transform.
        Outputs carry a composition depth (input+1); _fill skips inputs
        past XF_MAX_DEPTH so the sync() fixed point converges even for
        mutually prefixing transforms."""
        vocab = self.vocab

        def table_fn(s):
            if not isinstance(s, str):
                return -1, False
            try:
                out = fn(s)
            except Exception:
                return -1, False
            oid = vocab.str_id(out)
            d = self._fill_depth + 1
            if d < self._xf_depth.get(oid, 99):
                self._xf_depth[oid] = d
            return oid, True

        key = self.register(
            f"xf:{name}", table_fn, dtype=np.int32, is_transform=True
        )
        if self._tables[key].raw_xf is None:
            self._tables[key].raw_xf = fn
        return key


_SKIP = object()

# transform-composition depth cutoff (see StrTables.__init__)
XF_MAX_DEPTH = 2


def _persist_dir() -> Optional[str]:
    import os

    if os.environ.get("GATEKEEPER_TPU_NO_COMPILE_CACHE") == "1":
        return None
    return os.environ.get(
        "GATEKEEPER_TPU_ORACLE_CACHE_DIR",
        os.path.expanduser("~/.cache/gatekeeper_tpu/oracle_tables"),
    )


def _persist_path(key: str) -> Optional[str]:
    import hashlib
    import os

    d = _persist_dir()
    if d is None:
        return None
    return os.path.join(d, hashlib.sha256(key.encode()).hexdigest() + ".npz")


def _load_persist(key: str) -> Dict[str, Tuple[Any, bool]]:
    path = _persist_path(key)
    if path is None:
        return {}
    try:
        with np.load(path, allow_pickle=False) as z:
            strings = z["strings"]
            values = z["values"]
            defined = z["defined"]
        return {
            str(s): (float(v), bool(d))
            for s, v, d in zip(strings, values, defined)
        }
    except Exception:
        return {}


def _save_persist(key: str, store: Dict[str, Tuple[Any, bool]]) -> None:
    path = _persist_path(key)
    if path is None or not store:
        return
    import os
    import tempfile

    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        strings = np.array(list(store.keys()))
        values = np.array([float(v) for v, _ in store.values()], np.float64)
        defined = np.array([d for _, d in store.values()], bool)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), suffix=".tmp")
        os.close(fd)
        np.savez_compressed(
            tmp, strings=strings, values=values, defined=defined
        )
        # savez appends .npz to names lacking it
        os.replace(tmp + ".npz", path)
        os.unlink(tmp)
    except Exception:
        pass  # persistence is an optimization; never fail the fill


def _decode_entry(s: str):
    if s.startswith("s:"):
        return s[2:]
    if s.startswith("j:"):
        import json

        try:
            return json.loads(s[2:])
        except ValueError:
            return _SKIP
    return _SKIP
