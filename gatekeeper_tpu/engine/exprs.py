"""Vectorized expression DAG for compiled template programs.

Compiled violation rules are DAGs of these nodes, evaluated by tracing
into jax.numpy under jit. Spaces (array shapes) are:

    ()        -> [N]          per-resource scalars
    ("tok",)  -> [N, L]       per-token (object-key iteration bindings)
    ("g0",)   -> [N, G0]      per-first-level-array-element (containers)
    ("g0","g1") -> [N, G0, G1]

Nodes are pure and hash-consed per evaluation via an id-keyed memo, so
shared subexpressions trace once. The same DAG also evaluates under numpy
(eager) for the host-side reference path used in tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple


class Expr:
    space: Tuple[str, ...] = ()

    def emit(self, ctx: "EvalCtx"):
        memo = ctx.memo
        key = id(self)
        if key not in memo:
            memo[key] = self._emit(ctx)
        return memo[key]

    def _emit(self, ctx):  # pragma: no cover - abstract
        raise NotImplementedError


@dataclass
class EvalCtx:
    """Evaluation context: token columns, tables, per-constraint consts.

    `slabs`/`slab_cols`: optional pre-gathered fused-table slabs. A TPU
    gather op costs ~10ms regardless of width, so the device path
    gathers ALL pattern/table columns in a handful of fused ops
    ([V, T] tables indexed by the token's spath/vid once, in the outer
    trace, shared across every program group and vmap lane) and each
    node slices its column out; without slabs, nodes fall back to
    individual gathers (the numpy path, and ids shapes the slabs don't
    cover)."""

    np: Any  # numpy-like module (jax.numpy under jit)
    tok: Dict[str, Any]  # spath/idx0/idx1/kind/vid/vnum, each [N, L]
    pat_member: Any  # [P, Vp] bool
    pat_capture: Any  # [P, Vp] int32
    str_tables: Dict[str, Any]  # name -> [Vs] array
    consts: Dict[str, Any]  # slot -> array (vmapped per constraint)
    g0: int = 8  # first-level array fanout
    g1: int = 8
    memo: Dict[int, Any] = field(default_factory=dict)
    # slab name -> [N, L, T] pre-gathered fused table (device path only)
    slabs: Optional[Dict[str, Any]] = None
    # slab name -> {identifier: column index}
    slab_cols: Optional[Dict[str, Dict[Any, int]]] = None
    # per-row feature arrays ([N] bool), e.g. inventory join-key
    # duplication bits; ERowFeature reads them, defaulting to True
    # (unrefined) when a caller supplies none
    row: Optional[Dict[str, Any]] = None
    # ephemeral vocab overlay (flatten.vocab.OverlayVocab): ids >= v_base
    # resolve against the batch's overlay blocks instead of the base
    # tables. ov_member/ov_capture are [B, P] (entry-major); ov_tabs maps
    # table name -> [B] rows (host/numpy path); ov_slabs/ov_cols carry
    # the per-kind [B, T] stacks for the device path.
    v_base: Optional[Any] = None
    ov_member: Optional[Any] = None
    ov_capture: Optional[Any] = None
    ov_tabs: Optional[Dict[str, Any]] = None
    ov_slabs: Optional[Dict[str, Any]] = None
    ov_cols: Optional[Dict[str, Tuple[str, int]]] = None

    @property
    def n(self) -> int:
        return self.tok["spath"].shape[0]

    @property
    def l(self) -> int:
        return self.tok["spath"].shape[1]


def _shape_for(ctx: EvalCtx, space: Tuple[str, ...]) -> Tuple[int, ...]:
    dims = [ctx.n]
    for ax in space:
        dims.append(
            {"tok": ctx.l, "g0": ctx.g0, "g1": ctx.g1, "g01": ctx.g0 * ctx.g1}[ax]
        )
    return tuple(dims)


# space dominance for broadcasting; ("tok","g0") is the rank-3 join space
_RANK = {
    (): 0,
    ("tok",): 1,
    ("g0",): 1,
    ("g01",): 2,
    ("tok", "g0"): 3,
    ("tok", "g01"): 3,
}


def _space_rank(s: Tuple[str, ...]) -> int:
    return _RANK.get(s, 0)


def join_spaces(a: Tuple[str, ...], b: Tuple[str, ...]) -> Tuple[str, ...]:
    """Smallest space both broadcast into, or None."""
    if a == b:
        return a
    if not a:
        return b
    if not b:
        return a
    pair = {a, b}
    if pair == {("g0",), ("g01",)}:
        return ("g01",)
    if pair == {("tok",), ("g0",)} or pair == {("tok", "g0"), ("g0",)} or (
        pair == {("tok", "g0"), ("tok",)}
    ):
        return ("tok", "g0")
    if pair == {("tok",), ("g01",)} or pair == {("tok", "g01"), ("g01",)} or (
        pair == {("tok", "g01"), ("tok",)}
    ):
        return ("tok", "g01")
    if pair == {("tok", "g0"), ("g01",)} or pair == {("tok", "g01"), ("g0",)}:
        return None
    return None


def _expand(ctx: EvalCtx, v, s: Tuple[str, ...], target: Tuple[str, ...]):
    if s == target:
        return v
    if s == ():
        for _ in target:
            v = v[..., None] if hasattr(v, "ndim") else v
        return v
    if s == ("g0",) and target == ("g01",):
        return ctx.np.repeat(v, ctx.g1, axis=-1)
    if s == ("tok",) and target in (("tok", "g0"), ("tok", "g01")):
        return v[:, :, None]
    if s == ("g0",) and target == ("tok", "g0"):
        return v[:, None, :]
    if s == ("g01",) and target == ("tok", "g01"):
        return v[:, None, :]
    if s == ("g0",) and target == ("tok", "g01"):
        return ctx.np.repeat(v, ctx.g1, axis=-1)[:, None, :]
    raise ValueError(f"cannot expand {s} -> {target}")


def broadcast(ctx: EvalCtx, vals: Sequence[Any], spaces: Sequence[Tuple[str, ...]]):
    """Align values of compatible spaces for elementwise ops."""
    target: Tuple[str, ...] = ()
    for s in spaces:
        j = join_spaces(target, s)
        if j is None:
            raise ValueError(f"incompatible spaces {spaces}")
        target = j
    out = [_expand(ctx, v, s, target) for v, s in zip(vals, spaces)]
    return out, target


# -- leaves -----------------------------------------------------------------


@dataclass(eq=False)
class ELit(Expr):
    value: Any
    space: Tuple[str, ...] = ()

    def _emit(self, ctx):
        return self.value


@dataclass(eq=False)
class EFullN(Expr):
    """[N] array filled with a constant (anchors scalar conds to the batch)."""

    value: Any
    space: Tuple[str, ...] = ()

    def _emit(self, ctx):
        if isinstance(self.value, bool):
            return ctx.np.full((ctx.n,), self.value)
        return ctx.np.full((ctx.n,), self.value)


@dataclass(eq=False)
class ERowFeature(Expr):
    """[N] bool feature supplied by the dispatch layer (e.g. the
    inventory join-key duplication screen). Missing feature -> True
    (the unrefined, coarser-but-sound screen)."""

    name: str
    space: Tuple[str, ...] = ()

    def _emit(self, ctx):
        if ctx.row is not None:
            feat = ctx.row.get(self.name)
            if feat is not None:
                return feat
        return ctx.np.full((ctx.n,), True)


@dataclass(eq=False)
class EConstSlot(Expr):
    """Per-constraint constant (scalar or padded array), fed at call time."""

    slot: str
    space: Tuple[str, ...] = ()

    def _emit(self, ctx):
        return ctx.consts[self.slot]


@dataclass(eq=False)
class ETokCol(Expr):
    col: str  # spath | idx0 | idx1 | kind | vid | vnum
    space: Tuple[str, ...] = (("tok",))

    def __post_init__(self):
        self.space = ("tok",)

    def _emit(self, ctx):
        return ctx.tok[self.col]


@dataclass(eq=False)
class ESelPattern(Expr):
    """[N, L] bool: token's schema path matches the pattern."""

    pattern_idx: int

    def __post_init__(self):
        self.space = ("tok",)

    def _emit(self, ctx):
        spath = ctx.tok["spath"]
        if ctx.slabs is not None and "pat_member" in ctx.slabs:
            # overlay resolution happened at slab pre-gather time
            col = ctx.slab_cols["pat_member"].get(self.pattern_idx)
            if col is not None:
                return (spath >= 0) & ctx.slabs["pat_member"][..., col]
        width = ctx.pat_member.shape[1]
        safe = ctx.np.clip(spath, 0, max(width - 1, 0))
        base = (
            (spath >= 0)
            & (spath < width)
            & ctx.pat_member[self.pattern_idx][safe]
        )
        if ctx.ov_member is None:
            return base
        loc = spath - ctx.v_base
        b = ctx.ov_member.shape[0]
        safe_loc = ctx.np.clip(loc, 0, max(b - 1, 0))
        ov = (loc >= 0) & (loc < b) & ctx.ov_member[safe_loc, self.pattern_idx]
        return ctx.np.where(loc >= 0, ov, base)


@dataclass(eq=False)
class ECapture(Expr):
    """[N, L] int32: captured segment id for the pattern (-1 if none)."""

    pattern_idx: int

    def __post_init__(self):
        self.space = ("tok",)

    def _emit(self, ctx):
        spath = ctx.tok["spath"]
        if ctx.slabs is not None and "pat_capture" in ctx.slabs:
            col = ctx.slab_cols["pat_capture"].get(self.pattern_idx)
            if col is not None:
                return ctx.np.where(
                    spath >= 0, ctx.slabs["pat_capture"][..., col], -1
                )
        width = ctx.pat_capture.shape[1]
        safe = ctx.np.clip(spath, 0, max(width - 1, 0))
        base = ctx.np.where(
            (spath >= 0) & (spath < width),
            ctx.pat_capture[self.pattern_idx][safe],
            -1,
        )
        if ctx.ov_capture is None:
            return base
        loc = spath - ctx.v_base
        b = ctx.ov_capture.shape[0]
        safe_loc = ctx.np.clip(loc, 0, max(b - 1, 0))
        ov = ctx.np.where(
            (loc >= 0) & (loc < b),
            ctx.ov_capture[safe_loc, self.pattern_idx],
            -1,
        )
        return ctx.np.where(loc >= 0, ov, base)


@dataclass(eq=False)
class EStrTable(Expr):
    """Gather a vocab-derived table at an id expression (−1 -> default)."""

    table: str
    ids: Expr
    default: Any = False

    def __post_init__(self):
        self.space = self.ids.space

    def _emit(self, ctx):
        # tok-space vid lookups ride the fused pre-gathered slabs
        if (
            ctx.slabs is not None
            and isinstance(self.ids, ETokCol)
            and self.ids.col == "vid"
        ):
            for slab in ("vid_f32", "vid_bool", "vid_i32"):
                if slab in ctx.slabs:
                    col = ctx.slab_cols[slab].get(self.table)
                    if col is not None:
                        ids = ctx.tok["vid"]
                        return ctx.np.where(
                            ids >= 0,
                            ctx.slabs[slab][..., col],
                            self.default,
                        )
        ids = self.ids.emit(ctx)
        tab = ctx.str_tables[self.table]
        rows = tab.shape[0]
        safe = ctx.np.clip(ids, 0, max(rows - 1, 0))
        base = ctx.np.where(
            (ids >= 0) & (ids < rows), tab[safe], self.default
        )
        if ctx.v_base is None:
            return base
        ov_row = None
        if ctx.ov_tabs is not None:
            ovt = ctx.ov_tabs.get(self.table)
            if ovt is not None:
                loc = ids - ctx.v_base
                b = ovt.shape[0]
                safe_loc = ctx.np.clip(loc, 0, max(b - 1, 0))
                ov_row = ctx.np.where(
                    (loc >= 0) & (loc < b), ovt[safe_loc], self.default
                )
        elif ctx.ov_slabs is not None and ctx.ov_cols is not None:
            kc = ctx.ov_cols.get(self.table)
            if kc is not None:
                kind, col = kc
                ov = ctx.ov_slabs[kind]
                loc = ids - ctx.v_base
                b = ov.shape[0]
                safe_loc = ctx.np.clip(loc, 0, max(b - 1, 0))
                ov_row = ctx.np.where(
                    (loc >= 0) & (loc < b),
                    ov[safe_loc, col],
                    self.default,
                )
        if ov_row is None:
            return base
        return ctx.np.where(ids - ctx.v_base >= 0, ov_row, base)


@dataclass(eq=False)
class EIsInConst(Expr):
    """ids ∈ const id set (slot holds padded [K] ids, -1 pad)."""

    ids: Expr
    slot: str

    def __post_init__(self):
        self.space = self.ids.space

    def _emit(self, ctx):
        ids = self.ids.emit(ctx)
        members = ctx.consts[self.slot]  # [K]
        hit = (members != -1) & (members == ids[..., None])
        return hit.any(axis=-1)


# -- combinators ------------------------------------------------------------


@dataclass(eq=False)
class EMap(Expr):
    """Elementwise op over broadcast-aligned children."""

    fn: Callable
    args: List[Expr]
    name: str = "map"

    def __post_init__(self):
        target: Tuple[str, ...] = ()
        for a in self.args:
            j = join_spaces(target, a.space)
            if j is None:
                raise ValueError(
                    f"incompatible spaces {[a.space for a in self.args]}"
                )
            target = j
        self.space = target

    def _emit(self, ctx):
        vals = [a.emit(ctx) for a in self.args]
        vals, _ = broadcast(ctx, vals, [a.space for a in self.args])
        return self.fn(ctx.np, *vals)


def e_and(*args: Expr) -> Expr:
    return EMap(lambda np, *vs: _fold(np, vs, lambda a, b: a & b), list(args), "and")


def e_or(*args: Expr) -> Expr:
    return EMap(lambda np, *vs: _fold(np, vs, lambda a, b: a | b), list(args), "or")


def e_not(a: Expr) -> Expr:
    return EMap(lambda np, v: ~v, [a], "not")


def _fold(np, vs, f):
    out = vs[0]
    for v in vs[1:]:
        out = f(out, v)
    return out


def e_cmp(op: str, a: Expr, b: Expr) -> Expr:
    fns = {
        "==": lambda np, x, y: x == y,
        "!=": lambda np, x, y: x != y,
        "<": lambda np, x, y: x < y,
        "<=": lambda np, x, y: x <= y,
        ">": lambda np, x, y: x > y,
        ">=": lambda np, x, y: x >= y,
    }
    return EMap(fns[op], [a, b], f"cmp{op}")


def e_arith(op: str, a: Expr, b: Expr) -> Expr:
    fns = {
        "+": lambda np, x, y: x + y,
        "-": lambda np, x, y: x - y,
        "*": lambda np, x, y: x * y,
        "/": lambda np, x, y: x / y,
        "%": lambda np, x, y: x % y,
    }
    return EMap(fns[op], [a, b], f"arith{op}")


def e_where(c: Expr, t: Expr, f: Expr) -> Expr:
    return EMap(lambda np, cc, tt, ff: np.where(cc, tt, ff), [c, t, f], "where")


# -- reductions / regrouping ------------------------------------------------


@dataclass(eq=False)
class EReduce(Expr):
    """Reduce the innermost axis of child's space: any | all | sum | max."""

    child: Expr
    how: str

    def __post_init__(self):
        if not self.child.space:
            raise ValueError("cannot reduce a scalar space")
        self.space = self.child.space[:-1]

    def _emit(self, ctx):
        v = self.child.emit(ctx)
        np = ctx.np
        if self.how == "any":
            return v.any(axis=-1)
        if self.how == "all":
            return v.all(axis=-1)
        if self.how == "sum":
            return v.sum(axis=-1)
        if self.how == "max":
            return v.max(axis=-1)
        raise ValueError(self.how)


@dataclass(eq=False)
class EReduceAxis(Expr):
    """Reduce a NAMED axis of the child's space (any | sum)."""

    child: Expr
    axis: str
    how: str = "any"

    def __post_init__(self):
        if self.axis not in self.child.space:
            raise ValueError(f"axis {self.axis} not in {self.child.space}")
        self.space = tuple(a for a in self.child.space if a != self.axis)

    def _emit(self, ctx):
        v = self.child.emit(ctx)
        dim = 1 + self.child.space.index(self.axis)
        if self.how == "any":
            return v.any(axis=dim)
        if self.how == "sum":
            return v.sum(axis=dim)
        raise ValueError(self.how)


@dataclass(eq=False)
class EGroup(Expr):
    """Regroup per-token values onto an array-index axis.

    For tokens where `mask` holds, place `value` at [n, idx] where idx is
    the token's idx0 (axis="g0") or idx1 (axis="g1"); `init` fills empty
    slots; `how` resolves collisions (max | any | sum).

    idx1 grouping composes under an idx0 binding: pass an extra equality on
    idx0 in the mask, and group by idx1 -> [N, G1].
    """

    mask: Expr  # [N, L] bool
    value: Optional[Expr]  # [N, L] or None (then value := mask)
    axis: str  # "g0" | "g1"
    how: str = "max"
    init: Any = -1

    def __post_init__(self):
        self.space = (self.axis,)

    def _emit(self, ctx):
        np = ctx.np
        if self.axis == "g01":
            g = ctx.g0 * ctx.g1
            i0 = ctx.tok["idx0"]
            i1 = ctx.tok["idx1"]
            idx = np.where((i0 >= 0) & (i1 >= 0), i0 * ctx.g1 + i1, -1)
        else:
            g = ctx.g0 if self.axis == "g0" else ctx.g1
            idx = ctx.tok["idx0" if self.axis == "g0" else "idx1"]
        mask = self.mask.emit(ctx)
        val = self.value.emit(ctx) if self.value is not None else mask
        live = mask & (idx >= 0) & (idx < g)
        # one-hot contraction instead of scatter: [N, L, G] fuses into a
        # masked reduce on TPU (scatters serialize badly there); L and G
        # are small so the broadcast intermediate is cheap
        onehot = _onehot(ctx, idx, live, g)  # [N, L, G] bool
        if self.how == "sum":
            contrib = np.where(onehot, val[:, :, None], 0)
            return contrib.sum(axis=1)
        if self.how == "any":
            contrib = onehot & (val[:, :, None] != 0)
            return contrib.any(axis=1)
        contrib = np.where(onehot, val[:, :, None], self.init)
        return contrib.max(axis=1)


def _onehot(ctx, idx, live, g):
    np = ctx.np
    slots = np.arange(g)
    return live[:, :, None] & (idx[:, :, None] == slots[None, None, :])


@dataclass(eq=False)
class EGatherElem(Expr):
    """[N, L]: for each token, the per-element value of ITS OWN array
    element — the inverse of EGroup. `elem` lives on ("g0",) (indexed by
    idx0) or ("g01",) (idx0*G1+idx1). Tokens outside the axis (idx -1)
    take `default`.

    This is what makes element-projected joins work: conditions on two
    DIFFERENT tokens of one element (a mount's name and its readOnly
    flag) become token-level expressions that agree across the element's
    tokens, so they AND correctly and reduce existentially."""

    elem: Expr
    default: Any = False

    def __post_init__(self):
        if self.elem.space not in (("g0",), ("g01",)):
            raise ValueError(f"gather from space {self.elem.space}")
        self.space = ("tok",)

    def _emit(self, ctx):
        np = ctx.np
        v = self.elem.emit(ctx)
        if self.elem.space == ("g0",):
            idx = ctx.tok["idx0"]
            g = ctx.g0
        else:
            i0 = ctx.tok["idx0"]
            i1 = ctx.tok["idx1"]
            idx = np.where((i0 >= 0) & (i1 >= 0), i0 * ctx.g1 + i1, -1)
            g = ctx.g0 * ctx.g1
        safe = np.clip(idx, 0, g - 1)
        vals = np.take_along_axis(v, safe, axis=1)
        return np.where((idx >= 0) & (idx < g), vals, self.default)


@dataclass(eq=False)
class EGroupPresent(Expr):
    """[N, G] bool: any selected token exists at that array index."""

    mask: Expr
    axis: str

    def __post_init__(self):
        self.space = (self.axis,)
        self._inner = EGroup(self.mask, None, self.axis, how="any")

    def _emit(self, ctx):
        return self._inner.emit(ctx)
