"""Compiled template programs: build, cache, evaluate.

A `Program` is the compiled form of one (template, constraint-params)
pair: an Expr DAG returning per-resource violation counts plus the
constraint's constant tensors. Programs with identical structural
signatures (same template control flow, same pattern set, same const
shapes) share one jitted callable — constraints differ only in the const
tensors they pass, so a template's whole constraint population typically
compiles the device program once.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..rego import ast as A
from .exprs import EvalCtx, Expr
from .patterns import PatternRegistry
from .symbolic import Compiler, CompilerEnv, CompileUnsupported
from .tables import StrTables


@dataclass
class Program:
    expr: Expr
    consts: Dict[str, np.ndarray]
    signature: Tuple
    g_max: int = 8  # array-axis fanout the program was evaluated with
    # screen programs over-approximate (inventory-join conditions are
    # dropped, symbolic.InventoryDependent); exact results come from the
    # interpreter re-check of flagged pairs
    screen: bool = False
    # per-row feature names this program consumes ("invdup:<pattern>"
    # join-key duplication bits the dispatch layer computes per corpus)
    row_features: Tuple[str, ...] = ()
    # compiled-render metadata (exact programs only, engine/render.py):
    # grouped violation branches (un-flagged cond + head render plan) and
    # row-level safety flags; flagged rows render via the interpreter
    branches: Optional[Tuple] = None
    flags: Tuple = ()
    # derived-key join prune plan ({fn, review_prefix, tree}): flagged
    # pairs render against the key index's candidate objects instead of
    # the whole inventory (uniqueserviceselector at 100k scale)
    prune: Optional[Dict[str, Any]] = None


def compile_program(
    env: CompilerEnv, modules: Sequence[A.Module], params: Any
) -> Program:
    try:
        comp = Compiler(env, modules, params)
        expr = comp.compile_violation_counts()
    except CompileUnsupported:
        try:
            # element projection may have aborted (a second-array join
            # whose conditions could not reduce existentially): retry
            # exact with projection off — conflicted iterations take the
            # flag-guarded object branch instead
            comp = Compiler(env, modules, params, elem_projection=False)
            expr = comp.compile_violation_counts()
        except CompileUnsupported:
            # retry as a screen: uncompilable calls/comprehensions become
            # opaque and conditions on them drop — a sound
            # over-approximation whose flagged pairs the driver re-checks
            # via the interpreter. This keeps inventory joins
            # (uniqueingresshost/-serviceselector) and intra-object joins
            # (seccomp/apparmor annotation matching) on the device path
            # for the dense non-matching bulk.
            comp = Compiler(env, modules, params, screen_mode=True)
            expr = comp.compile_violation_counts()
            comp.uses_inventory = True
            comp.opaque = True  # retried conditions over-approximate
    env.patterns.sync()
    env.tables.sync()
    sig = tuple(
        x if not isinstance(x, list) else tuple(x) for x in comp.signature
    )
    if comp.uses_inventory:
        sig = sig + (("inventory-screen",),)
    return Program(
        expr=expr,
        consts=comp.pool.values,
        signature=sig,
        screen=comp.uses_inventory,
        row_features=tuple(comp.row_features),
        # render branches stay valid when only safety FLAGS fired (the
        # render path routes flagged rows to the interpreter itself);
        # genuine opacity (dropped conditions) disables them entirely
        branches=tuple(comp.out_branches) if not comp.opaque else None,
        flags=tuple(comp.out_flags),
        prune=comp.prune_plan,
    )


class ProgramEvaluator:
    """Evaluates programs over token tables (numpy eagerly, or jax jitted
    with signature-level callable sharing)."""

    def __init__(self, patterns: PatternRegistry, tables: StrTables, use_jax: bool = True):
        self.patterns = patterns
        self.tables = tables
        self.use_jax = use_jax
        self._jit_cache: Dict[Tuple, Any] = {}
        self._device_tables: Optional[Tuple[int, Dict[str, Any]]] = None

    def _table_arrays(self):
        self.patterns.sync()
        self.tables.sync()
        gen = (self.patterns.generation, self.tables.generation)
        if self._device_tables is None or self._device_tables[0] != gen:
            arrs = {
                "pat_member": self.patterns.member,
                "pat_capture": self.patterns.capture,
                **self.tables.arrays(),
            }
            if self.use_jax:
                import jax.numpy as jnp

                arrs = {k: jnp.asarray(v) for k, v in arrs.items()}
            self._device_tables = (gen, arrs)
        return self._device_tables[1]

    def eval_np(
        self,
        program: Program,
        tok: Dict[str, np.ndarray],
        g: int = 8,
        overlay: Optional[Dict[str, Any]] = None,
        row: Optional[Dict[str, np.ndarray]] = None,
    ):
        """`overlay` (ephemeral batches): {"v_base", "member", "capture",
        "tabs"} vocab-overlay blocks for ids >= v_base. `row`: per-row
        feature planes ({name -> [N] bool}) consumed by ERowFeature —
        the numpy mirror of the jax path's stage_row_feats (absent
        names default True: coarse, sound)."""
        arrs = self._table_arrays()
        host = {
            k: (np.asarray(v) if not isinstance(v, np.ndarray) else v)
            for k, v in arrs.items()
        }
        ov = overlay or {}
        g0, g1 = (g if isinstance(g, tuple) else (g, g))
        ctx = EvalCtx(
            np=np,
            tok=tok,
            pat_member=host["pat_member"],
            pat_capture=host["pat_capture"],
            str_tables={
                k: v
                for k, v in host.items()
                if k not in ("pat_member", "pat_capture")
            },
            consts=program.consts,
            g0=g0,
            g1=g1,
            row=row,
            v_base=ov.get("v_base"),
            ov_member=ov.get("member"),
            ov_capture=ov.get("capture"),
            ov_tabs=ov.get("tabs"),
        )
        return np.asarray(program.expr.emit(ctx))

    def eval_jax(
        self,
        programs: Sequence[Program],
        tok: Dict[str, Any],
        g: int = 8,
    ) -> np.ndarray:
        """Evaluate a batch of programs -> [n_programs, N] counts.

        ALL programs trace into ONE jitted function (one device dispatch
        per sweep — per-program dispatch over a remote TPU link dominates
        otherwise); the fused callable is cached on the ordered signature
        tuple, so a fixed template population re-uses it across sweeps
        with only const tensors changing."""
        import jax
        import jax.numpy as jnp

        if not programs:
            n = tok["spath"].shape[0]
            return np.zeros((0, n), np.int32)
        arrs = self._table_arrays()
        tok_dev = {k: jnp.asarray(v) for k, v in tok.items()}
        key = (
            tuple(p.signature for p in programs),
            g,
            tok_dev["spath"].shape,
        )
        fn = self._jit_cache.get(key)
        if fn is None:
            exprs = [p.expr for p in programs]

            def run(tok_in, tabs, const_list):
                str_tabs = {
                    k: v
                    for k, v in tabs.items()
                    if k not in ("pat_member", "pat_capture")
                }
                outs = []
                for expr, consts in zip(exprs, const_list):
                    g0_, g1_ = (g if isinstance(g, tuple) else (g, g))
                    ctx = EvalCtx(
                        np=jnp,
                        tok=tok_in,
                        pat_member=tabs["pat_member"],
                        pat_capture=tabs["pat_capture"],
                        str_tables=str_tabs,
                        consts=consts,
                        g0=g0_,
                        g1=g1_,
                    )
                    outs.append(expr.emit(ctx).astype(jnp.int32))
                return jnp.stack(outs, axis=0)

            fn = jax.jit(run)
            self._jit_cache[key] = fn
        const_list = [
            {k: jnp.asarray(v) for k, v in p.consts.items()} for p in programs
        ]
        return np.asarray(fn(tok_dev, arrs, const_list))
