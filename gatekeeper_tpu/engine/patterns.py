"""Schema-path pattern registry: host-side path classification tables.

Token tables store one interned schema-path id per leaf
(gatekeeper_tpu/flatten/encoder.py). Compiled template programs select
tokens by *pattern* — a segment sequence where "#" matches an array level,
"*" matches exactly one segment (capturing it), and "**" matches any
(possibly empty) suffix. Membership and captures are resolved once per
distinct path string on the host and shipped to the device as lookup
tables indexed by path id:

    member[pattern_id, path_id]  -> bool
    capture[pattern_id, path_id] -> captured segment's "s:<seg>" vocab id

The device then classifies a token with two gathers — the TPU analog of
OPA's per-eval ref walking (vendor/.../opa/topdown/eval.go evalTree).
Tables grow append-only alongside the vocab; a generation counter lets
device caches invalidate cheaply.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..flatten.vocab import Vocab
from ..flatten.encoder import esc_seg, unesc_seg


@dataclass(frozen=True)
class Pattern:
    segs: Tuple[str, ...]  # literal | "#" | "*" | "?" | "**" (final only)

    @property
    def key(self) -> Tuple[str, ...]:
        return self.segs


def _match(pattern: Tuple[str, ...], segs: List[str]) -> Tuple[bool, Optional[str]]:
    """Returns (matches, captured segment for the first "*").

    "*" matches exactly one OBJECT-KEY segment (never the "#" array
    marker — object and array iteration branches must stay disjoint);
    "#" matches exactly the array marker; "?" matches exactly one
    segment of ANY kind (array marker or key, no capture — used by
    inventory-join mirror patterns where the partner's structure is
    unknown); "**" (final position) matches any remaining suffix
    including the empty one.
    """
    cap: Optional[str] = None
    pi = 0
    for si, seg in enumerate(segs):
        if pi >= len(pattern):
            return False, None
        p = pattern[pi]
        if p == "**":
            return True, cap
        if p == "*":
            if seg == "#":
                return False, None
            if cap is None:
                cap = seg
        elif p == "?":
            pass
        elif p == "#":
            if seg != "#":
                return False, None
        elif p != seg:
            return False, None
        pi += 1
    if pi == len(pattern):
        return True, cap
    if pi == len(pattern) - 1 and pattern[pi] == "**":
        return True, cap
    return False, None


class PatternRegistry:
    """Registered patterns + lazily grown [P, V] membership/capture tables."""

    def __init__(self, vocab: Vocab):
        self.vocab = vocab
        self._patterns: List[Pattern] = []
        self._index: Dict[Tuple[str, ...], int] = {}
        self._member = np.zeros((0, 0), bool)
        self._capture = np.full((0, 0), -1, np.int32)
        self._scanned = 0  # vocab entries processed
        self.generation = 0

    def register(self, segs: Sequence[str]) -> int:
        key = tuple(segs)
        idx = self._index.get(key)
        if idx is not None:
            return idx
        idx = len(self._patterns)
        self._patterns.append(Pattern(key))
        self._index[key] = idx
        # grow rows and back-fill for already-scanned vocab entries
        v = self._member.shape[1]
        self._member = np.concatenate(
            [self._member, np.zeros((1, v), bool)], axis=0
        )
        self._capture = np.concatenate(
            [self._capture, np.full((1, v), -1, np.int32)], axis=0
        )
        for pid in range(min(self._scanned, v)):
            self._classify(idx, pid)
        self.generation += 1
        return idx

    def _classify(self, pat_idx: int, vocab_id: int) -> None:
        s = self.vocab.string(vocab_id)
        if not s.startswith("p:"):
            return
        segs = s[2:].split(".") if len(s) > 2 else []
        ok, cap = _match(self._patterns[pat_idx].segs, segs)
        if ok:
            self._member[pat_idx, vocab_id] = True
            if cap is not None:
                # captures are unescaped back to the raw object key so they
                # compare equal to interned parameter strings
                self._capture[pat_idx, vocab_id] = self.vocab.str_id(
                    unesc_seg(cap)
                )

    def sync(self) -> None:
        """Classify vocab entries added since the last sync. Note: str_id
        interning inside _classify may itself grow the vocab; loop until
        fixed point."""
        while True:
            n = len(self.vocab)
            if n == self._scanned and self._member.shape[1] >= n:
                return
            if self._member.shape[1] < n:
                pad = n - self._member.shape[1]
                self._member = np.concatenate(
                    [self._member, np.zeros((len(self._patterns), pad), bool)],
                    axis=1,
                )
                self._capture = np.concatenate(
                    [
                        self._capture,
                        np.full((len(self._patterns), pad), -1, np.int32),
                    ],
                    axis=1,
                )
            start = self._scanned
            self._scanned = n
            for vid in range(start, n):
                for pi in range(len(self._patterns)):
                    self._classify(pi, vid)
            self.generation += 1

    def classify_overlay(
        self, overlay, start: int, end: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        """-> (member [B, P] bool, capture [B, P] int32) rows for
        overlay entries [start, end) — the ephemeral counterpart of
        sync(), touching neither the base tables nor the base vocab.
        Captured segments intern into the OVERLAY; the caller loops
        while that grows it. Non-path entries (values) skip fast, so the
        per-batch cost is #new-paths x P, typically tiny."""
        P = len(self._patterns)
        B = end - start
        member = np.zeros((B, P), bool)
        capture = np.full((B, P), -1, np.int32)
        for j in range(B):
            s = overlay.string(start + j)
            if not s.startswith("p:"):
                continue
            segs = s[2:].split(".") if len(s) > 2 else []
            for pi, pat in enumerate(self._patterns):
                ok, cap = _match(pat.segs, segs)
                if ok:
                    member[j, pi] = True
                    if cap is not None:
                        capture[j, pi] = overlay.str_id(unesc_seg(cap))
        return member, capture

    @property
    def member(self) -> np.ndarray:
        return self._member

    @property
    def capture(self) -> np.ndarray:
        return self._capture

    @property
    def n_patterns(self) -> int:
        return len(self._patterns)

    def segs(self, idx: int) -> Tuple[str, ...]:
        return self._patterns[idx].segs
