"""Constraint spec.match → dense match tensors.

Compiles each constraint's match block (kinds/namespaces/excludedNamespaces/
scope/labelSelector/namespaceSelector — schema in pkg/target/target.go:
246-318) into padded int32 tensors consumed by the jitted match kernel.
Every encoding decision mirrors a clause of the reference matching library
(target_template_source.go) via the native oracle in constraint/match.py;
the differential test battery in tests/test_match_kernel.py enforces
bit-equality between the two.

Sentinel codes:
  -1  padding (row ignored)
  -2  wildcard "*" (kind selector group/kind)
  -3  invalid selector row (present but malformed -> never matches)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..constraint import match as M
from ..flatten.vocab import Vocab

WILDCARD = -2
INVALID = -3

# scope codes
SCOPE_ABSENT, SCOPE_STAR, SCOPE_NAMESPACED, SCOPE_CLUSTER, SCOPE_INVALID = (
    0,
    1,
    2,
    3,
    4,
)

# matchExpression op codes (OP_ALWAYS_VIOLATED retained for kernel compat)
OP_IGNORE, OP_IN, OP_NOT_IN, OP_EXISTS, OP_NOT_EXISTS, OP_ALWAYS_VIOLATED = (
    0,
    1,
    2,
    3,
    4,
    5,
)


def _is_scalar(v):
    return v is None or isinstance(v, (str, int, float, bool))
_OP_CODES = {
    "In": OP_IN,
    "NotIn": OP_NOT_IN,
    "Exists": OP_EXISTS,
    "DoesNotExist": OP_NOT_EXISTS,
}


def _bucket(n: int, lo: int = 1) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


@dataclass
class _Selector:
    invalid: bool
    ml_pairs: List[Tuple[int, int]]
    exprs: List[Tuple[int, int, int, List[int]]]  # (key, op, n_values, ids)


def _compile_selector(sel: Any, vocab: Vocab) -> _Selector:
    """LabelSelector -> pairs/expressions (target_template_source.go:213-230)."""
    ml = M.get_default(sel, "matchLabels", {})
    pairs: List[Tuple[int, int]] = []
    invalid = False
    if isinstance(ml, dict):
        for k, v in ml.items():
            pairs.append((vocab.str_id(str(k)), vocab.val_id(v)))
    elif ml not in ([], ""):
        invalid = True  # non-object matchLabels never match
    exprs: List[Tuple[int, int, int, List[int]]] = []
    me = M.get_default(sel, "matchExpressions", [])
    if isinstance(me, list):
        for e in me:
            if not isinstance(e, dict) or "operator" not in e or "key" not in e:
                continue
            op = e["operator"]
            code = _OP_CODES.get(op, OP_IGNORE)
            if code == OP_IGNORE:
                continue
            values = M.get_default(e, "values", [])
            key_id = vocab.str_id(str(e["key"]))
            # mirror the oracle's values normalization exactly
            # (match.py values_shape): n_values encodes `count(values)>0`,
            # ids are the reachable members
            count_pos, elems = M.values_shape(values)
            ids = [
                vocab.val_id(v) for v in elems if _is_scalar(v)
            ]
            nv = 1 if count_pos else 0
            exprs.append((key_id, code, nv, ids))
    return _Selector(invalid=invalid, ml_pairs=pairs, exprs=exprs)


@dataclass
class MatchSpecSet:
    """Stacked match tensors for C constraints (numpy; jnp-ready)."""

    # kind selectors, cross-product expanded: [C, K, 2]
    kind_rows: np.ndarray
    # namespaces / excludedNamespaces
    ns_has: np.ndarray  # [C] bool
    ns_ids: np.ndarray  # [C, M]
    excl_has: np.ndarray  # [C] bool
    excl_ids: np.ndarray  # [C, M2]
    scope: np.ndarray  # [C] int32
    # labelSelector
    lab_invalid: np.ndarray  # [C] bool
    lab_ml: np.ndarray  # [C, P, 2]
    lab_expr: np.ndarray  # [C, E, 3] (key, op, n_values)
    lab_expr_vals: np.ndarray  # [C, E, V]
    # namespaceSelector
    nssel_has: np.ndarray  # [C] bool
    nssel_matches_empty: np.ndarray  # [C] selector matches empty label set
    nssel_invalid: np.ndarray
    nssel_ml: np.ndarray
    nssel_expr: np.ndarray
    nssel_expr_vals: np.ndarray

    @property
    def n(self) -> int:
        return int(self.kind_rows.shape[0])


def _expand_kind_rows(match: Any) -> Optional[List[Tuple[int, int]]]:
    """Returns rows of (group, kind) raw strings / sentinels, or None for the
    default wildcard selector."""
    kinds = M.get_default(match, "kinds", None)
    if kinds is None:
        return None
    if not isinstance(kinds, list):
        return [(INVALID, INVALID)]
    rows: List[Tuple[Any, Any]] = []
    for ks in kinds:
        if not isinstance(ks, dict):
            continue
        groups = ks.get("apiGroups")
        kk = ks.get("kinds")
        if not isinstance(groups, list) or not isinstance(kk, list):
            rows.append((INVALID, INVALID))
            continue
        if not groups or not kk:
            rows.append((INVALID, INVALID))
            continue
        for g in groups:
            for k in kk:
                rows.append((g, k))
    if not rows:
        rows.append((INVALID, INVALID))
    return rows


def compile_match_specs(
    constraints: Sequence[Dict[str, Any]], vocab: Vocab
) -> MatchSpecSet:
    """Raw constraints -> tensors (the K8s identity translation)."""
    return compile_match_irs(
        [M.constraint_match(c) for c in constraints], vocab
    )


def compile_match_irs(
    matches: Sequence[Any], vocab: Vocab
) -> MatchSpecSet:
    """Pre-extracted match blocks -> tensors. Target handlers translate
    their public match schema into this module's field vocabulary first
    (docs/targets.md); the K8s handler's translation is the identity."""
    per: List[Dict[str, Any]] = []
    for match in matches:
        raw_rows = _expand_kind_rows(match)
        if raw_rows is None:
            rows = [(WILDCARD, WILDCARD)]
        else:
            rows = []
            for g, k in raw_rows:
                if g is INVALID:
                    rows.append((INVALID, INVALID))
                    continue
                gc = WILDCARD if g == "*" else (
                    vocab.str_id(g) if isinstance(g, str) else INVALID
                )
                kc = WILDCARD if k == "*" else (
                    vocab.str_id(k) if isinstance(k, str) else INVALID
                )
                rows.append((gc, kc))

        ns_has = M._has_field(match, "namespaces")
        nss = match.get("namespaces") if ns_has else None
        ns_ids = (
            [vocab.str_id(n) for n in nss if isinstance(n, str)]
            if isinstance(nss, list)
            else []
        )
        excl_has = M._has_field(match, "excludedNamespaces")
        excl = match.get("excludedNamespaces") if excl_has else None
        excl_ids = (
            [vocab.str_id(n) for n in excl if isinstance(n, str)]
            if isinstance(excl, list)
            else []
        )

        if not M._has_field(match, "scope"):
            scope = SCOPE_ABSENT
        else:
            scope = {
                "*": SCOPE_STAR,
                "Namespaced": SCOPE_NAMESPACED,
                "Cluster": SCOPE_CLUSTER,
            }.get(match.get("scope"), SCOPE_INVALID)

        lab = _compile_selector(M.get_default(match, "labelSelector", {}), vocab)
        nssel_has = M._has_field(match, "namespaceSelector")
        nssel_raw = M.get_default(match, "namespaceSelector", {})
        nssel = _compile_selector(nssel_raw, vocab)
        nssel_empty_ok = M.matches_label_selector(nssel_raw, {})

        per.append(
            dict(
                rows=rows,
                ns_has=ns_has,
                ns_ids=ns_ids,
                excl_has=excl_has,
                excl_ids=excl_ids,
                scope=scope,
                lab=lab,
                nssel_has=nssel_has,
                nssel=nssel,
                nssel_empty_ok=nssel_empty_ok,
            )
        )

    C = len(per)
    K = _bucket(max((len(p["rows"]) for p in per), default=1))
    NM = _bucket(max((len(p["ns_ids"]) for p in per), default=1))
    NE = _bucket(max((len(p["excl_ids"]) for p in per), default=1))

    def sel_dims(key):
        P = _bucket(max((len(p[key].ml_pairs) for p in per), default=1))
        E = _bucket(max((len(p[key].exprs) for p in per), default=1))
        V = _bucket(
            max(
                (len(e[3]) for p in per for e in p[key].exprs),
                default=1,
            )
        )
        return P, E, V

    LP, LE, LV = sel_dims("lab")
    SP, SE, SV = sel_dims("nssel")

    kind_rows = np.full((C, K, 2), -1, np.int32)
    ns_has = np.zeros((C,), bool)
    ns_ids = np.full((C, NM), -1, np.int32)
    excl_has = np.zeros((C,), bool)
    excl_ids = np.full((C, NE), -1, np.int32)
    scope = np.zeros((C,), np.int32)

    def pack_sel(P, E, V):
        return (
            np.zeros((C,), bool),
            np.full((C, P, 2), -1, np.int32),
            np.full((C, E, 3), -1, np.int32),
            np.full((C, E, V), -1, np.int32),
        )

    lab_invalid, lab_ml, lab_expr, lab_expr_vals = pack_sel(LP, LE, LV)
    nssel_invalid, nssel_ml, nssel_expr, nssel_expr_vals = pack_sel(SP, SE, SV)
    nssel_has_arr = np.zeros((C,), bool)
    nssel_matches_empty = np.zeros((C,), bool)

    def fill_sel(i, sel: _Selector, invalid, ml, expr, expr_vals):
        invalid[i] = sel.invalid
        for p, (k, v) in enumerate(sel.ml_pairs):
            ml[i, p, 0] = k
            ml[i, p, 1] = v
        for e, (k, op, nv, ids) in enumerate(sel.exprs):
            expr[i, e, 0] = k
            expr[i, e, 1] = op
            expr[i, e, 2] = nv
            for v, vid in enumerate(ids):
                expr_vals[i, e, v] = vid

    for i, p in enumerate(per):
        for r, (g, k) in enumerate(p["rows"]):
            kind_rows[i, r, 0] = g
            kind_rows[i, r, 1] = k
        ns_has[i] = p["ns_has"]
        for j, n in enumerate(p["ns_ids"]):
            ns_ids[i, j] = n
        excl_has[i] = p["excl_has"]
        for j, n in enumerate(p["excl_ids"]):
            excl_ids[i, j] = n
        scope[i] = p["scope"]
        fill_sel(i, p["lab"], lab_invalid, lab_ml, lab_expr, lab_expr_vals)
        nssel_has_arr[i] = p["nssel_has"]
        nssel_matches_empty[i] = p["nssel_empty_ok"]
        fill_sel(
            i, p["nssel"], nssel_invalid, nssel_ml, nssel_expr, nssel_expr_vals
        )

    return MatchSpecSet(
        kind_rows=kind_rows,
        ns_has=ns_has,
        ns_ids=ns_ids,
        excl_has=excl_has,
        excl_ids=excl_ids,
        scope=scope,
        lab_invalid=lab_invalid,
        lab_ml=lab_ml,
        lab_expr=lab_expr,
        lab_expr_vals=lab_expr_vals,
        nssel_has=nssel_has_arr,
        nssel_matches_empty=nssel_matches_empty,
        nssel_invalid=nssel_invalid,
        nssel_ml=nssel_ml,
        nssel_expr=nssel_expr,
        nssel_expr_vals=nssel_expr_vals,
    )
