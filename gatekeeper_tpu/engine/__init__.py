"""TPU evaluation engine: vectorized kernels over flattened policy state.

The compute core of the framework. Where the reference evaluates one
interpreted Rego query per review (drivers/local/local.go:302 wrapping the
OPA topdown interpreter), this package compiles constraint match specs and
template violation rules into dense JAX programs evaluated for the whole
[n_constraints, n_resources] cross-product in a single jitted call:

  * matchspec/matchkernel — constraint `spec.match` → int tensors → the
    batched match matrix (the vectorization of
    pkg/target/target_template_source.go's matching_constraints).
  * compile/predkernel (template rules) — the Rego-subset compiler from
    violation rules to token-table predicate programs.
"""

from .matchspec import MatchSpecSet, compile_match_specs  # noqa: F401
from .matchkernel import match_matrix  # noqa: F401
