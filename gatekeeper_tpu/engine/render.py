"""Compiled violation-message rendering (the SURVEY §7 step-3 design).

The reference renders violation messages inside the engine (OPA topdown
sprintf; response shape vendor/.../constraint/pkg/client/regolib/
src.go:7-45). Until round 4 this build re-ran the Python interpreter per
violating (constraint, resource) pair (~1ms each), which saturated the
violation-heavy webhook. This module closes that gap:

  * at template-compile time each EXACT (non-screen) program keeps, per
    violation branch, its un-flagged condition Expr and a `RenderPlan`
    tree over the head value (format string + captured slots);
  * at render time the driver evaluates the branch conditions and slot
    expressions with numpy over ONLY the violating rows' token slices —
    the same compiled DAG the device ran, so the true (branch, element)
    set is exact — and formats messages by decoding captured vocab ids
    through the interpreter's own `_sprintf`/`opa_repr`, giving
    bit-exact message parity without interpreting any Rego.

Fallback safety: anything a plan cannot prove it renders exactly
(unsupported head shapes, flagged rows, decode anomalies) routes the
pair to the interpreter exactly as before. Semantically-undefined heads
(e.g. sprintf arity errors, missing paths) SKIP the element, matching
Rego's undefined-head semantics.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..flatten.encoder import (
    K_BOOL,
    K_EMPTY_ARR,
    K_EMPTY_OBJ,
    K_NULL,
    K_NUM,
    K_STR,
    unesc_seg,
)
from ..rego.builtins import BuiltinError, _sprintf
from ..rego.values import EMPTY_OBJ, freeze, sort_key
from .exprs import EvalCtx, Expr, _expand


class _Undef:
    """Head value semantically undefined at this element: the element
    contributes no violation (Rego undefined-head semantics)."""


class _CantRender(Exception):
    """The plan cannot guarantee an exact render: route the whole pair
    to the interpreter."""


UNDEF = _Undef()


def _decode_val(vocab, vid: int):
    """Typed value id -> frozen python value (exact: the id interns the
    canonical JSON of the scalar, so no float32 round-trip)."""
    import json

    s = vocab.string(int(vid))
    if s.startswith("s:"):
        return s[2:]
    if s.startswith("j:"):
        return freeze(json.loads(s[2:]))
    raise _CantRender(f"undecodable vocab entry {s[:16]!r}")


# ---------------------------------------------------------------------------
# plan nodes


class RVal:
    def value(self, ev: "_BranchEval", r: int, elem: Tuple[int, ...]):
        raise NotImplementedError


@dataclass
class RConst(RVal):
    v: Any  # pre-frozen

    def value(self, ev, r, elem):
        return self.v


@dataclass
class RScalar(RVal):
    """Token-table leaf: decode the captured vid by kind."""

    vid: Expr
    kind: Expr
    exists: Expr
    space: Tuple[str, ...]

    def value(self, ev, r, elem):
        if not ev.arr(self.exists, self.space)[(r, *elem)]:
            return UNDEF
        k = int(ev.arr(self.kind, self.space)[(r, *elem)])
        vid = int(ev.arr(self.vid, self.space)[(r, *elem)])
        if k == K_EMPTY_OBJ:
            return EMPTY_OBJ
        if k == K_EMPTY_ARR:
            return ()
        if k in (K_STR, K_NUM, K_BOOL, K_NULL):
            if vid < 0:
                return UNDEF
            return _decode_val(ev.vocab, vid)
        raise _CantRender(f"unexpected token kind {k}")


@dataclass
class RKey(RVal):
    """Captured object-key of a token-space iteration (ECapture ids are
    str_ids of the unescaped key)."""

    ids: Expr
    space: Tuple[str, ...]

    def value(self, ev, r, elem):
        vid = int(ev.arr(self.ids, self.space)[(r, *elem)])
        if vid < 0:
            return UNDEF
        return _decode_val(ev.vocab, vid)


@dataclass
class RPath(RVal):
    """Navigate the raw review document ("#" segments consume the
    element's array indices) — object/array-valued head references
    (e.g. a container's securityContext in a message)."""

    segs: Tuple[str, ...]  # unescaped; "#" = array index hole
    n_holes: int

    def value(self, ev, r, elem):
        idxs = ev.g_indices(elem)
        if len(idxs) < self.n_holes:
            raise _CantRender("path holes exceed element indices")
        cur = ev.review
        hole = 0
        for seg in self.segs:
            if seg == "#":
                if not isinstance(cur, (list, tuple)):
                    return UNDEF
                i = idxs[hole]
                hole += 1
                if i >= len(cur):
                    return UNDEF
                cur = cur[i]
            else:
                if not isinstance(cur, dict) or seg not in cur:
                    return UNDEF
                cur = cur[seg]
        return freeze(cur)


@dataclass
class RTokSet(RVal):
    """Set comprehension over a token selection; `axes` non-empty means
    one set per first-level array element (idx0-filtered)."""

    mask: Expr
    elem_ids: Expr
    axes: Tuple[str, ...]

    def value(self, ev, r, elem):
        m = ev.arr_raw(self.mask)[r]
        ids = ev.arr_raw(self.elem_ids)[r]
        if self.axes == ("g0",):
            idxs = ev.g_indices(elem)
            if not idxs:
                raise _CantRender("per-element token set without g index")
            m = m & (ev.idx0[r] == idxs[0])
        elif self.axes != ():
            raise _CantRender(f"token-set axes {self.axes}")
        out = set()
        for t in np.nonzero(m)[0]:
            vid = int(ids[t])
            if vid < 0:
                raise _CantRender("masked token without value id")
            out.add(_decode_val(ev.vocab, vid))
        return frozenset(out)


@dataclass
class RSetDiff(RVal):
    """const_set - token_set (requiredlabels' `missing`)."""

    const: frozenset  # pre-frozen elements
    tokset: RTokSet

    def value(self, ev, r, elem):
        present = self.tokset.value(ev, r, elem)
        if present is UNDEF:
            return UNDEF
        return frozenset(x for x in self.const if x not in present)


@dataclass
class RSprintf(RVal):
    fmt: str
    args: Tuple[RVal, ...]

    def value(self, ev, r, elem):
        vals = []
        for a in self.args:
            v = a.value(ev, r, elem)
            if v is UNDEF:
                return UNDEF
            vals.append(v)
        try:
            return _sprintf(self.fmt, tuple(vals))
        except BuiltinError:
            return UNDEF  # interp: sprintf undefined -> head undefined


@dataclass
class RObj(RVal):
    items: Tuple[Tuple[Any, RVal], ...]  # (frozen key, value plan)

    def value(self, ev, r, elem):
        d = {}
        for k, vp in self.items:
            v = vp.value(ev, r, elem)
            if v is UNDEF:
                return UNDEF
            d[k] = v
        from ..rego.values import Obj

        return Obj(d)


# ---------------------------------------------------------------------------
# plan construction (compile time)


def build_plan(comp, hv) -> Optional[RVal]:
    """Symbolic head value -> render plan, or None if any part is not
    provably exactly renderable. `comp` is the symbolic.Compiler (for
    pattern segs). A failed plan must NEVER affect compilation — the
    SVal accessors this walks (vid/exists/kindv) can raise
    CompileUnsupported on shapes the count path never materializes, and
    leaking that would demote an exact program to a screen."""
    try:
        return _plan(comp, hv)
    except _CantRender:
        return None
    except Exception:
        return None


def _plan(comp, hv) -> RVal:
    # local imports: symbolic imports this module
    from .symbolic import (
        SConst,
        SDerived,
        SKey,
        SMsg,
        SNode,
        SScalar,
        STokenSet,
    )

    if isinstance(hv, SConst):
        try:
            return RConst(freeze(hv.value))
        except TypeError:
            raise _CantRender("unfreezable const")
    if isinstance(hv, SScalar):
        if hv.num_override is not None:
            raise _CantRender("derived-number slot")
        return RScalar(
            vid=hv.vid(), kind=hv.kindv(), exists=hv.exists(), space=hv.space
        )
    if isinstance(hv, SKey):
        ids = hv.ids()
        return RKey(ids=ids, space=ids.space)
    if isinstance(hv, SNode):
        segs = tuple(
            "#" if s == "#" else unesc_seg(s) for s in hv.prefix
        )
        return RPath(segs=segs, n_holes=sum(1 for s in segs if s == "#"))
    if isinstance(hv, STokenSet):
        return RTokSet(mask=hv.mask, elem_ids=hv.elem_ids, axes=hv.axes)
    if isinstance(hv, SDerived):
        r = getattr(hv, "render", None)
        if r is not None and r[0] == "constdiff":
            _, const_elems, tokset = r
            return RSetDiff(
                const=frozenset(freeze(x) for x in const_elems),
                tokset=_plan(comp, tokset),
            )
        raise _CantRender("derived value")
    if isinstance(hv, SMsg):
        parts = getattr(hv, "parts", None)
        if parts is None and hv.recipe is not None:
            parts = ("sprintf", hv.recipe[0], (hv.recipe[1],))
        if parts is None:
            raise _CantRender("opaque message")
        if parts[0] == "sprintf":
            _, fmt, args = parts
            return RSprintf(
                fmt=fmt, args=tuple(_plan(comp, a) for a in args)
            )
        if parts[0] == "obj":
            _, items = parts
            return RObj(
                items=tuple(
                    (freeze(k), _plan(comp, v)) for k, v in items
                )
            )
        raise _CantRender(f"message parts {parts[0]}")
    # SList loses its array-vs-set kind in symbolic form; SDerived
    # without render info, SBool, etc. — all route to the interpreter
    raise _CantRender(f"head value {type(hv).__name__}")


# ---------------------------------------------------------------------------
# branch metadata stored on compiled programs


@dataclass
class Branch:
    """One grouped violation branch of an exact program."""

    space: Tuple[str, ...]
    cond: Expr  # WITHOUT safety flags: true <=> violation at element
    plan: Optional[RVal]  # None => interpreter renders this branch


# ---------------------------------------------------------------------------
# render-time evaluation


class _BranchEval:
    """Per-(program, row-subset) expression evaluation context."""

    def __init__(self, ctx: EvalCtx, vocab, g1: int):
        self.ctx = ctx
        self.vocab = vocab
        self.g1 = g1
        self.review: Any = None
        self._cache: Dict[Tuple[int, Tuple[str, ...]], np.ndarray] = {}
        self._cond_space: Tuple[str, ...] = ()
        self.idx0 = np.asarray(ctx.tok["idx0"])

    def set_element_space(self, space: Tuple[str, ...]) -> None:
        self._cond_space = space

    def g_indices(self, elem: Tuple[int, ...]) -> Tuple[int, ...]:
        """Element multi-index -> first/second-level array indices."""
        out: List[int] = []
        for ax, e in zip(self._cond_space, elem):
            if ax == "g0":
                out.append(int(e))
            elif ax == "g01":
                out.append(int(e) // self.g1)
                out.append(int(e) % self.g1)
            # "tok" contributes no array index
        return tuple(out)

    def arr_raw(self, expr: Expr) -> np.ndarray:
        return np.asarray(expr.emit(self.ctx))

    def arr(self, expr: Expr, space: Tuple[str, ...]) -> np.ndarray:
        """Evaluate and expand to [n, *element dims] of the current
        element space (scalar/ELit results broadcast too)."""
        key = (id(expr), self._cond_space)
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        v = np.asarray(expr.emit(self.ctx))
        target = self._cond_space
        want = (self.ctx.n,) + tuple(_axlen(self.ctx, a) for a in target)
        try:
            if v.ndim == 0:
                v = np.broadcast_to(v, want)
            elif space == () and target:
                v = np.broadcast_to(
                    v.reshape(v.shape + (1,) * len(target)), want
                )
            else:
                if space != target:
                    v = _expand(self.ctx, v, space, target)
                v = np.broadcast_to(v, want)
        except ValueError:
            raise _CantRender(f"expand {space} -> {target}")
        self._cache[key] = v
        return v


def _axlen(ctx: EvalCtx, ax: str) -> int:
    return {
        "tok": ctx.l,
        "g0": ctx.g0,
        "g1": ctx.g1,
        "g01": ctx.g0 * ctx.g1,
    }[ax]


class RenderSet:
    """Renders violation objects for one exact program over a row
    subset. `render_row` returns the row's frozen violation objects in
    interpreter order, or None when the pair must fall back."""

    def __init__(
        self,
        program,
        ctx: EvalCtx,
        vocab,
    ):
        self.program = program
        self.ev = _BranchEval(ctx, vocab, ctx.g1)
        self._conds: List[np.ndarray] = []
        # flags: any true -> the row routes to the interpreter
        flagged = np.zeros((ctx.n,), bool)
        for f in program.flags or ():
            v = np.asarray(f.emit(ctx))
            while v.ndim > 1:
                v = v.any(axis=-1)
            flagged |= v
        self.flagged = flagged
        for br in program.branches or ():
            self._conds.append(np.asarray(br.cond.emit(ctx)))

    def render_row(self, r: int, review: Any) -> Optional[List[Any]]:
        if self.flagged[r]:
            return None
        self.ev.review = review
        objs: List[Any] = []
        seen = set()
        try:
            for br, cond in zip(self.program.branches, self._conds):
                row = cond[r]
                if row.ndim == 0:
                    elems = [()] if row else []
                else:
                    elems = [tuple(e) for e in np.argwhere(row)]
                if not elems:
                    continue
                if br.plan is None:
                    return None
                self.ev.set_element_space(br.space)
                for e in elems:
                    v = br.plan.value(self.ev, r, e)
                    if v is UNDEF:
                        continue
                    if v in seen:
                        continue
                    seen.add(v)
                    objs.append(v)
        except _CantRender:
            return None
        objs.sort(key=sort_key)
        return objs
